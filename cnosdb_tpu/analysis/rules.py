"""Project-invariant lint rules.

Every rule documents the incident or PR that motivated it (``motivation``)
— a rule that can't point at a real failure it prevents is noise. To add
one: subclass :class:`~cnosdb_tpu.analysis.Rule`, set ``name`` (kebab-case;
it is the suppression token and the baseline key), declare ``node_types``
for the shared walk and/or override ``begin_module`` for whole-module
passes, and append it to :func:`all_rules`. Run ``--fix-baseline`` once if
the tree has pre-existing debt the new rule should ratchet rather than
block on.
"""
from __future__ import annotations

import ast
import re

from . import Rule

# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------


def _call_name(node: ast.Call) -> str | None:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _recv_text(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        try:
            return ast.unparse(node.func.value)
        except Exception:
            return "?"
    return ""


def _walk_no_nested_funcs(root: ast.AST):
    """Walk a statement subtree without descending into nested function /
    lambda bodies (code merely *defined* there doesn't run under the
    enclosing lock/handler)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_time_time_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "time"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ("time", "_time"))


# --------------------------------------------------------------------------
# 1. no-bare-except — migrated from tests/test_no_bare_except.py (PR 1),
#    widened from parallel/+storage/ to the whole package
# --------------------------------------------------------------------------
class NoBareExcept(Rule):
    name = "no-bare-except"
    motivation = ("PR 1 chaos suite: a bare except in RPC/recovery paths "
                  "swallows KeyboardInterrupt/SystemExit, turning operator "
                  "Ctrl-C and injected crashes into silently-ignored events")
    node_types = (ast.ExceptHandler,)

    def visit(self, node, ctx):
        if node.type is None:
            ctx.report(self, node,
                       "bare 'except:' — catch Exception (or narrower) so "
                       "control-flow exceptions propagate")


# --------------------------------------------------------------------------
# 2. rpc-call-timeout — migrated from tests/test_no_bare_except.py (PR 4),
#    widened to the whole package
# --------------------------------------------------------------------------
class RpcCallTimeout(Rule):
    name = "rpc-call-timeout"
    motivation = ("PR 4 deadline plane: an rpc_call inheriting the 10 s "
                  "default ignores the caller's request deadline — one slow "
                  "peer absorbs the node for 10 s per split")
    node_types = (ast.Call,)

    def applies_to(self, relpath):
        # net.py defines rpc_call (wait_rpc_ready's probe is capped there)
        return relpath != "cnosdb_tpu/parallel/net.py"

    def visit(self, node, ctx):
        if _call_name(node) != "rpc_call":
            return
        has_kw = any(kw.arg == "timeout" or kw.arg is None  # **kwargs
                     for kw in node.keywords)
        if not has_kw and len(node.args) < 4:   # positional timeout = 4th
            ctx.report(self, node,
                       "rpc_call without explicit timeout= — every hop must "
                       "pick a budget (the request deadline then caps it)")


# --------------------------------------------------------------------------
# 3/4. row-loop — migrated from tests/test_no_row_loops.py (PR 5)
# --------------------------------------------------------------------------
_VECTORIZED_FUNCS = ("_merge_distinct_vec", "_apply_gapfill",
                     "_merge_results_vec")
_FALLBACK_FUNC = "_merge_distinct"
_ROW_ITER_NAMES = {"idxs", "idx", "rows", "row_idxs"}


def _row_loops(fn: ast.AST):
    """For-loops whose iterable is a row-index array: a bare name from the
    denylist, or a direct np.nonzero(...) subscript."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.For):
            continue
        it = node.iter
        if isinstance(it, ast.Name) and it.id in _ROW_ITER_NAMES:
            yield node.lineno
        elif isinstance(it, ast.Subscript) \
                and isinstance(it.value, ast.Call) \
                and isinstance(it.value.func, ast.Attribute) \
                and it.value.func.attr == "nonzero":
            yield node.lineno


class _RowLoopBase(Rule):
    def applies_to(self, relpath):
        return relpath == "cnosdb_tpu/sql/executor.py"

    def _funcs(self, ctx, names):
        found = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in names:
                found[node.name] = node
        return found


class RowLoop(_RowLoopBase):
    name = "row-loop"
    motivation = ("PR 5 aggregation plane: a per-row Python loop in a "
                  "vectorized section regresses silently — results stay "
                  "right, only 10-100x slower at ClickBench cardinalities")

    def begin_module(self, ctx):
        found = self._funcs(ctx, _VECTORIZED_FUNCS)
        for name in _VECTORIZED_FUNCS:
            fn = found.get(name)
            if fn is None:
                ctx.report(self, 1,
                           f"vectorized section {name} not found — if it "
                           f"was renamed, update analysis/rules.py so the "
                           f"lint keeps covering it")
                continue
            for line in _row_loops(fn):
                ctx.report(self, line,
                           f"per-row loop in vectorized section {name} — "
                           f"use factorized codes + bincount/reduceat/"
                           f"grouped_order (ops/group_agg.py) instead")


class RowLoopFallback(_RowLoopBase):
    name = "row-loop-fallback"
    motivation = ("PR 5: _merge_distinct keeps per-row folds ONLY for "
                  "payloads that defeat factorization; the baseline pins "
                  "the count so new code paths can't quietly join them")

    def begin_module(self, ctx):
        fn = self._funcs(ctx, (_FALLBACK_FUNC,)).get(_FALLBACK_FUNC)
        if fn is None:
            ctx.report(self, 1,
                       f"{_FALLBACK_FUNC} not found — update "
                       f"analysis/rules.py if it was renamed")
            return
        for line in _row_loops(fn):
            ctx.report(self, line,
                       "scalar row-loop fallback in _merge_distinct "
                       "(baselined; new aggregation work belongs in "
                       "_merge_distinct_vec)")


# --------------------------------------------------------------------------
# 5. lock-blocking — new: blocking calls written inside `with <lock>:`
# --------------------------------------------------------------------------
_LOCKISH = ("lock", "mutex", "cond", "_cv")
_BLOCKING_NAMES = {"rpc_call", "wait_rpc_ready", "urlopen", "recv",
                   "recv_into", "sendall", "accept", "getresponse",
                   "run_all"}
_SUBPROCESS_NAMES = {"run", "check_call", "check_output", "Popen", "call"}


def _lockish_name(expr: ast.AST) -> str | None:
    if isinstance(expr, ast.Name):
        n = expr.id
    elif isinstance(expr, ast.Attribute):
        n = expr.attr
    elif isinstance(expr, ast.Call):
        # with self._registry.lock_for(x): — look at the callee name
        return _lockish_name(expr.func)
    else:
        return None
    low = n.lower()
    return n if any(k in low for k in _LOCKISH) else None


class LockBlocking(Rule):
    name = "lock-blocking"
    motivation = ("PRs 1-4 each found a stall where one slow peer/disk op "
                  "serialized the node because a mutex was held across it; "
                  "ROADMAP #1/#2 add more threads and more locks")
    node_types = (ast.With,)

    def visit(self, node, ctx):
        locks = [n for n in (_lockish_name(it.context_expr)
                             for it in node.items) if n]
        if not locks:
            return
        ctx_texts = set()
        for it in node.items:
            try:
                ctx_texts.add(ast.unparse(it.context_expr))
            except Exception:
                pass
        seen_lines = set()
        for inner in _walk_no_nested_funcs(node):
            if not isinstance(inner, ast.Call) or inner.lineno in seen_lines:
                continue
            what = self._blocking(inner, ctx_texts)
            if what:
                seen_lines.add(inner.lineno)
                ctx.report(self, inner,
                           f"{what} while holding {'/'.join(locks)} — move "
                           f"the blocking call outside the lock (snapshot "
                           f"state, drop the lock, then block)")

    @staticmethod
    def _blocking(call: ast.Call, ctx_texts: set) -> str | None:
        name = _call_name(call)
        recv = _recv_text(call)
        if name in _BLOCKING_NAMES:
            return f"{name}()"
        if name == "sleep" and recv in ("", "time"):
            return "time.sleep()"
        if name == "open" and isinstance(call.func, ast.Name):
            return "file open()"
        if name == "result" and recv:
            return "future .result()"
        if name == "wait" and recv and recv not in ctx_texts:
            # cv.wait() on the with-target releases the lock; .wait() on
            # anything else (Event, Thread, process) blocks while holding it
            return f"{recv}.wait()"
        if name in _SUBPROCESS_NAMES and recv == "subprocess":
            return f"subprocess.{name}()"
        return None


# --------------------------------------------------------------------------
# 6. swallowed-exception — new: `except Exception: pass` in the planes
#    where silence has already masked corruption
# --------------------------------------------------------------------------
class SwallowedException(Rule):
    name = "swallowed-exception"
    motivation = ("PR 3 integrity plane: quarantine/repair bugs hid behind "
                  "silent except-pass until a counter was added; in "
                  "parallel/+storage/ every swallow needs a log or metric")
    node_types = (ast.ExceptHandler,)

    def applies_to(self, relpath):
        return relpath.startswith(("cnosdb_tpu/parallel/",
                                   "cnosdb_tpu/storage/"))

    def visit(self, node, ctx):
        if not (isinstance(node.type, ast.Name)
                and node.type.id == "Exception"):
            return
        if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
            ctx.report(self, node,
                       "'except Exception: pass' with no log/metric — count "
                       "it (utils/stages.count_error) or narrow the except; "
                       "silent swallows have masked real corruption before")


# --------------------------------------------------------------------------
# 7. jax-purity — new: Python control flow / host syncs on traced values
# --------------------------------------------------------------------------
_JAX_PURITY_FILES = ("cnosdb_tpu/ops/kernels.py",
                     "cnosdb_tpu/ops/group_agg.py",
                     "cnosdb_tpu/ops/pallas_kernels.py",
                     "cnosdb_tpu/ops/device_decode.py")
_ARRAY_MODULES = {"jnp", "lax", "pl"}


def _contains_jit(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id == "jit":
            return True
        if isinstance(n, ast.Attribute) and n.attr == "jit":
            return True
    return False


def _static_argnames(call: ast.Call) -> set:
    out: set = set()
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            out.add(v.value)
        elif isinstance(v, (ast.Tuple, ast.List)):
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    out.add(elt.value)
    return out


class JaxPurity(Rule):
    name = "jax-purity"
    motivation = ("tracer leaks are the standing failure mode of the "
                  "device plane (ROADMAP #1/#2): a Python `if` or .item() "
                  "on a traced value breaks jit tracing or forces a "
                  "device->host sync in the middle of the kernel")

    def applies_to(self, relpath):
        return relpath in _JAX_PURITY_FILES

    def begin_module(self, ctx):
        funcs = {n.name: n for n in ast.walk(ctx.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        traced: dict[str, set] = {}   # fn name → static argnames
        for name, fn in funcs.items():
            if name.endswith("_kernel"):
                traced.setdefault(name, set())
            for dec in fn.decorator_list:
                if _contains_jit(dec):
                    statics = _static_argnames(dec) \
                        if isinstance(dec, ast.Call) else set()
                    traced.setdefault(name, set()).update(statics)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            is_jit = _contains_jit(node.func) or (
                _call_name(node) == "pallas_call")
            if not is_jit:
                continue
            statics = _static_argnames(node)
            for n in ast.walk(node):
                if isinstance(n, ast.Name) and n.id in funcs:
                    traced.setdefault(n.id, set()).update(statics)
        for name in traced:
            self._check_traced(funcs[name], traced[name], ctx)
        # host syncs are wrong anywhere in these files' device sections:
        # .item() stalls the pipeline per element
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                ctx.report(self, node,
                           ".item() forces a device->host sync — keep "
                           "values on device or pull whole arrays once "
                           "with np.asarray")

    def _check_traced(self, fn, statics: set, ctx):
        args = fn.args
        tainted = {a.arg for a in
                   list(args.posonlyargs) + list(args.args)
                   if a.arg not in statics and a.arg != "self"}
        # forward-propagate through assignments from array expressions
        assigns = sorted((n for n in ast.walk(fn)
                          if isinstance(n, (ast.Assign, ast.AugAssign,
                                            ast.AnnAssign))),
                         key=lambda n: n.lineno)
        for _ in range(2):   # two passes ≈ fixpoint for real code
            for a in assigns:
                value = a.value
                if value is None:
                    continue
                refs = _names_in(value)
                is_arrayish = bool(refs & tainted) or any(
                    isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id in _ARRAY_MODULES
                    for n in ast.walk(value))
                if not is_arrayish:
                    continue
                targets = a.targets if isinstance(a, ast.Assign) \
                    else [a.target]
                for t in targets:
                    tainted |= _names_in(t)
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While, ast.IfExp)) \
                    and _names_in(node.test) & tainted:
                ctx.report(self, node,
                           f"Python branch on traced value "
                           f"({', '.join(sorted(_names_in(node.test) & tainted))}) "
                           f"inside jitted {fn.name} — use jnp.where/"
                           f"lax.cond, or mark the arg static")
            elif isinstance(node, ast.Call):
                name = _call_name(node)
                if name in ("bool", "int", "float") and node.args \
                        and _names_in(node.args[0]) & tainted:
                    ctx.report(self, node,
                               f"{name}() on traced value inside jitted "
                               f"{fn.name} — concretizes the tracer "
                               f"(ConcretizationTypeError at best)")
                elif name in ("asarray", "array") \
                        and _recv_text(node) == "np" and node.args \
                        and _names_in(node.args[0]) & tainted:
                    ctx.report(self, node,
                               f"np.{name}() on traced value inside jitted "
                               f"{fn.name} — host materialization under "
                               f"trace")


# --------------------------------------------------------------------------
# 8. wallclock-duration — new: time.time() arithmetic where monotonic()
#    is required
# --------------------------------------------------------------------------
class WallclockDuration(Rule):
    name = "wallclock-duration"
    motivation = ("PR 4: deadline/backoff/breaker intervals measured with "
                  "time.time() jump under NTP step/slew — a clock step "
                  "mid-flight fires timeouts early or never")

    def begin_module(self, ctx):
        # each function is its own scope (the per-scope walks stop at
        # nested defs, so nothing is visited twice); module level last
        scopes = [n for n in ast.walk(ctx.tree)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        scopes.append(ctx.tree)
        for scope in scopes:
            self._check_scope(scope, ctx)

    def _check_scope(self, scope, ctx):
        tainted: set = set()
        for n in _walk_no_nested_funcs(scope):
            if isinstance(n, ast.Assign) and _is_time_time_call(n.value):
                # only plain names: `kwargs["at"] = time.time()` stores a
                # timestamp in a container, it doesn't make the container
                # a clock reading
                tainted |= {t.id for t in n.targets
                            if isinstance(t, ast.Name)}
        reported: set = set()
        for n in _walk_no_nested_funcs(scope):
            if not isinstance(n, (ast.BinOp, ast.Compare)):
                continue
            if isinstance(n, ast.BinOp) \
                    and not isinstance(n.op, (ast.Add, ast.Sub)):
                continue
            hit = any(_is_time_time_call(x) for x in ast.walk(n))
            if not hit and tainted:
                hit = bool(_names_in(n) & tainted)
            if hit and n.lineno not in reported:
                reported.add(n.lineno)
                ctx.report(self, n,
                           "duration arithmetic on time.time() — wall "
                           "clock steps under NTP; use time.monotonic() "
                           "(wall clock is only for cross-process "
                           "timestamps, which deserve a disable= + reason)")


# --------------------------------------------------------------------------
# 9. metrics-naming — new: /metrics naming conventions
# --------------------------------------------------------------------------
_METRIC_NAME_RE = re.compile(r"^cnosdb_[a-z0-9_]+$")
_METRIC_METHODS = {"incr", "set_gauge", "set_counter", "observe"}


class MetricsNaming(Rule):
    name = "metrics-naming"
    motivation = ("dashboards and the bench-trajectory tooling key on "
                  "cnosdb_* naming; unprefixed or mis-suffixed series "
                  "silently fall out of every query")
    node_types = (ast.Call,)

    def visit(self, node, ctx):
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS):
            return
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            return
        name = node.args[0].value
        method = node.func.attr
        if not _METRIC_NAME_RE.match(name):
            ctx.report(self, node,
                       f"metric {name!r} must match cnosdb_[a-z0-9_]+ "
                       f"(prefixed, lowercase snake_case)")
            return
        if method in ("incr", "set_counter") \
                and not name.endswith("_total"):
            ctx.report(self, node,
                       f"counter {name!r} must end in _total "
                       f"(prometheus counter convention)")
        elif method == "observe" and not name.endswith(
                ("_ms", "_seconds", "_bytes")):
            ctx.report(self, node,
                       f"histogram {name!r} must end in a unit suffix "
                       f"(_ms, _seconds, _bytes)")


# --------------------------------------------------------------------------
# 10. stage-catalog — new: profiling stage names must come from the
#     documented catalog
# --------------------------------------------------------------------------
_STAGE_METHODS = {"stage", "count"}
_STAGE_RECEIVERS = {"stages", "_stages"}


class StageCatalog(Rule):
    name = "stage-catalog"
    motivation = ("PR 7 profiling plane: EXPLAIN ANALYZE, the slow-query "
                  "log and bench trend tooling all key on stage names; a "
                  "typo'd or undocumented name silently drifts out of "
                  "every report instead of failing")
    node_types = (ast.Call,)

    def visit(self, node, ctx):
        if _call_name(node) not in _STAGE_METHODS \
                or _recv_text(node) not in _STAGE_RECEIVERS \
                or not node.args:
            return
        from ..utils.stages import DYNAMIC_STAGE_PREFIXES, STAGE_CATALOG

        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
            if name in STAGE_CATALOG \
                    or name.startswith(DYNAMIC_STAGE_PREFIXES):
                return
            ctx.report(self, node,
                       f"stage name {name!r} is not in the documented "
                       f"catalog (utils/stages.STAGE_CATALOG) — add it "
                       f"there with a description, or fix the typo")
        elif isinstance(arg, ast.JoinedStr):
            head = arg.values[0].value \
                if (arg.values and isinstance(arg.values[0], ast.Constant)
                    and isinstance(arg.values[0].value, str)) else ""
            if not head.startswith(DYNAMIC_STAGE_PREFIXES):
                ctx.report(self, node,
                           f"dynamic stage name (f-string head {head!r}) "
                           f"does not start with a registered prefix "
                           f"(utils/stages.DYNAMIC_STAGE_PREFIXES)")


# --------------------------------------------------------------------------
# 11. device-decode-accounting — new (PR 9): no silent host fallbacks
# --------------------------------------------------------------------------
_DDA_FUNCS = {
    "cnosdb_tpu/storage/codecs.py": ("split_for_device",),
    "cnosdb_tpu/storage/scan.py": ("_submit_device_page",),
    "cnosdb_tpu/ops/device_decode.py": ("run", "attach_device_columns"),
}
_DDA_ACCOUNTING = {"_rejected", "_count_fallback", "count_outcome",
                   "declined", "submit", "note_engaged", "count_error"}


def _dda_has_accounting(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _call_name(n) in _DDA_ACCOUNTING:
            return True
    return False


def _dda_success_return(stmt: ast.AST) -> bool:
    """``return <plan>, None`` — split_for_device's accepted shape."""
    return (isinstance(stmt, ast.Return)
            and isinstance(stmt.value, ast.Tuple)
            and len(stmt.value.elts) == 2
            and isinstance(stmt.value.elts[1], ast.Constant)
            and stmt.value.elts[1].value is None)


def _dda_blocks(fn: ast.AST):
    """Every statement list in fn, nested functions excluded (a sink
    closure's exits belong to its own call-time contract)."""
    stack = [fn]
    while stack:
        node = stack.pop()
        for field in ("body", "orelse", "finalbody"):
            block = getattr(node, field, None)
            if isinstance(block, list) and block:   # IfExp's are exprs
                yield block
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


class DeviceDecodeAccounting(Rule):
    name = "device-decode-accounting"
    motivation = ("PR 9 device-decode plane: every page the device lane "
                  "examines but does not decode must book a (lane, "
                  "reason) outcome — an unaccounted early return/raise "
                  "reintroduces invisible host fallbacks, the exact "
                  "regression cnosdb_device_decode_total exists to catch")

    def applies_to(self, relpath):
        return relpath in _DDA_FUNCS

    def begin_module(self, ctx):
        want = _DDA_FUNCS.get(ctx.relpath)
        guarded = want is not None
        if want is None:
            # scope-ignored run (fixtures/self-tests): lint any function
            # bearing a guarded name, but skip the presence check — only
            # the real lane files owe us all of them
            want = tuple({n for names in _DDA_FUNCS.values()
                          for n in names})
        found = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name not in want:
                continue
            found.add(fn.name)
            terminal = fn.body[-1]
            for block in _dda_blocks(fn):
                for i, stmt in enumerate(block):
                    if not isinstance(stmt, (ast.Return, ast.Raise)) \
                            or stmt is terminal:
                        continue
                    prev = block[i - 1] if i else None
                    if _dda_has_accounting(stmt) \
                            or _dda_success_return(stmt) \
                            or (prev is not None
                                and _dda_has_accounting(prev)):
                        continue
                    kind = "return" if isinstance(stmt, ast.Return) \
                        else "raise"
                    ctx.report(self, stmt,
                               f"unaccounted early {kind} in {fn.name} — "
                               f"device-decode lane exits must pass "
                               f"reason accounting (_rejected/declined/"
                               f"count_outcome/_count_fallback) so host "
                               f"fallbacks stay visible on /metrics")
        for name in want if guarded else ():
            if name not in found:
                ctx.report(self, 1,
                           f"device-decode guarded function {name} not "
                           f"found — if it was renamed, update "
                           f"analysis/rules.py so the lint keeps "
                           f"covering it")


# --------------------------------------------------------------------------
# 12. string-filter-accounting — new (PR 10): no silent per-row fallbacks
# --------------------------------------------------------------------------
_SFA_FUNCS = {
    "cnosdb_tpu/ops/strkernels.py": ("unique_mask", "like_rows",
                                     "topk_order_indices"),
    "cnosdb_tpu/sql/expr.py": ("_per_unique_cmp",),
}
_SFA_ACCOUNTING = {"note_path", "count", "note_engaged", "count_outcome"}


def _sfa_has_accounting(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _call_name(n) in _SFA_ACCOUNTING:
            return True
    return False


def _sfa_silent_none(stmt: ast.AST) -> bool:
    """``return None`` / bare ``return`` — a decline the CALLER books (the
    normal evaluator that then runs is not itself a string predicate, e.g.
    a numeric cmp falling out of _per_unique_cmp)."""
    return (isinstance(stmt, ast.Return)
            and (stmt.value is None
                 or (isinstance(stmt.value, ast.Constant)
                     and stmt.value.value is None)))


class StringFilterAccounting(Rule):
    name = "string-filter-accounting"
    motivation = ("PR 10 string/search plane: every exit out of the "
                  "per-unique/top-k lanes must book a (path, reason) "
                  "outcome or a topk.* stage — a silent early return "
                  "reintroduces invisible per-row host fallbacks, the "
                  "exact regression cnosdb_string_filter_total exists "
                  "to catch")

    def applies_to(self, relpath):
        return relpath in _SFA_FUNCS

    def begin_module(self, ctx):
        want = _SFA_FUNCS.get(ctx.relpath)
        guarded = want is not None
        if want is None:
            # scope-ignored run (fixtures/self-tests): lint any function
            # bearing a guarded name, but skip the presence check
            want = tuple({n for names in _SFA_FUNCS.values()
                          for n in names})
        found = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name not in want:
                continue
            found.add(fn.name)
            terminal = fn.body[-1]
            for block in _dda_blocks(fn):
                for i, stmt in enumerate(block):
                    if not isinstance(stmt, (ast.Return, ast.Raise)) \
                            or stmt is terminal:
                        continue
                    prev = block[i - 1] if i else None
                    if _sfa_has_accounting(stmt) \
                            or _sfa_silent_none(stmt) \
                            or (prev is not None
                                and _sfa_has_accounting(prev)):
                        continue
                    kind = "return" if isinstance(stmt, ast.Return) \
                        else "raise"
                    ctx.report(self, stmt,
                               f"unaccounted early {kind} in {fn.name} — "
                               f"string-lane exits must book a path/"
                               f"reason (note_path/stages.count) so "
                               f"per-row fallbacks stay visible on "
                               f"/metrics")
        for name in want if guarded else ():
            if name not in found:
                ctx.report(self, 1,
                           f"string-filter guarded function {name} not "
                           f"found — if it was renamed, update "
                           f"analysis/rules.py so the lint keeps "
                           f"covering it")


# --------------------------------------------------------------------------
# 13. cold-tier-accounting — new (PR 12): no silent cold-lane exits
# --------------------------------------------------------------------------
_CTA_FUNCS = {
    "cnosdb_tpu/storage/tiering.py": (
        "tier_vnode", "_tier_file", "rehydrate_file", "recover_vnode",
        "fetch_pages", "_page_raw", "_read_page", "buffer_array",
        "verify_cold_file", "purge_vnode"),
}
_CTA_ACCOUNTING = {"_count_cold", "count", "count_error"}


def _cta_has_accounting(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _call_name(n) in _CTA_ACCOUNTING:
            return True
    return False


class ColdTierAccounting(Rule):
    name = "cold-tier-accounting"
    motivation = ("PR 12 cold-tier plane: every exit out of the tier/"
                  "fetch/rehydrate lanes must book a (lane, reason) into "
                  "cnosdb_cold_tier_total — an unaccounted early return/"
                  "raise hides exactly the events (skipped files, cache "
                  "overflows, remote divergence) the cold tier's "
                  "correctness story depends on observing")

    def applies_to(self, relpath):
        return relpath in _CTA_FUNCS

    def begin_module(self, ctx):
        want = _CTA_FUNCS.get(ctx.relpath)
        guarded = want is not None
        if want is None:
            # scope-ignored run (fixtures/self-tests): lint any function
            # bearing a guarded name, but skip the presence check
            want = tuple({n for names in _CTA_FUNCS.values()
                          for n in names})
        found = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name not in want:
                continue
            found.add(fn.name)
            terminal = fn.body[-1]
            for block in _dda_blocks(fn):
                for i, stmt in enumerate(block):
                    if not isinstance(stmt, (ast.Return, ast.Raise)) \
                            or stmt is terminal:
                        continue
                    prev = block[i - 1] if i else None
                    if _cta_has_accounting(stmt) \
                            or (prev is not None
                                and _cta_has_accounting(prev)):
                        continue
                    kind = "return" if isinstance(stmt, ast.Return) \
                        else "raise"
                    ctx.report(self, stmt,
                               f"unaccounted early {kind} in {fn.name} — "
                               f"cold-tier lane exits must book a (lane, "
                               f"reason) (_count_cold/stages.count) so "
                               f"tiering skips and fetch failures stay "
                               f"visible on /metrics")
        for name in want if guarded else ():
            if name not in found:
                ctx.report(self, 1,
                           f"cold-tier guarded function {name} not "
                           f"found — if it was renamed, update "
                           f"analysis/rules.py so the lint keeps "
                           f"covering it")


# --------------------------------------------------------------------------
# 14. serving-accounting — new (PR 15): no silent serving-plane exits
# --------------------------------------------------------------------------
_SVA_FUNCS = {
    "cnosdb_tpu/server/serving.py": ("try_execute", "submit"),
}
_SVA_ACCOUNTING = {"_count_serving", "count", "count_error"}


def _sva_has_accounting(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _call_name(n) in _SVA_ACCOUNTING:
            return True
    return False


class ServingAccounting(Rule):
    name = "serving-accounting"
    motivation = ("PR 15 serving plane: every exit out of the cache/fuse "
                  "entry points must book a (layer, outcome) into "
                  "cnosdb_serving_total — an unaccounted early return "
                  "makes hit-ratio and batching telemetry lie, hiding "
                  "exactly the regressions (silent bypasses, declined "
                  "fusions) the serving-plane SLO depends on seeing")

    def applies_to(self, relpath):
        return relpath in _SVA_FUNCS

    def begin_module(self, ctx):
        want = _SVA_FUNCS.get(ctx.relpath)
        guarded = want is not None
        if want is None:
            # scope-ignored run (fixtures/self-tests): lint any function
            # bearing a guarded name, but skip the presence check
            want = tuple({n for names in _SVA_FUNCS.values()
                          for n in names})
        found = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name not in want:
                continue
            found.add(fn.name)
            terminal = fn.body[-1]
            for block in _dda_blocks(fn):
                for i, stmt in enumerate(block):
                    if not isinstance(stmt, (ast.Return, ast.Raise)) \
                            or stmt is terminal:
                        continue
                    prev = block[i - 1] if i else None
                    if _sva_has_accounting(stmt) \
                            or (prev is not None
                                and _sva_has_accounting(prev)):
                        continue
                    kind = "return" if isinstance(stmt, ast.Return) \
                        else "raise"
                    ctx.report(self, stmt,
                               f"unaccounted early {kind} in {fn.name} — "
                               f"serving-plane exits must book a (layer, "
                               f"outcome) (_count_serving/stages.count) "
                               f"so cache bypasses and declined fusions "
                               f"stay visible on /metrics")
        for name in want if guarded else ():
            if name not in found:
                ctx.report(self, 1,
                           f"serving guarded function {name} not "
                           f"found — if it was renamed, update "
                           f"analysis/rules.py so the lint keeps "
                           f"covering it")


# --------------------------------------------------------------------------
# 15. backup-accounting — new (PR 16): no silent DR-plane exits
# --------------------------------------------------------------------------
_BKA_FUNCS = {
    "cnosdb_tpu/storage/backup.py": ("archive_segment", "create_backup",
                                     "restore_backup", "install_vnode"),
}
_BKA_ACCOUNTING = {"_count_backup", "count", "count_error"}


def _bka_has_accounting(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _call_name(n) in _BKA_ACCOUNTING:
            return True
    return False


class BackupAccounting(Rule):
    name = "backup-accounting"
    motivation = ("PR 16 disaster-recovery plane: every exit out of the "
                  "archive/backup/restore lanes must book an (op, "
                  "outcome) into cnosdb_backup_total — an unaccounted "
                  "early return makes the RPO/backup telemetry lie, and "
                  "a DR plane that silently skips segments or vnodes is "
                  "discovered exactly when the backup is needed")

    def applies_to(self, relpath):
        return relpath in _BKA_FUNCS

    def begin_module(self, ctx):
        want = _BKA_FUNCS.get(ctx.relpath)
        guarded = want is not None
        if want is None:
            # scope-ignored run (fixtures/self-tests): lint any function
            # bearing a guarded name, but skip the presence check
            want = tuple({n for names in _BKA_FUNCS.values()
                          for n in names})
        found = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name not in want:
                continue
            found.add(fn.name)
            terminal = fn.body[-1]
            for block in _dda_blocks(fn):
                for i, stmt in enumerate(block):
                    if not isinstance(stmt, (ast.Return, ast.Raise)) \
                            or stmt is terminal:
                        continue
                    prev = block[i - 1] if i else None
                    if _bka_has_accounting(stmt) \
                            or (prev is not None
                                and _bka_has_accounting(prev)):
                        continue
                    kind = "return" if isinstance(stmt, ast.Return) \
                        else "raise"
                    ctx.report(self, stmt,
                               f"unaccounted early {kind} in {fn.name} — "
                               f"DR-plane exits must book an (op, "
                               f"outcome) (_count_backup/stages.count) so "
                               f"skipped segments and failed installs "
                               f"stay visible on /metrics")
        for name in want if guarded else ():
            if name not in found:
                ctx.report(self, 1,
                           f"backup guarded function {name} not "
                           f"found — if it was renamed, update "
                           f"analysis/rules.py so the lint keeps "
                           f"covering it")


# --------------------------------------------------------------------------
# 16. fault-site-coverage — new (PR 13): every fire() site must be in the
#     FAULT_POINTS registry the crash sweep enumerates
# --------------------------------------------------------------------------
_FSC_RECEIVERS = {"faults", "_faults"}


class FaultSiteCoverage(Rule):
    name = "fault-site-coverage"
    motivation = ("PR 13 nemesis plane: the crash-point sweep enumerates "
                  "faults.FAULT_POINTS — a fire() site that never "
                  "registered is a fault point the sweep silently skips, "
                  "so its torn-state bugs go unexplored; every site must "
                  "register_point() in its module or carry a reasoned "
                  "disable")
    node_types = (ast.Call,)

    def begin_module(self, ctx):
        self._registered = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and _call_name(node) == "register_point" \
                    and _recv_text(node) in _FSC_RECEIVERS \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                self._registered.add(node.args[0].value)

    def visit(self, node, ctx):
        if _call_name(node) != "fire" \
                or _recv_text(node) not in _FSC_RECEIVERS \
                or not node.args:
            return
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in self._registered:
                ctx.report(self, node,
                           f"fault point {arg.value!r} fired here but "
                           f"never registered — add faults.register_point"
                           f"({arg.value!r}, __name__, ...) in this "
                           f"module so the crash sweep covers it")
        else:
            ctx.report(self, node,
                       "dynamic fault point name — the sweep registry is "
                       "static, so fire() must name a literal registered "
                       "point, or register every candidate point and "
                       "carry a reasoned lint disable")


# --------------------------------------------------------------------------
# 17. compressed-domain-accounting — new (PR 17): no silent lane bails
# --------------------------------------------------------------------------
_CDA_FUNCS = {
    "cnosdb_tpu/storage/compressed_domain.py":
        ("build_spec", "_classify", "_answer", "_page_row_mask"),
}
_CDA_ACCOUNTING = {"count_outcome", "_declined", "_mat"}


def _cda_has_accounting(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _call_name(n) in _CDA_ACCOUNTING:
            return True
    return False


def _cda_success_return(stmt: ast.AST) -> bool:
    """``return <name>`` — handing back a computed result (a survivor
    mask, a spec) is the accepted shape; bails return None / a literal
    and must book why."""
    return isinstance(stmt, ast.Return) and isinstance(stmt.value, ast.Name)


class CompressedDomainAccounting(Rule):
    name = "compressed-domain-accounting"
    motivation = ("PR 17 compressed-domain lane: every page the lane "
                  "declines to answer/skip/mask must book a (lane, "
                  "reason) outcome — an unaccounted early return/raise "
                  "is a silent fall-through to full decode, the exact "
                  "regression cnosdb_compressed_domain_total exists to "
                  "catch")

    def applies_to(self, relpath):
        return relpath in _CDA_FUNCS

    def begin_module(self, ctx):
        want = _CDA_FUNCS.get(ctx.relpath)
        guarded = want is not None
        if want is None:
            # scope-ignored run (fixtures/self-tests): lint any function
            # bearing a guarded name, but skip the presence check
            want = tuple({n for names in _CDA_FUNCS.values()
                          for n in names})
        found = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name not in want:
                continue
            found.add(fn.name)
            terminal = fn.body[-1]
            for block in _dda_blocks(fn):
                for i, stmt in enumerate(block):
                    if not isinstance(stmt, (ast.Return, ast.Raise)) \
                            or stmt is terminal:
                        continue
                    # accounting may land anywhere earlier in the same
                    # block (skip exits bump counters between the book
                    # and the return), or inside the return expression
                    if _cda_has_accounting(stmt) \
                            or _cda_success_return(stmt) \
                            or any(_cda_has_accounting(prev)
                                   for prev in block[:i]):
                        continue
                    kind = "return" if isinstance(stmt, ast.Return) \
                        else "raise"
                    ctx.report(self, stmt,
                               f"unaccounted early {kind} in {fn.name} — "
                               f"compressed-domain lane exits must book a "
                               f"reason (count_outcome/_declined/_mat) so "
                               f"silent full-decode fallbacks stay "
                               f"visible on /metrics")
        for name in want if guarded else ():
            if name not in found:
                ctx.report(self, 1,
                           f"compressed-domain guarded function {name} "
                           f"not found — if it was renamed, update "
                           f"analysis/rules.py so the lint keeps "
                           f"covering it")


# --------------------------------------------------------------------------
# 18. hedge-accounting — new (PR 18): no silent hedge-lane exits
# --------------------------------------------------------------------------
_HGA_FUNCS = {
    "cnosdb_tpu/parallel/coordinator.py": ("_scan_remote_hedged",),
}
_HGA_ACCOUNTING = {"count_hedge", "count", "count_error", "count_breaker"}


def _hga_has_accounting(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _call_name(n) in _HGA_ACCOUNTING:
            return True
    return False


def _hga_success_return(stmt: ast.AST) -> bool:
    """``return <name>`` / ``return None`` / bare ``return`` — the
    winner-settle shapes: won/lost were booked in the enclosing block
    before the result dispatch, so these carry no reason of their own.
    Literal returns and raises must book why."""
    return isinstance(stmt, ast.Return) and (
        stmt.value is None
        or isinstance(stmt.value, ast.Name)
        or (isinstance(stmt.value, ast.Constant)
            and stmt.value.value is None))


class HedgeAccounting(Rule):
    name = "hedge-accounting"
    motivation = ("PR 18 gray-failure plane: every exit out of the hedged "
                  "scan lane must book into cnosdb_hedge_total (fired/won/"
                  "lost/cancelled/suppressed) or a hedge.* stage — an "
                  "unaccounted early exit makes the hedge ledger lie, and "
                  "that ledger is the only proof hedging stays tail-only "
                  "instead of silently doubling cluster scan load")

    def applies_to(self, relpath):
        return relpath in _HGA_FUNCS

    def begin_module(self, ctx):
        want = _HGA_FUNCS.get(ctx.relpath)
        guarded = want is not None
        if want is None:
            # scope-ignored run (fixtures/self-tests): lint any function
            # bearing a guarded name, but skip the presence check
            want = tuple({n for names in _HGA_FUNCS.values()
                          for n in names})
        found = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name not in want:
                continue
            found.add(fn.name)
            terminal = fn.body[-1]
            for block in _dda_blocks(fn):
                for i, stmt in enumerate(block):
                    if not isinstance(stmt, (ast.Return, ast.Raise)) \
                            or stmt is terminal:
                        continue
                    # accounting may land anywhere earlier in the same
                    # block (the settle path books won/lost, then
                    # dispatches on the result shape)
                    if _hga_has_accounting(stmt) \
                            or _hga_success_return(stmt) \
                            or any(_hga_has_accounting(prev)
                                   for prev in block[:i]):
                        continue
                    kind = "return" if isinstance(stmt, ast.Return) \
                        else "raise"
                    ctx.report(self, stmt,
                               f"unaccounted early {kind} in {fn.name} — "
                               f"hedge-lane exits must book into "
                               f"cnosdb_hedge_total (count_hedge) or a "
                               f"hedge.* stage so the hedge ledger stays "
                               f"trustworthy on /metrics")
        for name in want if guarded else ():
            if name not in found:
                ctx.report(self, 1,
                           f"hedge guarded function {name} not found — "
                           f"if it was renamed, update analysis/rules.py "
                           f"so the lint keeps covering it")


# --------------------------------------------------------------------------
# 19. memory-accounting — new (PR 19): no silent ladder exits
# --------------------------------------------------------------------------
_MEM_FUNCS = {
    "cnosdb_tpu/server/memory.py": ("write_admit", "rebalance"),
}
_MEM_ACCOUNTING = {"count", "_event"}


def _mem_has_accounting(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _call_name(n) in _MEM_ACCOUNTING:
            return True
    return False


def _mem_success_return(stmt: ast.AST) -> bool:
    """``return <name>`` / ``return None`` / bare ``return`` — the
    under-watermark fast paths: nothing was degraded, so there is
    nothing to book. Literal returns and raises must book why."""
    return isinstance(stmt, ast.Return) and (
        stmt.value is None
        or isinstance(stmt.value, ast.Name)
        or (isinstance(stmt.value, ast.Constant)
            and stmt.value.value is None))


class MemoryAccounting(Rule):
    name = "memory-accounting"
    motivation = ("PR 19 memory-governance plane: every degradation the "
                  "ladder takes (reclaim, shed, backpressure delay, "
                  "fail-closed) must book into cnosdb_memory_total "
                  "{pool,action} — an unaccounted exit means the node "
                  "degraded service with no trace, and those counters "
                  "are the only proof the broker (not an OOM kill) "
                  "handled the pressure")

    def applies_to(self, relpath):
        return relpath in _MEM_FUNCS

    def begin_module(self, ctx):
        want = _MEM_FUNCS.get(ctx.relpath)
        guarded = want is not None
        if want is None:
            # scope-ignored run (fixtures/self-tests): lint any function
            # bearing a guarded name, but skip the presence check
            want = tuple({n for names in _MEM_FUNCS.values()
                          for n in names})
        found = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name not in want:
                continue
            found.add(fn.name)
            terminal = fn.body[-1]
            for block in _dda_blocks(fn):
                for i, stmt in enumerate(block):
                    if not isinstance(stmt, (ast.Return, ast.Raise)) \
                            or stmt is terminal:
                        continue
                    # booking may land anywhere earlier in the same
                    # block (the ladder counts, logs the event ring,
                    # then raises)
                    if _mem_has_accounting(stmt) \
                            or _mem_success_return(stmt) \
                            or any(_mem_has_accounting(prev)
                                   for prev in block[:i]):
                        continue
                    kind = "return" if isinstance(stmt, ast.Return) \
                        else "raise"
                    ctx.report(self, stmt,
                               f"unaccounted early {kind} in {fn.name} — "
                               f"memory-ladder exits must book into "
                               f"cnosdb_memory_total (count/_event) so "
                               f"every degradation stays visible on "
                               f"/metrics and /debug/memory")
        for name in want if guarded else ():
            if name not in found:
                ctx.report(self, 1,
                           f"memory guarded function {name} not found — "
                           f"if it was renamed, update analysis/rules.py "
                           f"so the lint keeps covering it")


# --------------------------------------------------------------------------
# 20. mesh-accounting — new (PR 20): no silent mesh-lane exits
# --------------------------------------------------------------------------
_MA_FUNCS = {
    "cnosdb_tpu/ops/mesh_exec.py": ("try_mesh_aggregate",),
}
_MA_ACCOUNTING = {"count_outcome", "_declined", "count_error"}


def _ma_has_accounting(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _call_name(n) in _MA_ACCOUNTING:
            return True
    return False


def _ma_success_return(stmt: ast.AST) -> bool:
    """``return <name>`` — handing back a merged AggResult is the
    engaged shape (booked just above the return); bails return None /
    a literal and must book why."""
    return isinstance(stmt, ast.Return) and isinstance(stmt.value, ast.Name)


class MeshAccounting(Rule):
    name = "mesh-accounting"
    motivation = ("PR 20 mesh execution plane: every query the mesh lane "
                  "declines must book a (lane, reason) outcome into "
                  "cnosdb_mesh_total — an unaccounted early return/raise "
                  "is a silent fall-through to the host msgpack merge, "
                  "and those counters are the only proof on-mesh merges "
                  "actually stay collective instead of quietly regressing "
                  "to per-batch host hops")

    def applies_to(self, relpath):
        return relpath in _MA_FUNCS

    def begin_module(self, ctx):
        want = _MA_FUNCS.get(ctx.relpath)
        guarded = want is not None
        if want is None:
            # scope-ignored run (fixtures/self-tests): lint any function
            # bearing a guarded name, but skip the presence check
            want = tuple({n for names in _MA_FUNCS.values()
                          for n in names})
        found = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name not in want:
                continue
            found.add(fn.name)
            terminal = fn.body[-1]
            for block in _dda_blocks(fn):
                for i, stmt in enumerate(block):
                    if not isinstance(stmt, (ast.Return, ast.Raise)) \
                            or stmt is terminal:
                        continue
                    # accounting may land anywhere earlier in the same
                    # block (engaged exits book both lane counters, then
                    # return the merged result)
                    if _ma_has_accounting(stmt) \
                            or _ma_success_return(stmt) \
                            or any(_ma_has_accounting(prev)
                                   for prev in block[:i]):
                        continue
                    kind = "return" if isinstance(stmt, ast.Return) \
                        else "raise"
                    ctx.report(self, stmt,
                               f"unaccounted early {kind} in {fn.name} — "
                               f"mesh-lane exits must book a reason "
                               f"(count_outcome/_declined) so silent "
                               f"host-merge fallbacks stay visible on "
                               f"/metrics")
        for name in want if guarded else ():
            if name not in found:
                ctx.report(self, 1,
                           f"mesh guarded function {name} not found — "
                           f"if it was renamed, update analysis/rules.py "
                           f"so the lint keeps covering it")


def all_rules() -> list:
    from .interproc import project_rules

    return [NoBareExcept(), RpcCallTimeout(), RowLoop(), RowLoopFallback(),
            LockBlocking(), SwallowedException(), JaxPurity(),
            WallclockDuration(), MetricsNaming(), StageCatalog(),
            DeviceDecodeAccounting(), StringFilterAccounting(),
            ColdTierAccounting(), ServingAccounting(), BackupAccounting(),
            FaultSiteCoverage(), CompressedDomainAccounting(),
            HedgeAccounting(), MemoryAccounting(), MeshAccounting(),
            *project_rules()]
