"""Interprocedural device-dataflow substrate + the device-plane rules.

The per-file rules in :mod:`.rules` cannot see that ``np.asarray(x)``
is a device→host sync when ``x`` came out of a jitted kernel two call
edges away. This module builds what they are missing:

* a **project index**: every function/method definition in the run,
  each file's imports, module-level ``X = jax.jit(f)`` aliases, and the
  named locks (``lockwatch.Lock(...)`` / ``threading.Lock()`` targets);
* a **call graph** with deliberately conservative resolution — a call
  resolves only through (a) local names, (b) ``from mod import f``,
  (c) ``mod.f`` where ``mod`` is an imported project module,
  (d) ``self.m``/``cls.m`` to a same-file method, or (e) a bare
  attribute name with exactly ONE definition project-wide that is not a
  stdlib-common name. Ambiguity resolves to *nothing*: a missed edge
  costs a finding, a wrong edge costs a false positive, and false
  positives kill linters;
* **per-function summaries** (returns-device, dispatches-on-device,
  reaches-rpc, accepts-deadline) driven to fixpoint with a worklist —
  all flags are monotone booleans so the pass count is bounded by the
  longest call chain;
* a per-function **taint environment** mapping local names to
  host/device, seeded by ``jnp.*``/``jax.*``/``lax.*`` calls,
  ``device_put``/``pallas_call``, jit aliases, and device-returning
  callees; ``np.asarray``/``float()``/``int()``/``bool()``/``len()``/
  ``.item()``/``.tolist()`` are the *crossings* — their results are
  host (and, in a hot path, the crossing itself is a finding).

Deliberate non-goals: attribute taint (``self.dev_out``) is not
tracked — the designed transfer points in ops/ stage device handles on
objects precisely so the crossing is one audited place; tracking them
would re-flag every one through every accessor.

Rules shipped on this substrate: host-sync, recompile-hazard,
lock-held-dispatch, deadline-propagation (see each class).
"""
from __future__ import annotations

import ast
import collections

from . import ProjectRule

_UNRESOLVED = object()                   # memo-table "no entry" marker

# modules whose attribute calls produce device values / dispatch work
_DEVICE_MODULES = {"jnp", "lax", "pl", "pltpu"}
_DEVICE_ENTRY_NAMES = {"device_put", "pallas_call"}
# under the bare `jax` namespace only these attrs touch arrays —
# jax.devices() / jax.local_device_count() return host metadata handles
_JAX_ARRAY_ATTRS = {"numpy", "lax", "ops", "device_put", "jit", "pmap",
                    "vmap", "block_until_ready", "pure_callback"}
# builtins that pass device-ness through untouched (no sync of their own)
_TRANSPARENT_CALLS = {"zip", "sorted", "enumerate", "reversed", "list",
                      "tuple", "iter", "min", "max", "abs", "sum"}
# results of these are host-side by construction (they ARE the crossing)
_HOST_CAST_NAMES = {"float", "int", "bool", "len", "str"}
_HOST_CAST_ATTRS = {"asarray", "array"}          # on np/numpy
_HOST_CAST_METHODS = {"item", "tolist"}
# bare attribute names too generic for unique-definition resolution —
# they are stdlib/dict/file vocabulary, so `obj.get(...)` must never
# resolve to some lone project function that happens to share the name
_AMBIGUOUS_ATTRS = {
    "run", "get", "put", "eval", "check", "close", "open", "append",
    "add", "update", "pop", "read", "write", "count", "wait", "cancel",
    "copy", "join", "start", "stop", "send", "recv", "result", "clear",
    "sort", "extend", "remove", "acquire", "release", "sleep", "next",
    "items", "values", "keys", "setdefault", "submit", "format",
}
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}


def _contains_jit(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id == "jit":
            return True
        if isinstance(n, ast.Attribute) and n.attr == "jit":
            return True
    return False


def _static_argnames(call: ast.Call) -> set:
    out: set = set()
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            out.add(v.value)
        elif isinstance(v, (ast.Tuple, ast.List)):
            for elt in v.elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, str):
                    out.add(elt.value)
    return out


def _walk_no_nested(root: ast.AST):
    """Child walk that stops at nested function/lambda boundaries (each
    nested def is summarized as its own function)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _attr_base(expr: ast.AST) -> str | None:
    """``jnp.linalg.norm`` → ``jnp``; ``x.item`` → ``x``."""
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _param_names(args: ast.arguments) -> list:
    names = [a.arg for a in list(args.posonlyargs) + list(args.args)]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _is_host_cast(expr: ast.AST) -> bool:
    """True when ``expr`` is structurally a device→host crossing whose
    RESULT lives on the host: np.asarray(...), float/int/bool/len(...),
    .item()/.tolist(), and any subscript/astype chain on one of those."""
    if isinstance(expr, ast.Subscript):
        return _is_host_cast(expr.value)
    if not isinstance(expr, ast.Call):
        return False
    fn = expr.func
    if isinstance(fn, ast.Name):
        return fn.id in _HOST_CAST_NAMES
    if isinstance(fn, ast.Attribute):
        if fn.attr in _HOST_CAST_ATTRS \
                and _attr_base(fn) in ("np", "numpy"):
            return True
        if fn.attr in _HOST_CAST_METHODS:
            return True
        if fn.attr == "astype":          # host.astype(...) stays host
            return _is_host_cast(fn.value)
    return False


class FuncInfo:
    """One function/method definition + its dataflow summary."""

    __slots__ = ("qualname", "relpath", "name", "node", "params",
                 "jitted", "static_argnames", "synthetic", "call_sites",
                 "returns_device", "dispatches_device", "does_rpc",
                 "reaches_device", "reaches_rpc", "tainted",
                 "deadline_params", "taint_stmts", "returns")

    def __init__(self, qualname: str, relpath: str, node,
                 synthetic: bool = False):
        self.qualname = qualname
        self.relpath = relpath
        self.name = qualname.split(":", 1)[-1].rsplit(".", 1)[-1]
        self.node = node
        self.synthetic = synthetic
        self.params: list = []
        self.jitted = False
        self.static_argnames: set = set()
        self.call_sites: list = []       # [(ast.Call, FuncInfo | None)]
        self.returns_device = False
        self.dispatches_device = False
        self.does_rpc = False
        self.reaches_device = False
        self.reaches_rpc = False
        self.tainted: set = set()
        self.deadline_params: set = set()
        self.taint_stmts: list = []      # line-ordered assign/for/comp
        self.returns: list = []          # ast.Return nodes, own body only

    @property
    def accepts_deadline(self) -> bool:
        return bool(self.deadline_params)


class FileIndex:
    """Per-file slice of the project index."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.relpath = ctx.relpath
        self.tree = ctx.tree
        # dotted module name: cnosdb_tpu/ops/kernels.py →
        # cnosdb_tpu.ops.kernels; files outside the package keep their
        # stem so fixture pairs can import each other by basename
        rp = ctx.relpath
        stem = rp[:-3] if rp.endswith(".py") else rp
        parts = stem.replace("\\", "/").split("/")
        self.is_pkg = parts[-1] == "__init__"
        if self.is_pkg:
            parts = parts[:-1]
        if parts and parts[0] == "cnosdb_tpu":
            self.module = ".".join(parts)
            self.pkg_parts = parts if self.is_pkg else parts[:-1]
        else:
            self.module = parts[-1] if parts else stem
            self.pkg_parts = []
        self.funcs: dict = {}            # dotted-in-file name → FuncInfo
        self.by_bare: dict = {}          # bare name → [FuncInfo]
        self.toplevel: dict = {}         # module-level name → FuncInfo
        self.import_modules: dict = {}   # alias → dotted module
        self.from_targets: dict = {}     # name → (dotted module, orig)
        self.jit_aliases: dict = {}      # name → synthetic FuncInfo
        self.lock_names: set = set()


class Project:
    """Whole-run call graph + summaries; the substrate project rules
    query. Construction: index every file, link imports, resolve call
    sites once, then drive the monotone summary flags to fixpoint."""

    def __init__(self, contexts, ignore_scope: bool = False):
        self.ignore_scope = ignore_scope
        self._resolved: dict = {}        # id(ast.Call) → FuncInfo | None
        self.files: dict = {}            # relpath → FileIndex
        self.modules: dict = {}          # dotted module → FileIndex
        self.by_bare: dict = {}          # bare name → [FuncInfo]
        self.functions: list = []        # every FuncInfo, stable order
        self.lock_names: set = set()
        for ctx in contexts:
            fi = FileIndex(ctx)
            self.files[fi.relpath] = fi
            self.modules[fi.module] = fi
            self._index_file(fi)
        self._link_imports()
        # one body walk per function: call sites (resolved + memoized),
        # the taint-relevant statements, and the returns — the fixpoint
        # revisits functions but never re-walks their ASTs
        for info in self.functions:
            if info.synthetic:
                continue
            fi = self.files[info.relpath]
            for n in _walk_no_nested(info.node):
                if isinstance(n, ast.Call):
                    info.call_sites.append((n, self.resolve_call(n, fi)))
                elif isinstance(n, (ast.Assign, ast.AnnAssign,
                                    ast.AugAssign, ast.For,
                                    ast.comprehension)):
                    info.taint_stmts.append(n)
                elif isinstance(n, ast.Return):
                    info.returns.append(n)
            info.taint_stmts.sort(
                key=lambda n: getattr(n, "lineno",
                                      getattr(getattr(n, "iter", None),
                                              "lineno", 0)))
        self._fixpoint()

    # ------------------------------------------------------------ index
    def _index_file(self, fi: FileIndex) -> None:
        def add_func(node, prefix):
            qual = f"{prefix}{node.name}" if prefix else node.name
            info = FuncInfo(f"{fi.relpath}:{qual}", fi.relpath, node)
            info.params = _param_names(node.args)
            for a in (list(node.args.posonlyargs) + list(node.args.args)
                      + list(node.args.kwonlyargs)):
                ann = ""
                if a.annotation is not None:
                    try:
                        ann = ast.unparse(a.annotation)
                    except Exception:
                        ann = ""
                if a.arg == "deadline" or "Deadline" in ann:
                    info.deadline_params.add(a.arg)
            if node.name.endswith("_kernel"):
                info.jitted = True
            for dec in node.decorator_list:
                if _contains_jit(dec):
                    info.jitted = True
                    if isinstance(dec, ast.Call):
                        info.static_argnames |= _static_argnames(dec)
            if info.jitted:
                # calling a jitted function yields device arrays no
                # matter what its body looks like textually
                info.returns_device = True
            fi.funcs[qual] = info
            fi.by_bare.setdefault(node.name, []).append(info)
            self.by_bare.setdefault(node.name, []).append(info)
            if not prefix:
                fi.toplevel[node.name] = info
            self.functions.append(info)
            return info

        def visit(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    info = add_func(child, prefix)
                    visit(child, info.qualname.split(":", 1)[1] + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}{child.name}.")
                else:
                    visit(child, prefix)

        visit(fi.tree, "")

        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name
                    if alias.asname or "." not in alias.name:
                        fi.import_modules[name] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(fi, node)
                if base is None:
                    continue
                for alias in node.names:
                    fi.from_targets[alias.asname or alias.name] = \
                        (base, alias.name)
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                call = node.value
                cname = call.func.attr \
                    if isinstance(call.func, ast.Attribute) else (
                        call.func.id if isinstance(call.func, ast.Name)
                        else None)
                if cname in _LOCK_FACTORIES:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            fi.lock_names.add(t.id)
                        elif isinstance(t, ast.Attribute):
                            fi.lock_names.add(t.attr)
                elif _contains_jit(call.func):
                    self._add_jit_alias(fi, node, call)
        self.lock_names |= fi.lock_names

    def _add_jit_alias(self, fi: FileIndex, node: ast.Assign,
                       call: ast.Call) -> None:
        """Module-level ``X = jax.jit(f, static_argnames=...)``: calls
        to X dispatch on device and return device arrays; f itself is
        traced under X's static set."""
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            info = FuncInfo(f"{fi.relpath}:{t.id}", fi.relpath, node,
                            synthetic=True)
            info.jitted = True
            info.returns_device = True
            info.dispatches_device = info.reaches_device = True
            info.static_argnames = _static_argnames(call)
            wrapped = call.args[0] if call.args else None
            if isinstance(wrapped, ast.Lambda):
                info.params = _param_names(wrapped.args)
            elif isinstance(wrapped, ast.Name):
                target = fi.toplevel.get(wrapped.id)
                if target is not None:
                    info.params = list(target.params)
                    target.jitted = True
                    target.returns_device = True
                    target.static_argnames |= info.static_argnames
            fi.jit_aliases[t.id] = info
            self.functions.append(info)

    def _import_base(self, fi: FileIndex, node: ast.ImportFrom):
        if node.level == 0:
            return node.module
        base = fi.pkg_parts[:len(fi.pkg_parts) - (node.level - 1)] \
            if node.level - 1 <= len(fi.pkg_parts) else None
        if base is None:
            return None
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base) if base else None

    def _link_imports(self) -> None:
        """Second pass once every module is known: a ``from pkg import
        name`` binds either a submodule or a function."""
        for fi in self.files.values():
            for name, (base, orig) in fi.from_targets.items():
                sub = f"{base}.{orig}" if base else orig
                if sub in self.modules:
                    fi.import_modules[name] = sub
                elif orig in self.modules and not base:
                    fi.import_modules[name] = orig

    # ------------------------------------------------------- resolution
    def resolve_call(self, call: ast.Call, fi: FileIndex):
        # nodes are owned by this Project for its whole lifetime, so
        # id() is a stable memo key; resolution is pure after indexing
        key = id(call)
        hit = self._resolved.get(key, _UNRESOLVED)
        if hit is not _UNRESOLVED:
            return hit
        out = self._resolve_call(call, fi)
        self._resolved[key] = out
        return out

    def _resolve_call(self, call: ast.Call, fi: FileIndex):
        fn = call.func
        if isinstance(fn, ast.Name):
            n = fn.id
            if n in fi.jit_aliases:
                return fi.jit_aliases[n]
            if n in fi.toplevel:
                return fi.toplevel[n]
            tgt = fi.from_targets.get(n)
            if tgt is not None:
                tfi = self.modules.get(tgt[0]) if tgt[0] else None
                if tfi is not None:
                    return tfi.jit_aliases.get(tgt[1]) \
                        or tfi.toplevel.get(tgt[1])
            return None
        if isinstance(fn, ast.Attribute):
            a = fn.attr
            v = fn.value
            if isinstance(v, ast.Name):
                mod = fi.import_modules.get(v.id)
                if mod is not None:
                    tfi = self.modules.get(mod)
                    if tfi is not None:
                        return tfi.jit_aliases.get(a) \
                            or tfi.toplevel.get(a)
                    return None
                if v.id in ("self", "cls"):
                    cands = [x for x in fi.by_bare.get(a, ())
                             if x not in fi.toplevel.values()]
                    return cands[0] if len(cands) == 1 else None
            # last resort: a bare method name with exactly one
            # definition anywhere in the project, and not so common
            # that stdlib objects answer to it too
            if a in _AMBIGUOUS_ATTRS:
                return None
            cands = self.by_bare.get(a, ())
            return cands[0] if len(cands) == 1 else None
        return None

    # -------------------------------------------------------- summaries
    def _is_device_call(self, call: ast.Call, fi: FileIndex) -> bool:
        fn = call.func
        if isinstance(fn, ast.Attribute):
            base = _attr_base(fn)
            if base in _DEVICE_MODULES:
                return True
            if base == "jax":
                chain = set()
                e = fn
                while isinstance(e, ast.Attribute):
                    chain.add(e.attr)
                    e = e.value
                return bool(chain & _JAX_ARRAY_ATTRS)
            if fn.attr in _DEVICE_ENTRY_NAMES:
                return True
        elif isinstance(fn, ast.Name):
            if fn.id in _DEVICE_ENTRY_NAMES:
                return True
            if fn.id in fi.jit_aliases:
                return True
        return False

    def _expr_device(self, expr, tainted: set, fi: FileIndex) -> bool:
        """Does ``expr`` evaluate to a device value? Host casts cut the
        flow; device-ness enters via device calls, jit aliases,
        device-returning callees, or already-tainted names."""
        if expr is None or _is_host_cast(expr):
            return False
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Call):
            if self._is_device_call(expr, fi):
                return True
            if isinstance(expr.func, ast.Name) \
                    and expr.func.id in _TRANSPARENT_CALLS:
                return any(self._expr_device(a, tainted, fi)
                           for a in expr.args)
            callee = self.resolve_call(expr, fi)
            if callee is not None and callee.returns_device:
                return True
            # unresolved/host callee: its RESULT is not assumed device
            # (host helpers over device args are the common case), but
            # a device receiver keeps method-call results device:
            # dev.sum() / dev.reshape(...) stay on device
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr not in _HOST_CAST_METHODS \
                    and self._expr_device(expr.func.value, tainted, fi):
                return True
            return False
        if isinstance(expr, (ast.JoinedStr, ast.Constant)):
            return False
        return any(self._expr_device(c, tainted, fi)
                   for c in ast.iter_child_nodes(expr))

    def taint_env(self, info: FuncInfo) -> set:
        """Device-tainted local names of ``info`` given current callee
        summaries. Two line-ordered passes approximate the intra-
        function fixpoint (real code assigns before use)."""
        if info.synthetic:
            return set()
        fi = self.files[info.relpath]
        tainted: set = set()
        stmts = info.taint_stmts
        for _ in range(2):
            for n in stmts:
                if isinstance(n, (ast.Assign, ast.AnnAssign,
                                  ast.AugAssign)):
                    value = n.value
                    if value is None:
                        continue
                    targets = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    tnames = set()
                    for t in targets:
                        tnames |= _names_in(t) \
                            if not isinstance(t, (ast.Attribute,
                                                  ast.Subscript)) \
                            else set()
                    if _is_host_cast(value):
                        tainted -= tnames
                    elif self._expr_device(value, tainted, fi):
                        tainted |= tnames
                elif isinstance(n, ast.For):
                    if self._expr_device(n.iter, tainted, fi):
                        tainted |= _names_in(n.target)
                elif isinstance(n, ast.comprehension):
                    if not self._expr_device(n.iter, tainted, fi):
                        continue
                    # `.items()` of a tainted dict: keys stay host
                    it = n.iter
                    if isinstance(it, ast.Call) \
                            and isinstance(it.func, ast.Attribute) \
                            and it.func.attr == "items" \
                            and isinstance(n.target, ast.Tuple) \
                            and len(n.target.elts) == 2:
                        tainted |= _names_in(n.target.elts[1])
                    elif isinstance(it, ast.Call) \
                            and isinstance(it.func, ast.Attribute) \
                            and it.func.attr == "keys":
                        pass
                    else:
                        tainted |= _names_in(n.target)
        return tainted

    def _fixpoint(self) -> None:
        """Worklist pass: seed each function's direct facts, then
        re-summarize a function only when one of its callees' monotone
        flags changed. Termination: three booleans per function, each
        flips at most once, and a flip enqueues only the callers."""
        callers: dict = {}               # FuncInfo → [caller FuncInfo]
        for info in self.functions:
            if info.synthetic:
                continue
            fi = self.files[info.relpath]
            for call, callee in info.call_sites:
                if self._is_device_call(call, fi):
                    info.dispatches_device = True
                fname = call.func.id \
                    if isinstance(call.func, ast.Name) else (
                        call.func.attr
                        if isinstance(call.func, ast.Attribute)
                        else None)
                if fname == "rpc_call":
                    info.does_rpc = True
                if callee is not None:
                    callers.setdefault(callee, []).append(info)
            info.reaches_device = info.dispatches_device
            info.reaches_rpc = info.does_rpc
        pending = collections.deque(
            i for i in self.functions if not i.synthetic)
        queued = {id(i) for i in pending}
        while pending:
            info = pending.popleft()
            queued.discard(id(info))
            rd, rr = info.reaches_device, info.reaches_rpc
            for _call, callee in info.call_sites:
                if callee is None:
                    continue
                rd = rd or callee.reaches_device
                rr = rr or callee.reaches_rpc
            tainted = self.taint_env(info)
            ret_dev = info.returns_device
            if not ret_dev:
                fi = self.files[info.relpath]
                for n in info.returns:
                    if self._expr_device(n.value, tainted, fi):
                        ret_dev = True
                        break
            info.tainted = tainted
            if (rd, rr, ret_dev) != (info.reaches_device,
                                     info.reaches_rpc,
                                     info.returns_device):
                info.reaches_device = rd
                info.reaches_rpc = rr
                info.returns_device = ret_dev
                for caller in callers.get(info, ()):
                    if id(caller) not in queued:
                        queued.add(id(caller))
                        pending.append(caller)

    # -------------------------------------------------------- reporting
    def report(self, rule, relpath: str, node, message: str) -> None:
        ctx = self.files[relpath].ctx
        if not (self.ignore_scope or rule.applies_to(relpath)):
            return
        ctx.report(rule, node, message)

    def render_callgraph(self) -> str:
        lines = []
        for info in sorted(self.functions, key=lambda i: i.qualname):
            tags = [t for t, on in (
                ("jit", info.jitted),
                ("returns-device", info.returns_device),
                ("dispatches", info.reaches_device),
                ("rpc", info.reaches_rpc),
                ("deadline", info.accepts_deadline)) if on]
            callees = sorted({c.qualname for _x, c in info.call_sites
                              if c is not None})
            lines.append(f"{info.qualname} [{','.join(tags)}]"
                         + (f" -> {', '.join(callees)}" if callees else ""))
        return "\n".join(lines)


# ==========================================================================
# the device-plane rule family
# ==========================================================================

_HOT_PATHS = ("cnosdb_tpu/ops/",)
_HOT_FILES = ("cnosdb_tpu/storage/scan.py", "cnosdb_tpu/sql/executor.py")


class HostSync(ProjectRule):
    """Device→host pulls on values that flow (possibly through several
    call edges) from jax ops, inside the scan/exec/kernel hot paths."""

    name = "host-sync"
    motivation = ("PR 9/10 device planes: a stray np.asarray/.item() on "
                  "a device array stalls the XLA pipeline mid-query — "
                  "the transfer is silent, correct, and 10-100x the cost "
                  "of the op it interrupts; every crossing must be one "
                  "of the audited single-transfer points")

    def applies_to(self, relpath):
        return relpath.startswith(_HOT_PATHS) or relpath in _HOT_FILES

    def check(self, project: Project) -> None:
        for info in project.functions:
            if info.synthetic or info.jitted:
                continue   # traced bodies are jax-purity's domain
            if not (project.ignore_scope
                    or self.applies_to(info.relpath)):
                continue
            fi = project.files[info.relpath]
            tainted = info.tainted
            seen: set = set()

            def flag(node, what):
                if node.lineno in seen:
                    return
                seen.add(node.lineno)
                project.report(self, info.relpath, node,
                               f"{what} on a device value inside "
                               f"{info.name} — a silent device->host "
                               f"sync in a hot path; keep it on device "
                               f"or route it through an audited "
                               f"transfer point")

            for node in _walk_no_nested(info.node):
                if isinstance(node, ast.Call):
                    fn = node.func
                    if isinstance(fn, ast.Attribute) \
                            and fn.attr in _HOST_CAST_ATTRS \
                            and _attr_base(fn) in ("np", "numpy") \
                            and node.args \
                            and project._expr_device(node.args[0],
                                                     tainted, fi):
                        flag(node, f"np.{fn.attr}()")
                    elif isinstance(fn, ast.Name) \
                            and fn.id in ("float", "int", "bool") \
                            and node.args \
                            and project._expr_device(node.args[0],
                                                     tainted, fi):
                        flag(node, f"{fn.id}()")
                    elif isinstance(fn, ast.Attribute) \
                            and fn.attr == "item" and not node.args \
                            and project._expr_device(fn.value,
                                                     tainted, fi):
                        flag(node, ".item()")
                elif isinstance(node, ast.For):
                    if isinstance(node.iter, ast.Name) \
                            and node.iter.id in tainted:
                        flag(node, "python iteration")


class RecompileHazard(ProjectRule):
    """Jitted callees reached with data-dependent Python scalars at
    non-static params, and shape-dependent branching in jitted bodies —
    both retrace/recompile per distinct value or shape class."""

    name = "recompile-hazard"
    motivation = ("the kernel cache (ops/fused, pad_rows size classes) "
                  "exists because one uncached shape per call turned "
                  "seconds of query into minutes of XLA compile; a "
                  "len()/.shape argument at a non-static jit param "
                  "quietly reintroduces that per-call retrace")

    def applies_to(self, relpath):
        return relpath.startswith("cnosdb_tpu/ops/")

    @staticmethod
    def _shape_scalar(expr: ast.AST) -> str | None:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id == "len":
                return "len(...)"
            if isinstance(n, ast.Attribute) and n.attr == "shape":
                return ".shape"
        return None

    def check(self, project: Project) -> None:
        for info in project.functions:
            if info.synthetic:
                continue
            if not (project.ignore_scope
                    or self.applies_to(info.relpath)):
                continue
            for call, callee in info.call_sites:
                if callee is None or not callee.jitted:
                    continue
                statics = callee.static_argnames
                params = callee.params
                for i, a in enumerate(call.args):
                    pname = params[i] if i < len(params) else None
                    if pname is not None and pname in statics:
                        continue
                    what = self._shape_scalar(a)
                    if what:
                        project.report(
                            self, info.relpath, call,
                            f"data-dependent scalar ({what}) passed to "
                            f"jitted {callee.name} at non-static "
                            f"position {i} — every distinct value "
                            f"retraces; declare it in static_argnames "
                            f"or pad to a size class")
                for kw in call.keywords:
                    if kw.arg is None or kw.arg in statics:
                        continue
                    what = self._shape_scalar(kw.value)
                    if what:
                        project.report(
                            self, info.relpath, call,
                            f"data-dependent scalar ({what}) passed to "
                            f"jitted {callee.name} at non-static param "
                            f"{kw.arg!r} — every distinct value "
                            f"retraces; declare it static or pad to a "
                            f"size class")
            if info.jitted:
                nonstatic = set(info.params) - info.static_argnames
                for node in _walk_no_nested(info.node):
                    if not isinstance(node, (ast.If, ast.While,
                                             ast.IfExp)):
                        continue
                    hit = None
                    for n in ast.walk(node.test):
                        if isinstance(n, ast.Attribute) \
                                and n.attr == "shape" \
                                and isinstance(n.value, ast.Name) \
                                and n.value.id in nonstatic:
                            hit = f"{n.value.id}.shape"
                        elif isinstance(n, ast.Call) \
                                and isinstance(n.func, ast.Name) \
                                and n.func.id == "len" and n.args \
                                and isinstance(n.args[0], ast.Name) \
                                and n.args[0].id in nonstatic:
                            hit = f"len({n.args[0].id})"
                    if hit:
                        project.report(
                            self, info.relpath, node,
                            f"shape-dependent branch on {hit} inside "
                            f"jitted {info.name} — compiles one program "
                            f"per shape class; hoist the branch to the "
                            f"host wrapper or pad to a fixed size")


class LockHeldDispatch(ProjectRule):
    """Any path that reaches device dispatch or an RPC while a named
    lock is held — the static complement to utils/lockwatch's runtime
    watchdog, catching the transitive cases lock-blocking (direct calls
    only) cannot see."""

    name = "lock-held-dispatch"
    motivation = ("lockwatch (PR 6) fires at runtime when a dispatch "
                  "already stalled everyone queued on the mutex; this "
                  "catches the same bug in review — a callee that "
                  "reaches jnp dispatch or rpc_call two edges down "
                  "serializes the node just as hard as an inline one")

    def check(self, project: Project) -> None:
        for info in project.functions:
            if info.synthetic:
                continue
            if not (project.ignore_scope
                    or self.applies_to(info.relpath)):
                continue
            fi = project.files[info.relpath]
            for node in _walk_no_nested(info.node):
                if not isinstance(node, ast.With):
                    continue
                held = []
                for it in node.items:
                    ce = it.context_expr
                    base = ce.func if isinstance(ce, ast.Call) else ce
                    nm = base.attr if isinstance(base, ast.Attribute) \
                        else (base.id if isinstance(base, ast.Name)
                              else None)
                    if nm is not None and nm in project.lock_names:
                        held.append(nm)
                if not held:
                    continue
                seen: set = set()
                for stmt in node.body:
                    for inner in [stmt, *_walk_no_nested(stmt)]:
                        if not isinstance(inner, ast.Call) \
                                or inner.lineno in seen:
                            continue
                        fname = inner.func.id \
                            if isinstance(inner.func, ast.Name) else (
                                inner.func.attr
                                if isinstance(inner.func, ast.Attribute)
                                else None)
                        if fname == "rpc_call":
                            continue   # lock-blocking owns direct RPCs
                        if project._is_device_call(inner, fi):
                            seen.add(inner.lineno)
                            project.report(
                                self, info.relpath, inner,
                                f"device dispatch while holding "
                                f"{'/'.join(held)} — one slow compile/"
                                f"transfer stalls every thread queued "
                                f"on the lock; snapshot state, drop "
                                f"the lock, then dispatch")
                            continue
                        callee = project.resolve_call(inner, fi)
                        if callee is None:
                            continue
                        if callee.reaches_device or callee.reaches_rpc:
                            what = "device dispatch" \
                                if callee.reaches_device else "an RPC"
                            seen.add(inner.lineno)
                            project.report(
                                self, info.relpath, inner,
                                f"call to {callee.name}() which reaches "
                                f"{what} while holding "
                                f"{'/'.join(held)} — move the call "
                                f"outside the lock")


class DeadlinePropagation(ProjectRule):
    """A function that accepts a Deadline must thread it into every
    deadline-accepting callee that transitively reaches an RPC —
    dropping it silently re-widens that hop to the 10 s default."""

    name = "deadline-propagation"
    motivation = ("PR 4 deadline plane: the budget shrinks hop by hop "
                  "ONLY if every layer passes it on; one dropped edge "
                  "and a nearly-expired query still burns the full "
                  "default timeout on its next RPC")

    def check(self, project: Project) -> None:
        for info in project.functions:
            if info.synthetic or not info.accepts_deadline:
                continue
            if not (project.ignore_scope
                    or self.applies_to(info.relpath)):
                continue
            dl_names = info.deadline_params
            for call, callee in info.call_sites:
                if callee is None or not callee.accepts_deadline \
                        or not callee.reaches_rpc:
                    continue
                passed = any(kw.arg in callee.deadline_params
                             for kw in call.keywords if kw.arg)
                if not passed:
                    passed = any(
                        _names_in(a) & dl_names
                        for a in list(call.args)
                        + [kw.value for kw in call.keywords])
                if not passed:
                    project.report(
                        self, info.relpath, call,
                        f"{info.name} holds a Deadline but calls "
                        f"{callee.name}() — which reaches an RPC — "
                        f"without threading it; the hop falls back to "
                        f"the default timeout and the budget stops "
                        f"shrinking")


def project_rules() -> list:
    return [HostSync(), RecompileHazard(), LockHeldDispatch(),
            DeadlinePropagation()]
