"""CLI: ``python -m cnosdb_tpu.analysis [paths…] [--json] [--fix-baseline]``.

Exit status: 0 when the tree is clean (no findings beyond the baseline,
no stale baseline cells), 1 otherwise. CI runs this as a tier-1 gate
(tests/test_invariants.py); run it locally before pushing.
"""
from __future__ import annotations

import argparse
import json
import sys

from . import BASELINE_PATH, run, write_baseline


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cnosdb_tpu.analysis",
        description="single-walk AST lint over the cnosdb_tpu invariants")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the whole package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--fix-baseline", action="store_true",
                    help="freeze the current findings as the new baseline "
                         "(ratchet down after fixing debt, or absorb a "
                         "new rule's pre-existing findings)")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline file (default: the package baseline)")
    ap.add_argument("--all-rules", action="store_true",
                    help="ignore per-rule path scoping (fixture testing)")
    args = ap.parse_args(argv)

    rep = run(args.paths or None, baseline_path=args.baseline,
              ignore_scope=args.all_rules)

    if args.fix_baseline:
        if args.paths:
            print("--fix-baseline requires a whole-tree run (no paths)",
                  file=sys.stderr)
            return 2
        write_baseline(rep.counts, args.baseline)
        print(f"baseline rewritten: {len(rep.findings)} finding(s) in "
              f"{len(rep.counts)} (rule, file) cell(s) -> {args.baseline}")
        return 0

    if args.as_json:
        print(json.dumps(rep.as_dict(), indent=1))
        return 0 if rep.ok else 1

    for f in sorted(rep.violations, key=lambda f: (f.path, f.line)):
        print(f.render())
    for rule, path, allowed, found in rep.stale:
        print(f"{path}: [{rule}] baseline stale: {allowed} allowed but "
              f"only {found} found — lock the fix in with --fix-baseline")
    n_base = len(rep.findings) - len(rep.violations)
    if rep.ok:
        print(f"OK: 0 violations ({n_base} baselined finding(s))")
    else:
        print(f"FAIL: {len(rep.violations)} violation(s), "
              f"{len(rep.stale)} stale baseline cell(s) "
              f"({n_base} baselined)")
    return 0 if rep.ok else 1


if __name__ == "__main__":
    sys.exit(main())
