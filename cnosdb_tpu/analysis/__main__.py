"""CLI: ``python -m cnosdb_tpu.analysis [paths…] [--json] [--fix-baseline]
[--changed [REF]] [--callgraph] [--artifact PATH]``.

Exit status: 0 when the tree is clean (no findings beyond the baseline,
no stale baseline cells), 1 otherwise. CI runs this as a tier-1 gate
(tests/test_invariants.py); run it locally before pushing.

``--changed [REF]`` (default HEAD) parses the WHOLE tree — the
interprocedural summaries need every file — but reports findings only
for files touched since REF, so a pre-push check on a big tree reads as
a short diff. ``--callgraph`` dumps the resolved call graph with each
function's summary tags and exits.
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys

from . import (BASELINE_PATH, PKG_PARENT, load_baseline, norm_relpath,
               run, write_baseline)


def _changed_relpaths(ref: str) -> set:
    """Repo-relative .py paths touched since ``ref`` (committed, staged,
    or unstaged) plus untracked ones — the working set a pre-push lint
    cares about."""
    out: set = set()
    for args in (["git", "diff", "--name-only", ref, "--", "*.py"],
                 ["git", "ls-files", "--others", "--exclude-standard",
                  "--", "*.py"]):
        p = subprocess.run(args, capture_output=True, text=True,
                           cwd=PKG_PARENT, timeout=60)
        if p.returncode != 0:
            raise SystemExit(f"--changed: {' '.join(args)} failed: "
                             f"{p.stderr.strip()}")
        out |= {line.strip() for line in p.stdout.splitlines()
                if line.strip()}
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cnosdb_tpu.analysis",
        description="AST + interprocedural dataflow lint over the "
                    "cnosdb_tpu invariants")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the whole package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--fix-baseline", action="store_true",
                    help="freeze the current findings as the new baseline "
                         "(ratchet down after fixing debt, or absorb a "
                         "new rule's pre-existing findings); prunes and "
                         "reports cells whose findings are gone")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline file (default: the package baseline)")
    ap.add_argument("--all-rules", action="store_true",
                    help="ignore per-rule path scoping (fixture testing)")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="report findings only for files touched since "
                         "git REF (default HEAD); the whole tree is "
                         "still parsed so cross-file dataflow stays "
                         "correct")
    ap.add_argument("--callgraph", action="store_true",
                    help="dump the interprocedural call graph + "
                         "per-function summary tags and exit")
    ap.add_argument("--artifact", metavar="PATH", default=None,
                    help="also write the JSON report (including the "
                         "cnosdb_analysis_findings_total gauge) to PATH")
    args = ap.parse_args(argv)

    if args.callgraph:
        from . import ModuleContext, interproc, iter_py_files
        import ast as _ast
        import tokenize as _tokenize

        contexts = []
        for path in iter_py_files(args.paths or None):
            relpath = norm_relpath(path)
            try:
                with _tokenize.open(path) as f:
                    source = f.read()
                tree = _ast.parse(source, filename=path)
            except (SyntaxError, UnicodeDecodeError):
                continue
            contexts.append(ModuleContext(path, relpath, source, tree, []))
        project = interproc.Project(contexts)
        print(project.render_callgraph())
        return 0

    report_filter = None
    if args.changed is not None:
        if args.paths:
            print("--changed analyzes the whole tree; drop the explicit "
                  "paths", file=sys.stderr)
            return 2
        report_filter = _changed_relpaths(args.changed)
        if not report_filter:
            print(f"no python files changed since {args.changed}")
            return 0

    rep = run(args.paths or None, baseline_path=args.baseline,
              ignore_scope=args.all_rules, report_filter=report_filter)

    if args.artifact:
        with open(args.artifact, "w", encoding="utf-8") as f:
            json.dump(rep.as_dict(), f, indent=1)
            f.write("\n")

    if args.fix_baseline:
        if args.paths or report_filter is not None:
            print("--fix-baseline requires a whole-tree run (no paths, "
                  "no --changed)", file=sys.stderr)
            return 2
        old = load_baseline(args.baseline)
        write_baseline(rep.counts, args.baseline)
        kept = {cell for cell, n in rep.counts.items() if n > 0}
        pruned = sorted(set(old) - kept)
        print(f"baseline rewritten: {len(rep.findings)} finding(s) in "
              f"{len(kept)} (rule, file) cell(s) -> {args.baseline}")
        for rule, relpath in pruned:
            print(f"pruned stale cell {rule}:{relpath} "
                  f"(findings no longer exist)")
        if pruned:
            print(f"pruned {len(pruned)} stale cell(s)")
        return 0

    if args.as_json:
        print(json.dumps(rep.as_dict(), indent=1))
        return 0 if rep.ok else 1

    for f in sorted(rep.violations, key=lambda f: (f.path, f.line)):
        print(f.render())
    for rule, path, allowed, found in rep.stale:
        print(f"{path}: [{rule}] baseline stale: {allowed} allowed but "
              f"only {found} found — lock the fix in with --fix-baseline")
    n_base = len(rep.findings) - len(rep.violations)
    if rep.ok:
        print(f"OK: 0 violations ({n_base} baselined finding(s))")
    else:
        print(f"FAIL: {len(rep.violations)} violation(s), "
              f"{len(rep.stale)} stale baseline cell(s) "
              f"({n_base} baselined)")
    return 0 if rep.ok else 1


if __name__ == "__main__":
    sys.exit(main())
