"""Runtime lock-order watchdog (CNOSDB_LOCKWATCH=1).

The AST lint plane (cnosdb_tpu/analysis) catches blocking calls written
*textually* inside a ``with lock:`` block, but it cannot see dynamic
composition — coordinator code that takes engine.lock and then calls a
helper that takes vnode.lock, or an RPC issued three frames below a held
mutex. This module is the runtime complement: an instrumented Lock/RLock
wrapper that records, per thread, the order in which locks nest, folds
every observed (held → acquired) pair into a global lock-order graph, and
reports

  * cycles in that graph (two threads taking A→B and B→A — a potential
    deadlock even if the interleaving never fired in this run),
  * the longest-held locks (ms), and
  * locks held across an RPC hop (``parallel/net.rpc_call`` notes itself
    via :func:`note_blocking` — one slow peer then stalls every thread
    queued on that lock).

Zero-cost when off: the :func:`Lock`/:func:`RLock` factories return plain
``threading`` primitives unless CNOSDB_LOCKWATCH was set at import (or
:func:`enable` was called before the lock was created), so production
paths pay nothing. The chaos and deadline cluster suites switch it on in
every spawned node, making each soak run double as a race/deadlock
detector; ``/debug/lockgraph`` serves :func:`report` and /metrics carries
``cnosdb_lockwatch_*`` counters.
"""
from __future__ import annotations

import os
import threading
import time

_ENABLED = os.environ.get("CNOSDB_LOCKWATCH", "") not in ("", "0", "false")

# Bookkeeping is guarded by one plain (never watched) leaf mutex: it is
# only ever taken *after* a watched lock's inner acquire succeeds, and no
# watched acquire happens under it, so it cannot extend the order graph.
_state = threading.Lock()
_tls = threading.local()

_edges: dict[tuple[str, str], int] = {}     # (held, acquired) → count
_held_max_ms: dict[str, float] = {}          # lock → longest single hold
_across: dict[tuple[str, str], int] = {}     # (lock, blocking op) → count
_counters: dict[str, int] = {
    "watched_locks": 0,      # _Watched instances created
    "acquires": 0,           # non-reentrant acquisitions recorded
    "order_edges": 0,        # unique (held → acquired) pairs seen
    "held_across_blocking": 0,   # note_blocking() hits with locks held
}


def enabled() -> bool:
    return _ENABLED


def enable(flag: bool = True) -> None:
    """Flip instrumentation for locks created *after* this call (tests).
    Locks already handed out keep their nature."""
    global _ENABLED
    _ENABLED = flag


def reset() -> None:
    with _state:
        _edges.clear()
        _held_max_ms.clear()
        _across.clear()
        for k in _counters:
            _counters[k] = 0


def Lock(name: str | None = None):
    """A ``threading.Lock`` — instrumented iff the watchdog is enabled."""
    if not _ENABLED:
        return threading.Lock()
    return _Watched(threading.Lock(), name or _callsite(), reentrant=False)


def RLock(name: str | None = None):
    """A ``threading.RLock`` — instrumented iff the watchdog is enabled."""
    if not _ENABLED:
        return threading.RLock()
    return _Watched(threading.RLock(), name or _callsite(), reentrant=True)


def _callsite() -> str:
    import sys

    f = sys._getframe(2)
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def _held_stack() -> list:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


class _Watched:
    """Instrumented lock: context-manager + acquire/release/locked, plus
    the ``_is_owned``/``_release_save``/``_acquire_restore`` trio so
    ``threading.Condition(watched_lock)`` keeps working (wait() must run
    the same bookkeeping as a plain release/acquire pair)."""

    __slots__ = ("_inner", "name", "_reentrant")

    def __init__(self, inner, name: str, reentrant: bool):
        self._inner = inner
        self.name = name
        self._reentrant = reentrant
        with _state:
            _counters["watched_locks"] += 1

    # ------------------------------------------------------- bookkeeping
    def _note_acquire(self) -> None:
        held = _held_stack()
        reentrant = any(e[0] is self for e in held)
        held.append((self, time.monotonic(), reentrant))
        if reentrant:
            return   # nesting on ourselves adds no ordering information
        with _state:
            _counters["acquires"] += 1
            seen = set()
            for other, _t0, _re in held[:-1]:
                if other is self or other.name in seen:
                    continue
                seen.add(other.name)
                key = (other.name, self.name)
                if key not in _edges:
                    _counters["order_edges"] += 1
                    _edges[key] = 0
                _edges[key] += 1

    def _note_release(self) -> None:
        held = getattr(_tls, "held", None)
        if not held:
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                _me, t0, reentrant = held.pop(i)
                if not reentrant:
                    ms = (time.monotonic() - t0) * 1e3
                    with _state:
                        if ms > _held_max_ms.get(self.name, 0.0):
                            _held_max_ms[self.name] = ms
                return

    # ---------------------------------------------------------- lock API
    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._note_acquire()
        return ok

    def release(self) -> None:
        self._note_release()
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockwatch {self.name} {self._inner!r}>"

    # ------------------------------------- threading.Condition protocol
    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        self._note_release()
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._note_acquire()


def note_blocking(what: str) -> None:
    """Called by known-blocking plumbing (the RPC client) so holds that
    span a network hop show up even though the AST never sees them."""
    if not _ENABLED:
        return
    held = getattr(_tls, "held", None)
    if not held:
        return
    names = {e[0].name for e in held if not e[2]}
    if not names:
        return
    with _state:
        _counters["held_across_blocking"] += 1
        for n in names:
            key = (n, what)
            _across[key] = _across.get(key, 0) + 1


# ------------------------------------------------------------- reporting
def cycles() -> list[list[str]]:
    """Strongly-connected components of the order graph with ≥2 locks
    (or a self-edge): each is a set of locks that some pair of code paths
    acquires in conflicting order — a potential deadlock."""
    with _state:
        adj: dict[str, set] = {}
        for (a, b) in _edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    def strongconnect(root: str):
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1 or (v, v) in _edges:
                    out.append(sorted(comp))

    for node in sorted(adj):
        if node not in index:
            strongconnect(node)
    return sorted(out)


def report() -> dict:
    """The /debug/lockgraph payload."""
    cyc = cycles()
    with _state:
        edges = [{"from": a, "to": b, "count": n}
                 for (a, b), n in sorted(_edges.items())]
        longest = [{"lock": k, "max_held_ms": round(v, 3)}
                   for k, v in sorted(_held_max_ms.items(),
                                      key=lambda kv: -kv[1])[:20]]
        across = [{"lock": a, "op": op, "count": n}
                  for (a, op), n in sorted(_across.items())]
        ctrs = dict(_counters)
    ctrs["order_cycles"] = len(cyc)
    return {"enabled": _ENABLED, "counters": ctrs, "edges": edges,
            "cycles": cyc, "longest_held": longest,
            "held_across_blocking": across}


def counters_snapshot() -> dict[str, int]:
    """Flat ints for the /metrics fold (cnosdb_lockwatch_total{kind=…})."""
    with _state:
        out = dict(_counters)
    out["order_cycles"] = len(cycles())
    return out
