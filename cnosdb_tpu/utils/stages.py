"""Per-stage query timing — the bench instrumentation plane.

`bench.py` enables this around each measured query to report where the
time goes (scan cache hit/miss, TSM decode, kernel, merge, finalize);
disabled it costs one dict lookup per stage() call. Counters accumulate
across threads (the scan fans out on a pool).

Stages recorded by the engine:
  scan_hit / scan_miss  — coordinator scan-snapshot cache counters
  delta_hit             — stale cache entry refreshed by decoding only
                          the new TSM files / memcache rows since its
                          snapshot token (no full rescan)
  delta_rows            — rows decoded by those delta scans (small when
                          the pipeline is healthy; a full rescan's worth
                          means tokens are being invalidated)
  decode_ms             — TSM read+decode (cache-miss and delta scans)
  upload_ms             — host→device column uploads (eager per-column
                          uploads overlapped with decode, plus any
                          residual transfer at DeviceBatch build)
  kernel_ms             — fused segment-aggregate kernels
  merge_ms              — cross-vnode partial merge / device delta-merge
  finalize_ms           — vectorized finalizers + output rendering
  factorize_ms          — group-key factorization (value column →
                          dense codes + dictionary; ~0 on warm
                          ScanToken caches)
  group_count           — output group cardinality per query
  distinct_path.sort    — count(DISTINCT) via host sorted pair codes
  distinct_path.device  — … via the jax segment kernels
  distinct_path.fallback— … via the scalar set fold (unfactorizable)
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from . import lockwatch

_lock = lockwatch.Lock("stages.counters")
_enabled = False
_ms: dict[str, float] = {}
_counts: dict[str, int] = {}
# Error counters are ALWAYS on (unlike timing stages): a swallowed RPC
# handler exception with no counter is invisible in production. Keyed
# "area.method" (e.g. "rpc.write_replica"); surfaced via /metrics.
_errors: dict[str, int] = {}


def enable(flag: bool = True) -> None:
    global _enabled
    _enabled = flag


def reset() -> None:
    with _lock:
        _ms.clear()
        _counts.clear()
        _errors.clear()


def snapshot() -> dict:
    with _lock:
        out = {k: round(v, 2) for k, v in sorted(_ms.items())}
        out.update(sorted(_counts.items()))
        return out


def count(name: str, n: int = 1) -> None:
    if not _enabled:
        return
    with _lock:
        _counts[name] = _counts.get(name, 0) + n


def count_error(name: str, n: int = 1) -> None:
    """Always-on failure counter (not gated on enable())."""
    with _lock:
        _errors[name] = _errors.get(name, 0) + n


def errors_snapshot() -> dict[str, int]:
    with _lock:
        return dict(sorted(_errors.items()))


@contextmanager
def stage(name: str):
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = (time.perf_counter() - t0) * 1e3
        with _lock:
            _ms[name] = _ms.get(name, 0.0) + dt
