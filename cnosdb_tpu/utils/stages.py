"""Per-query stage profiling — the always-on instrumentation plane.

Every `stage()` / `count()` call lands in the *active query's*
:class:`QueryProfile` (a contextvar installed at ingress by the SQL
executor, by `EXPLAIN ANALYZE`, or by bench.py). With no profile in
scope both are a single contextvar read — cheap enough to leave on in
production. Profiles propagate:

  * across the shared scan/decode pools (utils/executor.py re-runs each
    task inside the submitting thread's contextvars.Context), and
  * across RPC hops (parallel/net.py adds a `_profile` marker to the
    payload; the remote handler runs inside its own node-local profile
    and returns it in the reply, where the caller folds it into the
    active profile's `subprofiles`, keyed by node/vnode/method).

Consumers: `EXPLAIN ANALYZE` renders the merged per-stage/per-node
breakdown, HTTP exposes an opt-in summary header plus
`GET /debug/profile?qid=` over the bounded `PROFILES` ring, finished
profiles attach to their root trace span as tags, and the slow-query
log writes threshold-exceeding profiles into usage_schema.

Stage catalog — every *literal* name passed to stage()/count() must
appear in STAGE_CATALOG (enforced by the `stage-catalog` lint rule in
cnosdb_tpu/analysis); dynamically-built names must use a prefix from
DYNAMIC_STAGE_PREFIXES. Keys ending in `_ms` are durations, `_bytes`
byte totals; everything else is a plain count.
"""
from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from contextlib import contextmanager
from . import lockwatch

# The documented profile schema. A name missing here is invisible to
# every dashboard/bench consumer, so the lint plane refuses it.
STAGE_CATALOG: dict[str, str] = {
    "scan_hit": "coordinator scan-snapshot cache hits",
    "scan_miss": "coordinator scan-snapshot cache misses (full decode)",
    "delta_hit": "stale cache entries refreshed by decoding only the "
                 "new TSM files / memcache rows since their token",
    "delta_rows": "rows decoded by delta scans (a full rescan's worth "
                  "means tokens are being invalidated)",
    "decode_ms": "TSM read+decode (cache-miss and delta scans)",
    "device_decode_ms": "batched device codec kernels within a scan "
                        "(the accelerator half of decode_ms)",
    "device_decode_engagements": "pages decoded by the device-decode "
                                 "lane instead of a host lane",
    "upload_ms": "host→device column uploads",
    "upload_bytes": "bytes moved host→device by those uploads",
    "kernel_ms": "fused segment-aggregate kernels",
    "merge_ms": "cross-vnode partial merge / device delta-merge",
    "finalize_ms": "vectorized finalizers + output rendering",
    "factorize_ms": "group-key factorization (values → dense codes)",
    "group_count": "output group cardinality per query",
    "group_spill": "group-by accumulator epochs spilled to disk by the "
                   "memory broker's GroupSpiller (sql/executor.py)",
    "distinct_path.sort": "count(DISTINCT) via host sorted pair codes",
    "distinct_path.device": "count(DISTINCT) via the jax segment kernels",
    "distinct_path.fallback": "count(DISTINCT) via the scalar set fold",
    "pallas_engagements": "aggregations that ran through a Pallas kernel",
    "kernel_cache.hit": "segment-geometry/program cache hits on the "
                        "device batch (compile/derive skipped)",
    "kernel_cache.miss": "segment-geometry/program cache misses "
                         "(derived data rebuilt, jit may recompile)",
    "matview.refresh_ms": "materialized-rollup delta refresh (scan the "
                          "[hwm, watermark) slice + fold + persist)",
    "matview.delta_rows": "raw rows folded into rollup partials by delta "
                          "refreshes (full-history's worth means the "
                          "watermark is not advancing)",
    "matview.hit": "aggregate queries rewritten to read sealed buckets "
                   "from a materialized rollup",
    "matview.miss": "rewrite-eligible aggregate queries no registered "
                    "view subsumed (raw scan)",
    "matview.seed_groups": "accumulator groups seeded from sealed view "
                           "buckets by rewritten queries",
    "ngram_pages_skipped": "string pages pruned before decode by trigram "
                           "signatures (ops/strkernels)",
    "compressed_ms": "compressed-domain lane: page classification + "
                     "closed-form jobs (storage/compressed_domain)",
    "compressed.pages_answered": "pages whose aggregate contribution "
                                 "came from stats/closed forms — never "
                                 "decoded into rows",
    "compressed.pages_skipped": "pages proven predicate-false from "
                                "encoded form — zero bytes touched",
    "compressed.pages_masked": "pages filtered in code space (dict/"
                               "bitpack masks) — only survivors gather",
    "compressed.bytes_avoided": "page bytes the compressed-domain lane "
                                "kept out of every decode lane",
    "compressed.bytes_materialized": "page bytes that DID enter a decode "
                                     "lane (the ≥5× drop the lane exists "
                                     "to produce on selective scans)",
    "topk.host": "ORDER BY+LIMIT answered by np.partition select-then-"
                 "gather instead of a full sort",
    "topk.device": "ORDER BY+LIMIT thresholds computed by jax.lax.top_k",
    "topk.declined": "ORDER BY+LIMIT shapes outside the top-k fast path "
                     "(nulls/NaN/object keys, k≥n) — full sort",
    "cold.fetch_ms": "ranged object-store GETs for cold-tier pages "
                     "(storage/tiering.py fetch_pages)",
    "cold.range_gets": "coalesced byte-range requests issued to the "
                       "object store by cold scans",
    "cold.pages_fetched": "cold pages whose bytes were downloaded "
                          "(cache misses after pruning)",
    "cold.bytes_downloaded": "bytes fetched from the object store by "
                             "cold scans (vs. bytes the pages span)",
    "cold.pages_pruned": "cold pages eliminated locally by sidecar zone "
                         "maps/constraints — zero bytes downloaded",
    "chaos.checks": "consistency-checker verdicts evaluated by the "
                    "nemesis plane (chaos/checker.py)",
    "chaos.crash_sites": "crash-point sweep runs executed — one per "
                         "(fault point, nth crossing) pair",
    "chaos.mttr_ms": "crash→first-successful-read recovery time measured "
                     "by chaos workload verify",
    "serving.plan_hit": "SELECTs answered from a cached analyzed plan "
                        "(parse+analyze+plan all skipped)",
    "serving.plan_rebind": "template fingerprint hits re-bound with new "
                           "literal params (parse+analyze skipped, "
                           "plan_select re-run)",
    "serving.plan_miss": "fingerprintable SELECTs that paid a full "
                         "parse+analyze+plan (then seeded the cache)",
    "serving.result_hit": "SELECTs answered from the ScanToken-validated "
                          "result cache (engine untouched)",
    "serving.result_miss": "result-cache probes whose entry was absent "
                           "or token-stale",
    "serving.result_bypass": "executed SELECTs whose result was not "
                             "cacheable (system/relational path, remote "
                             "vnodes, oversized result)",
    "serving.fused": "point queries executed inside a fused micro-batch "
                     "(shared scan + stacked filter masks)",
    "serving.solo": "batchable point queries that ran alone (no gate "
                    "pressure, or the window closed empty)",
    "serving.fused_scan_ms": "shared scan wall time paid once per fused "
                             "batch (booked to the leader's profile)",
    "serving.remote_fp": "scan_vnode RPCs carrying a serving-plane "
                         "fingerprint (cluster-wide cache attribution)",
    "serving.fused_hedges": "hedged scan attempts fired during a fused "
                            "micro-batch's shared scan (booked to the "
                            "leader; process-wide delta, so concurrent "
                            "queries' hedges can bleed in)",
    "mesh.plan_ms": "mesh exec lane: global segment/label layout + "
                    "shard-major staging (ops/mesh_exec._build_prep)",
    "mesh.upload_ms": "mesh exec lane: sharded host→device uploads "
                      "(NamedSharding over the shard axis)",
    "mesh.collective_ms": "mesh exec lane: collective merge programs — "
                          "per-shard partials folded over the mesh in "
                          "batch order (distributed_agg.mesh_merge_"
                          "kernel) + the replicated-result fetch",
    "mesh.assemble_ms": "mesh exec lane: merged partials → the legacy "
                        "vec-merge AggResult shape",
    "mesh.plan_cache_hit": "mesh prep cache hits — sharded operands "
                           "reused from the lead batch (warm repeats "
                           "skip layout + upload)",
    "mesh.plan_cache_miss": "mesh prep cache misses (layout + sharded "
                            "upload rebuilt)",
    "mesh.rows": "rows aggregated through the mesh lane per query",
    "mesh.shards": "mesh devices participating in the collective merge",
    "hedge.fired": "hedged scan attempts launched at a next-ranked "
                   "replica after the adaptive p95 trigger elapsed",
    "hedge.won": "scans answered by a hedge attempt instead of the "
                 "primary (the tail the plane exists to cut)",
    "hedge.cancelled": "losing hedge/primary attempts cancelled through "
                       "the cancel_scan(qid) fan-out after a winner",
    "hedge.suppressed": "hedge triggers that elapsed without firing "
                        "(limiter / no budget / no alternate — proves "
                        "hedging stays tail-only)",
}

# Prefixes for names composed at runtime (skipped by the literal lint
# check but still part of the documented schema):
#   rpc_<method>_ms — server-side wall time of one RPC handler dispatch
#   string_path.<path> — string predicates per strkernels lane
#     (per_unique / ngram_skip / host_fallback)
DYNAMIC_STAGE_PREFIXES = ("rpc_", "string_path.")

_profile: contextvars.ContextVar = contextvars.ContextVar(
    "cnos_query_profile", default=None)

# Error counters are ALWAYS on and process-global (unlike stages): a
# swallowed RPC handler exception with no counter is invisible in
# production. Keyed "area.method" (e.g. "rpc.write_replica"); surfaced
# via /metrics.
_err_lock = lockwatch.Lock("stages.errors")
_errors: dict[str, int] = {}


class QueryProfile:
    """Stage timings/counters + device telemetry for ONE query.

    Thread-safe: scan/decode pool workers and RPC reply threads all
    accumulate into the submitting query's profile concurrently. The
    lock is a plain leaf mutex (never held across any other acquire).
    """

    __slots__ = ("qid", "sql", "trace_id", "node_id", "started_at",
                 "wall_ms", "error", "ms", "counts", "device",
                 "subprofiles", "_lock")

    def __init__(self, qid: str | None = None, node_id=None,
                 sql: str | None = None):
        self.qid = qid
        self.sql = sql
        self.trace_id: str | None = None
        self.node_id = node_id
        self.started_at = time.time()
        self.wall_ms: float | None = None
        self.error: str | None = None
        self.ms: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self.device: dict = {}
        # remote per-node sub-profiles: [{node, addr, method, vnode,
        # ms, counts}, ...] — appended by net.rpc_call as replies land
        self.subprofiles: list[dict] = []
        self._lock = threading.Lock()

    # ------------------------------------------------------- accumulation
    def add_ms(self, name: str, dt_ms: float) -> None:
        with self._lock:
            self.ms[name] = self.ms.get(name, 0.0) + dt_ms

    def add_count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counts[name] = self.counts.get(name, 0) + n

    def merge_remote(self, entry: dict) -> None:
        """Fold one remote node's wire sub-profile in (keyed by
        node/vnode/method — the coordinator-side merge keeps them
        separate so EXPLAIN ANALYZE can attribute per node)."""
        with self._lock:
            self.subprofiles.append(entry)

    def merge_child(self, child: "QueryProfile") -> None:
        """Fold a nested profile (e.g. EXPLAIN ANALYZE's inner query)
        into this one so its stages aren't lost to the outer scope."""
        with child._lock:
            ms = dict(child.ms)
            counts = dict(child.counts)
            subs = list(child.subprofiles)
        with self._lock:
            for k, v in ms.items():
                self.ms[k] = self.ms.get(k, 0.0) + v
            for k, v in counts.items():
                self.counts[k] = self.counts.get(k, 0) + v
            self.subprofiles.extend(subs)

    # ---------------------------------------------------------- rendering
    def snapshot(self) -> dict:
        """Local stage map, bench wire shape: rounded `*_ms` floats
        merged with integer counters, sorted by key (the format BENCH_r*
        `stages_warm`/`stages_cold` fields have always used)."""
        with self._lock:
            out = {k: round(v, 2) for k, v in sorted(self.ms.items())}
            out.update(sorted(self.counts.items()))
            return out

    def to_wire(self) -> dict:
        """Compact reply-envelope form for the RPC plane."""
        with self._lock:
            return {"node": self.node_id,
                    "ms": {k: round(v, 3) for k, v in self.ms.items()},
                    "counts": dict(self.counts)}

    def node_stages(self) -> dict[str, dict]:
        """Merged per-node view: node label → {stage: value}. Local
        stages land under this profile's node id; each remote
        sub-profile folds into its originating node's cell."""
        local = str(self.node_id) if self.node_id is not None else "local"
        with self._lock:
            out: dict[str, dict] = {local: {}}
            for k, v in self.ms.items():
                out[local][k] = round(out[local].get(k, 0.0) + v, 3)
            for k, v in self.counts.items():
                out[local][k] = out[local].get(k, 0) + v
            for sub in self.subprofiles:
                node = sub.get("node")
                label = str(node) if node is not None \
                    else str(sub.get("addr", "remote"))
                cell = out.setdefault(label, {})
                for k, v in (sub.get("ms") or {}).items():
                    cell[k] = round(cell.get(k, 0.0) + v, 3)
                for k, v in (sub.get("counts") or {}).items():
                    cell[k] = cell.get(k, 0) + v
            return out

    def stage_totals(self) -> dict:
        """Cluster-wide totals: every node's stages summed per name."""
        totals: dict = {}
        for cell in self.node_stages().values():
            for k, v in cell.items():
                totals[k] = round(totals.get(k, 0) + v, 3)
        return totals

    def to_dict(self) -> dict:
        with self._lock:
            return {"qid": self.qid, "sql": self.sql,
                    "trace_id": self.trace_id, "node_id": self.node_id,
                    "started_at": self.started_at, "wall_ms": self.wall_ms,
                    "error": self.error,
                    "ms": {k: round(v, 3) for k, v in sorted(self.ms.items())},
                    "counts": dict(sorted(self.counts.items())),
                    "device": dict(self.device),
                    "subprofiles": [dict(s) for s in self.subprofiles]}

    # ---------------------------------------------------------- lifecycle
    def finish(self, wall_ms: float | None = None,
               error: str | None = None) -> "QueryProfile":
        """Stamp wall time + device telemetry. Captures only from
        modules that are ALREADY imported — finishing a profile must
        never drag the jax stack in on a cold text-only query."""
        import sys

        if wall_ms is not None:
            self.wall_ms = round(wall_ms, 3)
        if error is not None:
            self.error = error
        pk = sys.modules.get("cnosdb_tpu.ops.pallas_kernels")
        if pk is None and "cnosdb_tpu.ops.kernels" in sys.modules:
            # the jax kernel stack is already resident (this query ran
            # aggregates), so the pallas module itself is a cheap import
            try:
                from ..ops import pallas_kernels as pk
            except Exception:  # telemetry stamp must never fail the query
                pk = None
        if pk is not None:
            try:
                self.device["pallas_enabled"] = pk.enabled()
                self.device["pallas_disabled_reason"] = pk.disabled_reason()
            except Exception:  # telemetry stamp must never fail the query
                pass
        dd = sys.modules.get("cnosdb_tpu.ops.device_decode")
        if dd is not None:
            try:
                self.device["device_decode_enabled"] = dd.enabled()
                self.device["device_decode_disabled_reason"] = \
                    dd.disabled_reason()
            except Exception:  # telemetry stamp must never fail the query
                pass
        return self


def current_profile() -> QueryProfile | None:
    return _profile.get()


class profile_scope:
    """Install `profile` as the active query profile for the block
    (None clears the scope — e.g. background work inside a request
    that must not bill to it)."""

    __slots__ = ("profile", "_token")

    def __init__(self, profile: QueryProfile | None):
        self.profile = profile
        self._token = None

    def __enter__(self):
        self._token = _profile.set(self.profile)
        return self.profile

    def __exit__(self, *exc):
        if self._token is not None:
            _profile.reset(self._token)
        return False


class ProfileRing:
    """Bounded ring of recently finished profiles (dict snapshots),
    queryable by qid — the trace collector's shape, applied to
    profiles so `GET /debug/profile?qid=` works after the fact."""

    def __init__(self, capacity: int = 256):
        self._ring: deque = deque(maxlen=capacity)
        self._lock = lockwatch.Lock("stages.profile_ring")

    def record(self, profile: QueryProfile) -> None:
        with self._lock:
            self._ring.append(profile.to_dict())

    def get(self, qid: str) -> dict | None:
        with self._lock:
            for d in reversed(self._ring):
                if d.get("qid") == str(qid):
                    return d
        return None

    def recent(self, limit: int = 50) -> list[dict]:
        with self._lock:
            out = list(self._ring)[-limit:]
        return [{"qid": d.get("qid"), "sql": d.get("sql"),
                 "trace_id": d.get("trace_id"), "wall_ms": d.get("wall_ms"),
                 "started_at": d.get("started_at"), "error": d.get("error")}
                for d in out]


PROFILES = ProfileRing()


# --------------------------------------------------------------- recording
@contextmanager
def stage(name: str):
    prof = _profile.get()
    if prof is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        prof.add_ms(name, (time.perf_counter() - t0) * 1e3)


def count(name: str, n: int = 1) -> None:
    prof = _profile.get()
    if prof is not None:
        prof.add_count(name, n)


def count_error(name: str, n: int = 1) -> None:
    """Always-on process-global failure counter (never profile-scoped)."""
    with _err_lock:
        _errors[name] = _errors.get(name, 0) + n


def errors_snapshot() -> dict[str, int]:
    with _err_lock:
        return dict(sorted(_errors.items()))


def reset() -> None:
    """Clear the process-global error counters (test isolation)."""
    with _err_lock:
        _errors.clear()
