"""Process-wide scan/decode thread pools.

The coordinator used to build a ThreadPoolExecutor per scan_table call —
pool construction and teardown on every query, and no global view of how
many decode threads are live. These two pools are created lazily, once
per process, and sized from config (`[query] scan_executor_threads` /
`decode_executor_threads`, env `CNOSDB_QUERY_*`, 0 = auto):

  "scan"   — coordinator vnode fan-out (one task per PlacedSplit)
  "decode" — per-(file, column) native page-decode tasks inside
             storage/scan._scan_vnode_native

They are deliberately SEPARATE: decode tasks are submitted from inside
scan tasks, and a single shared pool would deadlock once every thread is
a scan waiting on decode futures that can never be scheduled.

Active-task counts are exported to /metrics (cnosdb_scan_executor_active)
so decode-thread saturation is observable.
"""
from __future__ import annotations

import contextvars
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FuturesTimeout

from . import deadline as deadline_mod
from . import lockwatch

_lock = lockwatch.Lock("executor.pools")
_pools: dict[str, ThreadPoolExecutor] = {}
_sizes: dict[str, int] = {}
_active: dict[str, int] = {"scan": 0, "decode": 0}
# config-provided sizes (set once at server boot); env overrides still win
_configured: dict[str, int] = {}

_ENV = {"scan": "CNOSDB_QUERY_SCAN_EXECUTOR_THREADS",
        "decode": "CNOSDB_QUERY_DECODE_EXECUTOR_THREADS"}


def configure(query_cfg) -> None:
    """Adopt pool sizes from a QueryConfig. Only affects pools not yet
    created (first submission wins — pools are process-lifetime)."""
    with _lock:
        _configured["scan"] = int(getattr(
            query_cfg, "scan_executor_threads", 0) or 0)
        _configured["decode"] = int(getattr(
            query_cfg, "decode_executor_threads", 0) or 0)


def _auto_size(name: str) -> int:
    ncpu = os.cpu_count() or 1
    # scan fan-out keeps the historical cap of 8 concurrent vnode scans;
    # the decode pool covers the cores so per-column tasks can fill them
    return min(8, ncpu) if name == "scan" else max(2, ncpu)


def _pool(name: str) -> ThreadPoolExecutor:
    with _lock:
        ex = _pools.get(name)
        if ex is None:
            size = int(os.environ.get(_ENV[name], "0") or 0) \
                or _configured.get(name, 0) or _auto_size(name)
            ex = _pools[name] = ThreadPoolExecutor(
                max_workers=size, thread_name_prefix=f"cnosdb-{name}")
            _sizes[name] = size
        return ex


def submit(name: str, fn, *args):
    """Submit to the named shared pool with active-task accounting.

    The submitting thread's request deadline (if any) is captured here
    and re-installed in the worker, so deadline/cancel state crosses the
    pool boundary; its contextvars (active trace span, query profile)
    are captured as a Context and the task runs inside it, so a child
    span started in a pool worker keeps the submitting query's trace_id
    and stage timings land in that query's profile — cross-thread
    contextvar loss is the classic silent failure here. Shed-before-run:
    a task whose request is already dead by the time a worker picks it
    up raises instead of executing — queued column decodes for an
    expired scan never start."""
    dl = deadline_mod.current()
    ctx = contextvars.copy_context()

    def run():
        with _lock:
            _active[name] += 1
        try:
            if dl is not None:
                if dl.dead():
                    deadline_mod.bump("tasks_shed")
                    dl.check()
                with deadline_mod.scope(dl):
                    return ctx.run(fn, *args)
            return ctx.run(fn, *args)
        finally:
            with _lock:
                _active[name] -= 1
    return _pool(name).submit(run)


def run_all(name: str, fn, items: list) -> list:
    """Run fn over items on the named pool, results in item order.
    Exceptions propagate (matching the executor.map the scan used).

    With a request deadline in scope, the wait polls so a kill/expiry
    unblocks the caller promptly even while a worker is still stuck in
    a remote read (the worker itself is bounded by its capped socket
    timeout and its own shed checks)."""
    futures = [submit(name, fn, it) for it in items]
    dl = deadline_mod.current()
    if dl is None:
        return [f.result() for f in futures]
    out = []
    try:
        for f in futures:
            while True:
                try:
                    out.append(f.result(timeout=0.05))
                    break
                except _FuturesTimeout:
                    dl.check()
        return out
    finally:
        # a raise above abandons the remaining futures; cancel whatever
        # has not started so shed accounting stays truthful
        if len(out) != len(futures):
            for f in futures:
                f.cancel()


def pool_size(name: str) -> int:
    _pool(name)
    with _lock:
        return _sizes[name]


def active_counts() -> dict[str, int]:
    with _lock:
        return dict(_active)


def pool_sizes() -> dict[str, int]:
    """Sizes of pools that exist (no side effect of creating them)."""
    with _lock:
        return dict(_sizes)
