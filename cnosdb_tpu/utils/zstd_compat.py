"""zstandard import gate with a zlib fallback.

The storage layer (tsm.py, codecs.py) wants zstd, but the dependency may
be absent in slim environments. Rather than failing at import (which
takes the whole engine — and every test that touches it — down), fall
back to zlib behind the same two-class API surface the callers use.

The fallback is NOT wire-compatible with real zstd: files written with
one cannot be read with the other. That is fine for self-contained
deployments/tests (the only situation where zstandard is missing); the
chosen codec is a process-lifetime constant, so a single store never
mixes frames.
"""
from __future__ import annotations

try:
    import zstandard
except ImportError:  # slim environment: gate, don't crash the engine
    import zlib as _zlib

    class _Compressor:
        def __init__(self, level: int = 3):
            self._level = min(max(int(level), 1), 9)

        def compress(self, data: bytes) -> bytes:
            return _zlib.compress(data, self._level)

    class _Decompressor:
        def decompress(self, data: bytes) -> bytes:
            return _zlib.decompress(data)

    class zstandard:  # type: ignore[no-redef]  # namespace stand-in
        ZstdCompressor = _Compressor
        ZstdDecompressor = _Decompressor
