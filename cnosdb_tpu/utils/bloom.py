"""Series-id bloom filter stored in TSM footers.

Role mirrors the reference's 1 MiB series bloom in the TSM footer
(common/utils/src/bloom_filter.rs, tskv/src/tsm/footer.rs:30-80), used by
`ColumnFile::maybe_contains_series_id` to prune files per series before
opening them. Ours uses k=4 double-hashing (BKDR + FNV-1a) over a
power-of-two bit array, with numpy batch insert/query since series ids
arrive as arrays.
"""
from __future__ import annotations

import numpy as np

from .hash import bkdr_hash, fnv1a_64

_K = 4


def _hash_u64_batch(vs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized (BKDR, FNV-1a|1) over the 8 little-endian bytes of each
    u64 — bit-identical to the scalar `bkdr_hash`/`fnv1a_64` on the same
    bytes, so batch and single-item probes agree."""
    b = vs.reshape(-1, 1).view(np.uint8).reshape(len(vs), 8)
    seed = np.uint64(1313)
    h1 = np.zeros(len(vs), dtype=np.uint64)
    h2 = np.full(len(vs), 0xCBF29CE484222325, dtype=np.uint64)
    prime = np.uint64(0x100000001B3)
    with np.errstate(over="ignore"):
        for i in range(8):
            col = b[:, i].astype(np.uint64)
            h1 = h1 * seed + col
            h2 = (h2 ^ col) * prime
    return h1, h2 | np.uint64(1)


def _pow2(m: int) -> int:
    p = 1
    while p < m:
        p <<= 1
    return p


class BloomFilter:
    DEFAULT_BITS = 1 << 18  # 32 KiB per file; series-id cardinality per vnode file is modest

    def __init__(self, m_bits: int = DEFAULT_BITS):
        m = _pow2(max(8, m_bits))
        self._bits = np.zeros(m >> 3, dtype=np.uint8)
        self._mask = m - 1

    # -- single-item API -------------------------------------------------
    def insert(self, data: bytes) -> None:
        for loc in self._locations(data):
            self._bits[loc >> 3] |= np.uint8(1 << (loc & 7))

    def maybe_contains(self, data: bytes) -> bool:
        return all(
            self._bits[loc >> 3] & (1 << (loc & 7)) for loc in self._locations(data)
        )

    # -- u64-id API (series ids) ----------------------------------------
    def insert_u64(self, v: int) -> None:
        self.insert(int(v).to_bytes(8, "little"))

    def maybe_contains_u64(self, v: int) -> bool:
        return self.maybe_contains(int(v).to_bytes(8, "little"))

    def insert_u64_batch(self, vs: np.ndarray) -> None:
        h1, h2 = _hash_u64_batch(np.asarray(vs, dtype=np.uint64))
        mask = np.uint64(self._mask)
        for i in range(_K):
            locs = ((h1 + np.uint64(i) * h2) & mask).astype(np.int64)
            np.bitwise_or.at(self._bits, locs >> 3,
                             (np.uint8(1) << (locs & 7).astype(np.uint8)))

    def maybe_contains_u64_batch(self, vs: np.ndarray) -> np.ndarray:
        h1, h2 = _hash_u64_batch(np.asarray(vs, dtype=np.uint64))
        mask = np.uint64(self._mask)
        out = np.ones(len(h1), dtype=bool)
        for i in range(_K):
            locs = ((h1 + np.uint64(i) * h2) & mask).astype(np.int64)
            out &= (self._bits[locs >> 3] >> (locs & 7).astype(np.uint8)) & 1 > 0
        return out

    def _locations_u64(self, v: int):
        return self._locations(int(v).to_bytes(8, "little"))

    def _locations(self, data: bytes):
        h1 = bkdr_hash(data)
        h2 = fnv1a_64(data) | 1
        for i in range(_K):
            yield ((h1 + i * h2) & 0xFFFFFFFFFFFFFFFF) & self._mask

    # -- serialization ---------------------------------------------------
    def to_bytes(self) -> bytes:
        return self._bits.tobytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "BloomFilter":
        bf = cls.__new__(cls)
        arr = np.frombuffer(data, dtype=np.uint8).copy()
        m = _pow2(len(arr)) if len(arr) else 1
        if m != len(arr):
            arr = np.concatenate([arr, np.zeros(m - len(arr), dtype=np.uint8)])
        bf._bits = arr
        bf._mask = (len(arr) << 3) - 1
        return bf

    def __eq__(self, other) -> bool:
        return isinstance(other, BloomFilter) and np.array_equal(self._bits, other._bits)
