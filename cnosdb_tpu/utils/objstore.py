"""Object stores for external tables and COPY (s3 / gcs / azblob / local).

Counterpart of the reference's object_store wiring
(query_server/spi/src/query/datasource/{s3,gcs,azure}.rs and
logical_planner.rs:835-980 parse_connection_options): the same URI
schemes, option names and defaults, implemented directly over HTTP with
stdlib auth primitives — AWS SigV4 request signing, Azure SharedKey, and
GCS OAuth2 service-account JWTs — instead of binding a vendored SDK.
Endpoint overrides (`endpoint_url`, `gcs_base_url`, `use_emulator`) point
the stores at minio/fake-gcs/azurite-style emulators, which is also how
the test suite drives every code path without network egress.
"""
from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import html
import json
import os
import re
import time
import urllib.error
import urllib.parse
import urllib.request
import zlib

from ..errors import CnosError
from .. import faults
from . import deadline as _deadline
from .backoff import Backoff


class ObjectStoreError(CnosError):
    pass


faults.register_point("objstore.get", __name__,
                      desc="object download / ranged GET")
faults.register_point("objstore.put", __name__,
                      desc="object upload")


# ---------------------------------------------------------------------------
# URI handling
# ---------------------------------------------------------------------------
_SCHEMES = ("s3", "gcs", "azblob")


def parse_uri(uri: str) -> tuple[str, str | None, str]:
    """'s3://bucket/a/b.csv' → ('s3', 'bucket', 'a/b.csv'); plain paths and
    file:// URIs → ('local', None, path). Mirrors UriSchema + bucket split
    (reference logical_planner.rs:836-858)."""
    p = urllib.parse.urlparse(uri)
    scheme = p.scheme.lower()
    if scheme in ("", "file"):
        return "local", None, (p.path if scheme == "file" else uri)
    if scheme not in _SCHEMES:
        raise ObjectStoreError(f"unsupported url schema [{scheme}]")
    if not p.netloc:
        raise ObjectStoreError("lost bucket in url")
    return scheme, p.netloc, p.path.lstrip("/")


def store_for(uri: str, options: dict | None = None):
    """→ (store, key). Options use the reference's CONNECTION names."""
    scheme, bucket, key = parse_uri(uri)
    opts = {k.lower(): v for k, v in (options or {}).items()}
    if scheme == "local":
        return LocalStore(), key
    if scheme == "s3":
        return S3Store(
            bucket,
            region=opts.get("region", "us-east-1"),
            endpoint_url=opts.get("endpoint_url"),
            access_key_id=opts.get("access_key_id"),
            secret_key=opts.get("secret_key"),
            token=opts.get("token"),
            virtual_hosted_style=_boolish(
                opts.get("virtual_hosted_style", True)),
        ), key
    if scheme == "gcs":
        return GcsStore(
            bucket,
            gcs_base_url=opts.get("gcs_base_url"),
            disable_oauth=_boolish(opts.get("disable_oauth", False)),
            client_email=opts.get("client_email"),
            private_key=opts.get("private_key"),
        ), key
    return AzblobStore(
        bucket,
        account=opts.get("account"),
        access_key=opts.get("access_key"),
        bearer_token=opts.get("bearer_token"),
        use_emulator=_boolish(opts.get("use_emulator", False)),
        endpoint_url=opts.get("endpoint_url"),
    ), key


def read_uri(uri: str, options: dict | None = None) -> bytes:
    store, key = store_for(uri, options)
    return store.get(key)


def open_source(uri: str, options: dict | None = None):
    """→ something pyarrow readers accept: the local path itself, or a
    BytesIO of the fetched object for remote schemes. One parse, one
    fetch — the shared read-side entry for external tables and COPY."""
    import io

    scheme, _bucket, key = parse_uri(uri)
    if scheme == "local":
        return key if uri.startswith("file:") else uri
    return io.BytesIO(read_uri(uri, options))


def write_uri(uri: str, data: bytes, options: dict | None = None) -> None:
    store, key = store_for(uri, options)
    store.put(key, data)


def is_remote(uri: str) -> bool:
    return parse_uri(uri)[0] != "local"


def _boolish(v) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "t")
    return bool(v)


# HTTP statuses worth retrying: throttles, transient server errors, and
# request-timeout — anything else (403, 404, 412 …) is a caller bug or a
# permanent condition where a retry just burns the deadline budget.
_RETRYABLE_HTTP = frozenset({408, 429, 500, 502, 503, 504})


def _retries() -> int:
    try:
        return max(0, int(os.environ.get("CNOSDB_OBJSTORE_RETRIES", "4")))
    except ValueError:
        return 4


def _deadline_expiry() -> float | None:
    dl = _deadline.current()
    return dl.expires_at if dl is not None else None


def _apply_body_fault(hit, body: bytes) -> bytes:
    """Site implementation for response-body faults on `objstore.get`:
    ``torn(n)`` keeps only the first n bytes (a connection cut mid-stream
    that the transport didn't surface), ``corrupt(n)`` XOR-flips n bytes
    mid-body (bit rot in the object store) — both invisible until a page
    CRC check walks over them."""
    if hit is None or not body:
        return body
    action, arg = hit
    if action == "torn":
        keep = int(arg) if arg else len(body) // 2
        return body[:max(0, min(len(body), keep))]
    if action == "corrupt":
        n = max(1, int(arg or 1))
        off = zlib.crc32(body[:64]) % max(1, len(body) - n + 1)
        return (body[:off] + bytes(b ^ 0xFF for b in body[off:off + n])
                + body[off + n:])
    return body


def _http_status(method: str, url: str, headers: dict, body: bytes | None,
                 timeout: float = 30.0,
                 fault_point: str | None = None,
                 **fault_ctx) -> tuple[int, bytes]:
    """One store call with jittered-backoff retries and deadline-capped
    per-attempt timeouts → (status, body). Transient failures (URLError,
    throttle/5xx statuses, injected faults) retry until the attempt budget
    or the ambient request deadline runs out; permanent HTTP errors raise
    immediately."""
    bo = Backoff(initial=0.05, cap=2.0)
    attempts = _retries() + 1
    last: Exception | None = None
    for attempt in range(attempts):
        per_try = _deadline.cap_current(timeout)
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers)
        try:
            hit = None
            if faults.ENABLED and fault_point:
                hit = faults.fire(fault_point, method=method, url=url,  # lint: disable=fault-site-coverage (point is the caller's literal; objstore.get/put registered above)
                                  **fault_ctx)
                if hit is not None and hit[0] == "drop":
                    raise urllib.error.URLError("injected response drop")
            with urllib.request.urlopen(req, timeout=per_try) as r:
                return r.status, _apply_body_fault(hit, r.read())
        except faults.FaultInjected as e:
            last = e
        except urllib.error.HTTPError as e:
            detail = e.read()[:300]
            last = ObjectStoreError(
                f"{method} {url} → HTTP {e.code}: {detail!r}")
            if e.code not in _RETRYABLE_HTTP:
                raise last
        except urllib.error.URLError as e:
            last = ObjectStoreError(f"{method} {url} failed: {e.reason}")
        except TimeoutError:
            last = ObjectStoreError(f"{method} {url} timed out after "
                                    f"{per_try:.1f}s")
        if attempt + 1 >= attempts or not bo.sleep(_deadline_expiry()):
            break
    raise ObjectStoreError(
        f"{method} {url} failed after {attempts} attempts: {last}")


def _http(method: str, url: str, headers: dict, body: bytes | None,
          timeout: float = 30.0, fault_point: str | None = None,
          **fault_ctx) -> bytes:
    return _http_status(method, url, headers, body, timeout,
                        fault_point=fault_point, **fault_ctx)[1]


def _range_header(offset: int, length: int) -> str:
    return f"bytes={offset}-{offset + length - 1}"


def _xml_texts(tag: str, body: bytes) -> list[str]:
    """Text of every <tag>…</tag> in a listing response. The list XML
    bodies are flat (no attributes on these elements, text-only
    content), so a scan beats dragging in a namespace-aware parser."""
    return [html.unescape(m) for m in
            re.findall(rf"<{tag}>([^<]*)</{tag}>",
                       body.decode("utf-8", "replace"))]


def _xml_text(tag: str, body: bytes) -> str | None:
    hits = _xml_texts(tag, body)
    return hits[0] if hits and hits[0] else None


def _delete_listed(store, prefix: str) -> int:
    """Shared delete_prefix: page through list_prefix, delete each key
    (every request rides the per-call retry/deadline path). → keys
    deleted."""
    keys = store.list_prefix(prefix)
    for k in keys:
        store.delete(k)
    return len(keys)


def _slice_range(status: int, body: bytes, offset: int, length: int) -> bytes:
    """Normalize a ranged GET: 206 bodies are the requested window; a
    server that ignored Range answers 200 with the whole object, which we
    slice locally so callers always see at most `length` bytes."""
    if status == 206:
        return body[:length]
    return body[offset:offset + length]


# ---------------------------------------------------------------------------
# local
# ---------------------------------------------------------------------------
class LocalStore:
    """Filesystem-backed store. Carries the same fault sites and retry
    semantics as the HTTP stores so chaos suites and the cold tier behave
    identically against a local "bucket" (how the tests and benches run
    without network egress)."""

    def _retrying(self, fn, fault_point: str, key: str):
        bo = Backoff(initial=0.05, cap=2.0)
        attempts = _retries() + 1
        last: Exception | None = None
        for attempt in range(attempts):
            _deadline.check_current()
            try:
                hit = None
                if faults.ENABLED:
                    hit = faults.fire(fault_point, key=key, store="local")  # lint: disable=fault-site-coverage (point is the caller's literal; objstore.get/put registered above)
                return fn(hit)
            except FileNotFoundError:
                raise            # permanent: retrying cannot conjure the key
            except OSError as e:
                last = e
            if attempt + 1 >= attempts or not bo.sleep(_deadline_expiry()):
                break
        raise ObjectStoreError(
            f"local {key} failed after {attempts} attempts: {last}")

    def get(self, key: str) -> bytes:
        def fn(hit):
            with open(key, "rb") as f:
                return _apply_body_fault(hit, f.read())
        return self._retrying(fn, "objstore.get", key)

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        def fn(hit):
            with open(key, "rb") as f:
                f.seek(offset)
                return _apply_body_fault(hit, f.read(length))
        return self._retrying(fn, "objstore.get", key)

    def put(self, key: str, data: bytes) -> None:
        def fn(hit):
            d = os.path.dirname(key)
            if d:
                os.makedirs(d, exist_ok=True)
            body = data
            if hit is not None and hit[0] == "torn":
                keep = int(hit[1]) if hit[1] else len(data) // 2
                body = data[:keep]
            with open(key, "wb") as f:
                f.write(body)
            if body is not data:
                raise ObjectStoreError(f"local {key}: torn write injected")
        return self._retrying(fn, "objstore.put", key)

    def delete(self, key: str) -> None:
        def fn(hit):
            try:
                os.unlink(key)
            except FileNotFoundError:
                pass   # idempotent delete, like the HTTP stores' 404
        return self._retrying(fn, "objstore.put", key)

    def list_prefix(self, prefix: str) -> list[str]:
        """Every key (filesystem path) under `prefix`, sorted. Local keys
        ARE paths, so the walk root is the prefix's directory component
        and matching is a plain string-prefix test — same contract as the
        HTTP stores' paginated listings."""
        def fn(hit):
            base = prefix if os.path.isdir(prefix) \
                else os.path.dirname(prefix)
            if not base or not os.path.isdir(base):
                return []
            out = []
            for root, _dirs, names in os.walk(base):
                for name in names:
                    p = os.path.join(root, name)
                    if p.startswith(prefix):
                        out.append(p)
            return sorted(out)
        return self._retrying(fn, "objstore.get", prefix)

    def delete_prefix(self, prefix: str) -> int:
        return _delete_listed(self, prefix)


# ---------------------------------------------------------------------------
# AWS S3 — SigV4 request signing (stdlib hmac/sha256)
# ---------------------------------------------------------------------------
class S3Store:
    def __init__(self, bucket: str, region: str = "us-east-1",
                 endpoint_url: str | None = None,
                 access_key_id: str | None = None,
                 secret_key: str | None = None, token: str | None = None,
                 virtual_hosted_style: bool = True):
        self.bucket = bucket
        self.region = region
        self.access_key_id = access_key_id
        self.secret_key = secret_key
        self.token = token
        if endpoint_url:
            self.base = endpoint_url.rstrip("/")
            self.path_style = True   # emulators/minio serve path-style
        elif virtual_hosted_style:
            self.base = f"https://{bucket}.s3.{region}.amazonaws.com"
            self.path_style = False
        else:
            self.base = f"https://s3.{region}.amazonaws.com"
            self.path_style = True

    def _url_and_path(self, key: str) -> tuple[str, str]:
        key = urllib.parse.quote(key, safe="/~-._")
        path = (f"/{self.bucket}/{key}" if self.path_style else f"/{key}")
        return self.base + path, path

    def _signed_headers(self, method: str, path: str, body: bytes,
                        now: datetime.datetime | None = None,
                        query: str = "") -> dict:
        """AWS Signature Version 4 (the algorithm object_store's
        AmazonS3Builder clients implement; anonymous when no key is set).
        `query` is the already-canonical query string (keys sorted,
        values URI-encoded) for sub-resource requests like ListObjectsV2
        — it must be byte-identical to what goes on the wire."""
        host = urllib.parse.urlparse(self.base).netloc
        payload_hash = hashlib.sha256(body or b"").hexdigest()
        headers = {"host": host, "x-amz-content-sha256": payload_hash}
        if self.access_key_id is None or self.secret_key is None:
            return {"x-amz-content-sha256": payload_hash}
        now = now or datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        headers["x-amz-date"] = amz_date
        if self.token:
            headers["x-amz-security-token"] = self.token
        signed = ";".join(sorted(headers))
        canonical = "\n".join([
            method, path, query,
            *[f"{k}:{headers[k].strip()}" for k in sorted(headers)],
            "", signed, payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])

        def hm(k, msg):
            return hmac.new(k, msg.encode(), hashlib.sha256).digest()

        k = hm(("AWS4" + self.secret_key).encode(), datestamp)
        k = hm(hm(hm(k, self.region), "s3"), "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        out = dict(headers)
        out.pop("host")
        out["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key_id}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}")
        return out

    def get(self, key: str) -> bytes:
        url, path = self._url_and_path(key)
        return _http("GET", url, self._signed_headers("GET", path, b""), None,
                     fault_point="objstore.get", key=key)

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        url, path = self._url_and_path(key)
        headers = self._signed_headers("GET", path, b"")
        # Range rides unsigned: SigV4 covers only host/x-amz-* here
        # (SignedHeaders=host;x-amz-content-sha256;x-amz-date), so adding it
        # after signing is wire-legal
        headers["Range"] = _range_header(offset, length)
        status, body = _http_status("GET", url, headers, None,
                                    fault_point="objstore.get", key=key)
        return _slice_range(status, body, offset, length)

    def put(self, key: str, data: bytes) -> None:
        url, path = self._url_and_path(key)
        _http("PUT", url, self._signed_headers("PUT", path, data), data,
              fault_point="objstore.put", key=key)

    def delete(self, key: str) -> None:
        url, path = self._url_and_path(key)
        _http("DELETE", url, self._signed_headers("DELETE", path, b""),
              None, fault_point="objstore.put", key=key)

    def list_prefix(self, prefix: str) -> list[str]:
        """ListObjectsV2, paginated via continuation-token. The query
        string is part of the SigV4 canonical request, so it is built
        once in canonical form and signed byte-identical."""
        out: list[str] = []
        token: str | None = None
        path = f"/{self.bucket}" if self.path_style else "/"
        while True:
            params = {"list-type": "2", "prefix": prefix}
            if token:
                params["continuation-token"] = token
            query = "&".join(
                f"{urllib.parse.quote(k, safe='-_.~')}="
                f"{urllib.parse.quote(v, safe='-_.~')}"
                for k, v in sorted(params.items()))
            headers = self._signed_headers("GET", path, b"", query=query)
            body = _http("GET", f"{self.base}{path}?{query}", headers,
                         None, fault_point="objstore.get", key=prefix)
            out.extend(_xml_texts("Key", body))
            token = _xml_text("NextContinuationToken", body)
            if not token:
                return out

    def delete_prefix(self, prefix: str) -> int:
        return _delete_listed(self, prefix)


# ---------------------------------------------------------------------------
# Google Cloud Storage — JSON API + service-account OAuth JWT
# ---------------------------------------------------------------------------
class GcsStore:
    def __init__(self, bucket: str, gcs_base_url: str | None = None,
                 disable_oauth: bool = False,
                 client_email: str | None = None,
                 private_key: str | None = None):
        self.bucket = bucket
        self.base = (gcs_base_url or "https://storage.googleapis.com") \
            .rstrip("/")
        self.disable_oauth = disable_oauth
        self.client_email = client_email
        self.private_key = private_key
        self._tok: tuple[str, float] | None = None

    def _auth(self) -> dict:
        if self.disable_oauth:
            return {}
        if not (self.client_email and self.private_key):
            raise ObjectStoreError(
                "gcs needs client_email+private_key (or disable_oauth "
                "against an emulator)")
        if self._tok and self._tok[1] > time.monotonic() + 60:
            return {"Authorization": f"Bearer {self._tok[0]}"}
        token = self._fetch_token()
        return {"Authorization": f"Bearer {token}"}

    def _fetch_token(self) -> str:
        """OAuth2 JWT bearer grant, RS256-signed with the service-account
        key (what object_store's GoogleCloudStorageBuilder does with the
        service_account file)."""
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import padding

        now = int(time.time())
        claim = {
            "iss": self.client_email,
            "scope": "https://www.googleapis.com/auth/devstorage.read_write",
            "aud": "https://oauth2.googleapis.com/token",
            "iat": now, "exp": now + 3600,
        }

        def b64(d: bytes) -> bytes:
            return base64.urlsafe_b64encode(d).rstrip(b"=")

        signing_input = (b64(json.dumps({"alg": "RS256", "typ": "JWT"})
                             .encode()) + b"." +
                         b64(json.dumps(claim).encode()))
        key = serialization.load_pem_private_key(
            self.private_key.encode(), password=None)
        sig = key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
        jwt = (signing_input + b"." + b64(sig)).decode()
        body = urllib.parse.urlencode({
            "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
            "assertion": jwt}).encode()
        raw = _http("POST", "https://oauth2.googleapis.com/token",
                    {"Content-Type": "application/x-www-form-urlencoded"},
                    body)
        tok = json.loads(raw)["access_token"]
        # expiry on the monotonic clock: the token lives `expires_in`
        # seconds from NOW — an NTP step must not stretch or clip it
        self._tok = (tok, time.monotonic() + 3300)
        return tok

    def _media_url(self, key: str) -> str:
        return (f"{self.base}/storage/v1/b/{self.bucket}/o/"
                f"{urllib.parse.quote(key, safe='')}?alt=media")

    def get(self, key: str) -> bytes:
        return _http("GET", self._media_url(key), self._auth(), None,
                     fault_point="objstore.get", key=key)

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        headers = {**self._auth(), "Range": _range_header(offset, length)}
        status, body = _http_status("GET", self._media_url(key), headers,
                                    None, fault_point="objstore.get", key=key)
        return _slice_range(status, body, offset, length)

    def put(self, key: str, data: bytes) -> None:
        url = (f"{self.base}/upload/storage/v1/b/{self.bucket}/o"
               f"?uploadType=media&name={urllib.parse.quote(key, safe='')}")
        headers = {"Content-Type": "application/octet-stream", **self._auth()}
        _http("POST", url, headers, data, fault_point="objstore.put", key=key)

    def delete(self, key: str) -> None:
        url = (f"{self.base}/storage/v1/b/{self.bucket}/o/"
               f"{urllib.parse.quote(key, safe='')}")
        _http("DELETE", url, self._auth(), None,
              fault_point="objstore.put", key=key)

    def list_prefix(self, prefix: str) -> list[str]:
        """JSON-API object listing, paginated via nextPageToken."""
        out: list[str] = []
        token: str | None = None
        while True:
            params = {"prefix": prefix}
            if token:
                params["pageToken"] = token
            url = (f"{self.base}/storage/v1/b/{self.bucket}/o?"
                   + urllib.parse.urlencode(sorted(params.items())))
            raw = _http("GET", url, self._auth(), None,
                        fault_point="objstore.get", key=prefix)
            d = json.loads(raw)
            out.extend(item["name"] for item in d.get("items", []))
            token = d.get("nextPageToken")
            if not token:
                return out

    def delete_prefix(self, prefix: str) -> int:
        return _delete_listed(self, prefix)


# ---------------------------------------------------------------------------
# Azure Blob — SharedKey signing (or bearer token / azurite emulator)
# ---------------------------------------------------------------------------
class AzblobStore:
    def __init__(self, container: str, account: str | None = None,
                 access_key: str | None = None,
                 bearer_token: str | None = None,
                 use_emulator: bool = False,
                 endpoint_url: str | None = None):
        self.container = container
        self.account = account or ("devstoreaccount1" if use_emulator
                                   else None)
        if self.account is None:
            raise ObjectStoreError("azblob needs account (or use_emulator)")
        self.access_key = access_key
        self.bearer_token = bearer_token
        if endpoint_url:
            self.base = f"{endpoint_url.rstrip('/')}/{self.account}"
        elif use_emulator:
            self.base = f"http://127.0.0.1:10000/{self.account}"
        else:
            self.base = f"https://{self.account}.blob.core.windows.net"

    def _headers(self, method: str, key: str, body: bytes | None,
                 extra: dict | None = None,
                 url_path: str | None = None,
                 params: dict | None = None) -> dict:
        now = datetime.datetime.now(datetime.timezone.utc) \
            .strftime("%a, %d %b %Y %H:%M:%S GMT")
        headers = {"x-ms-date": now, "x-ms-version": "2021-08-06"}
        if extra:
            # merged before signing: x-ms-* extras (x-ms-range) land in the
            # sorted CanonicalizedHeaders block and are covered by the MAC
            headers.update(extra)
        length = str(len(body)) if body else ""
        content_type = ""
        if body is not None:
            headers["x-ms-blob-type"] = "BlockBlob"
            # urllib injects a default Content-Type on bodied requests; set
            # it explicitly so the signed value matches what's on the wire
            content_type = "application/octet-stream"
            headers["Content-Type"] = content_type
        if self.bearer_token:
            headers["Authorization"] = f"Bearer {self.bearer_token}"
            return headers
        if not self.access_key:
            return headers
        # SharedKey canonical form (Storage REST API auth): the resource is
        # "/<account>" + the request URL path (emulator paths already carry
        # the account segment, matching azurite's expectation)
        canon_headers = "".join(
            f"{k}:{headers[k]}\n" for k in sorted(headers)
            if k.startswith("x-ms-"))
        if url_path is None:
            url_path = urllib.parse.urlparse(self._url(key)).path
        canon_resource = f"/{self.account}{url_path}"
        if params:
            # query params join CanonicalizedResource as sorted
            # lowercase "\nkey:value" lines (Storage SharedKey spec) —
            # container listings are unforgeable only if signed
            canon_resource += "".join(
                f"\n{k.lower()}:{params[k]}" for k in sorted(params))
        to_sign = "\n".join([
            method, "", "", length, "", content_type, "", "", "", "", "",
            "",
        ]) + "\n" + canon_headers + canon_resource
        sig = base64.b64encode(hmac.new(
            base64.b64decode(self.access_key), to_sign.encode(),
            hashlib.sha256).digest()).decode()
        headers["Authorization"] = f"SharedKey {self.account}:{sig}"
        return headers

    def _url(self, key: str) -> str:
        return f"{self.base}/{self.container}/" \
               f"{urllib.parse.quote(key, safe='/')}"

    def get(self, key: str) -> bytes:
        return _http("GET", self._url(key), self._headers("GET", key, None),
                     None, fault_point="objstore.get", key=key)

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        headers = self._headers(
            "GET", key, None,
            extra={"x-ms-range": _range_header(offset, length)})
        status, body = _http_status("GET", self._url(key), headers, None,
                                    fault_point="objstore.get", key=key)
        return _slice_range(status, body, offset, length)

    def put(self, key: str, data: bytes) -> None:
        _http("PUT", self._url(key), self._headers("PUT", key, data), data,
              fault_point="objstore.put", key=key)

    def delete(self, key: str) -> None:
        _http("DELETE", self._url(key),
              self._headers("DELETE", key, None), None,
              fault_point="objstore.put", key=key)

    def list_prefix(self, prefix: str) -> list[str]:
        """Container blob listing (restype=container&comp=list),
        paginated via NextMarker; the query params ride inside the
        SharedKey CanonicalizedResource."""
        out: list[str] = []
        marker: str | None = None
        container_path = urllib.parse.urlparse(
            f"{self.base}/{self.container}").path
        while True:
            params = {"restype": "container", "comp": "list",
                      "prefix": prefix}
            if marker:
                params["marker"] = marker
            query = urllib.parse.urlencode(sorted(params.items()))
            headers = self._headers("GET", "", None,
                                    url_path=container_path,
                                    params=params)
            body = _http("GET",
                         f"{self.base}/{self.container}?{query}",
                         headers, None,
                         fault_point="objstore.get", key=prefix)
            out.extend(_xml_texts("Name", body))
            marker = _xml_text("NextMarker", body)
            if not marker:
                return out

    def delete_prefix(self, prefix: str) -> int:
        return _delete_listed(self, prefix)
