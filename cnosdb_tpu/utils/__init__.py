from .hash import bkdr_hash, bkdr_hash_u64, fnv1a_64, split_id
from .bloom import BloomFilter

__all__ = ["bkdr_hash", "bkdr_hash_u64", "fnv1a_64", "split_id", "BloomFilter"]
