"""Jittered exponential backoff for retry loops.

Replaces the fixed ``time.sleep(0.05)`` / ``time.sleep(0.1)`` spins in the
coordinator write/membership retry loops and ``wait_rpc_ready`` — fixed
delays synchronize retries across callers (thundering herd on a recovering
leader) and either burn CPU (too short) or stretch failover latency (too
long). Full jitter per AWS architecture-blog guidance: each delay is drawn
uniformly from ``[0, min(cap, initial * factor**attempt)]``.
"""
from __future__ import annotations

import random
import time


class Backoff:
    """One retry loop's backoff state.

    >>> bo = Backoff(initial=0.05, cap=2.0)
    >>> while not done():
    ...     if not bo.sleep(deadline):
    ...         raise TimeoutError(...)
    """

    def __init__(self, initial: float = 0.05, cap: float = 2.0,
                 factor: float = 2.0, rng: random.Random | None = None):
        self.initial = initial
        self.cap = cap
        self.factor = factor
        self.attempt = 0
        self._rng = rng or random

    def reset(self) -> None:
        """Back to the initial delay (call after a success mid-loop)."""
        self.attempt = 0

    def next(self) -> float:
        """The next delay (seconds), advancing the attempt counter."""
        ceiling = min(self.cap, self.initial * (self.factor ** self.attempt))
        self.attempt += 1
        return self._rng.uniform(0.0, ceiling) if ceiling > 0 else 0.0

    def sleep(self, deadline: float | None = None) -> bool:
        """Sleep the next delay, clamped to ``deadline`` (``time.monotonic``
        basis). Returns False iff the deadline has already passed — the
        caller should stop retrying."""
        d = self.next()
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            d = min(d, remaining)
        if d > 0:
            time.sleep(d)
        return True
