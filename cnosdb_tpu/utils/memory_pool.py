"""Greedy memory pool: a fixed byte budget gating queries and writes.

Role-parity with the reference's GreedyMemoryPool
(common/memory_pool/src/lib.rs:18-60, wired into writes at
coordinator/src/raft/writer.rs:58-84 and into DataFusion queries): callers
acquire an estimate before materializing large buffers and release when
done; an acquisition that would exceed the budget fails the operation
instead of OOM-killing the process."""
from __future__ import annotations

import threading

from ..errors import CnosError
from . import lockwatch


class MemoryExhausted(CnosError):
    pass


class MemoryPool:
    def __init__(self, capacity_bytes: int):
        self.capacity = int(capacity_bytes)
        self.used = 0
        self._lock = lockwatch.Lock("memory_pool")

    def acquire(self, n: int, what: str = "buffer"):
        with self._lock:
            if self.used + n > self.capacity:
                raise MemoryExhausted(
                    f"memory pool exhausted acquiring {n} bytes for {what} "
                    f"({self.used}/{self.capacity} in use)")
            self.used += n

    def release(self, n: int):
        with self._lock:
            self.used = max(0, self.used - n)

    def reservation(self, n: int, what: str = "buffer"):
        return _Reservation(self, n, what)


class _Reservation:
    """Context manager: acquire on enter, release on exit."""

    def __init__(self, pool: MemoryPool, n: int, what: str):
        self.pool = pool
        self.n = int(n)
        self.what = what

    def __enter__(self):
        self.pool.acquire(self.n, self.what)
        return self

    def __exit__(self, *exc):
        self.pool.release(self.n)
        return False


# a generous default for embedded/test use; servers size it from config
DEFAULT_POOL = MemoryPool(4 << 30)
