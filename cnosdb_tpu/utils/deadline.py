"""Per-request deadline + cancellation context.

The reference threads a tokio `CancellationToken`/timeout pair from the
HTTP service through the coordinator into tskv scans (query_server's
QueryTracker + the per-request `Duration` budget in config). This module
is the rebuild's equivalent for synchronous threads: a `Deadline` object
created once at ingress (header `X-CnosDB-Deadline-Ms`, else the config
`[query] read_timeout_ms` / `write_timeout_ms` defaults) and carried
thread-locally so every layer below — SQL executor, coordinator fan-out,
RPC hops, shared scan/decode pools, TPU partial-agg loops — can

  * shrink its own blocking budget to the remaining time (`cap()`),
  * refuse to start work that can no longer finish (`check()`), and
  * observe a cooperative cancel (KILL QUERY / client disconnect).

Clock discipline: expiry is tracked on the *monotonic* clock locally.
Crossing a process boundary (RPC payload `_deadline_ms`) uses wall-clock
epoch ms — same-host clocks in tests/clusters make this safe, and a
skewed clock only ever makes a remote hop more or less patient, never
wrong (the client's socket timeout is the hard bound).

`CANCELS` is the node-side registry: RPC handlers running on behalf of a
query register under its qid, and a best-effort `cancel_scan(qid)` RPC
flips every registered context's cancel flag so in-flight scan loops
stop at their next check.
"""
from __future__ import annotations

import threading
import time

from ..errors import DeadlineExceeded, QueryError
from . import lockwatch

_tls = threading.local()

# observability counters folded into /metrics by server/http.handle_metrics
_ctr_lock = lockwatch.Lock("deadline.counters")
_counters: dict[str, int] = {
    "cancel_scan_received": 0,   # cancel_scan RPCs handled on this node
    "tasks_shed": 0,             # pool tasks dropped before running
    "expired_rejected": 0,       # RPCs rejected already-expired on dequeue
}


def bump(name: str, n: int = 1) -> None:
    with _ctr_lock:
        _counters[name] = _counters.get(name, 0) + n


def counters_snapshot() -> dict[str, int]:
    with _ctr_lock:
        return dict(_counters)


class Deadline:
    """Monotonic deadline + cancel flag for one request.

    `timeout_s=None` means no time bound (cancel-only context). `qid`
    links the context to the query tracker so KILL QUERY and remote
    cancel fan-out can find it. `remote_nodes` records every RPC address
    the coordinator sent scan work to, for best-effort cancel fan-out.
    """

    __slots__ = ("expires_at", "qid", "cancelled", "cancel_reason",
                 "remote_nodes", "mem")

    def __init__(self, timeout_s: float | None = None, qid: str | None = None):
        self.expires_at = (time.monotonic() + timeout_s) \
            if timeout_s is not None else None
        self.qid = qid
        self.cancelled = False
        self.cancel_reason = ""
        self.remote_nodes: set[str] = set()
        # per-query memory account (server/memory.QueryMemory), created
        # lazily on first charge; rides the deadline so every layer the
        # deadline already reaches (scan assembly, decode pools, RPC
        # hops) can charge the same request without new plumbing
        self.mem = None

    def remaining(self) -> float | None:
        """Seconds left, None if unbounded. May be <= 0 once expired."""
        if self.expires_at is None:
            return None
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        r = self.remaining()
        return r is not None and r <= 0

    def cancel(self, reason: str = "cancelled") -> None:
        self.cancelled = True
        if not self.cancel_reason:
            self.cancel_reason = reason

    def dead(self) -> bool:
        return self.cancelled or self.expired()

    def check(self) -> None:
        """Raise if this request must stop (cancelled or out of budget)."""
        if self.cancelled:
            raise QueryError(f"query {self.qid or '?'} cancelled"
                             + (f" ({self.cancel_reason})"
                                if self.cancel_reason not in
                                ("", "cancelled") else ""))
        r = self.remaining()
        if r is not None and r <= 0:
            raise DeadlineExceeded(
                f"deadline exceeded ({-r * 1000:.0f} ms past budget)",
                qid=self.qid)

    def cap(self, timeout: float) -> float:
        """Shrink a blocking budget to the remaining deadline.

        Raises via check() when nothing remains — callers must not start
        a blocking operation they cannot finish. Floors at 50 ms so a
        nearly-expired request still gets a usable socket timeout rather
        than an instant local EAGAIN-style failure."""
        r = self.remaining()
        if r is None:
            return timeout
        if r <= 0 or self.cancelled:
            self.check()
        return min(timeout, max(r, 0.05))

    # ---- wire form (RPC payload `_deadline_ms`: wall-clock epoch ms) ----

    def to_wire_ms(self) -> int | None:
        r = self.remaining()
        if r is None:
            return None
        return int((time.time() + max(r, 0.0)) * 1000)  # lint: disable=wallclock-duration (wire form IS wall-clock epoch ms — see module docstring on clock discipline)


def from_wire(deadline_at_ms: int | None, qid: str | None = None) -> Deadline:
    if deadline_at_ms is None:
        return Deadline(None, qid=qid)
    return Deadline(deadline_at_ms / 1000.0 - time.time(), qid=qid)  # lint: disable=wallclock-duration (wire form IS wall-clock epoch ms — skew only shifts patience, socket timeout is the hard bound)


def derived(qid: str | None) -> Deadline:
    """Per-attempt child context for hedged fan-out: shares the calling
    thread's remaining budget (same monotonic expiry — a hedge must
    never outlive the query) but carries its OWN qid, so cancelling a
    losing hedge attempt through CANCELS / cancel_scan never touches
    the query's other work registered under the parent qid. The child
    also keeps its own `remote_nodes` set: loser cancel fan-out targets
    exactly the nodes that attempt reached."""
    parent = current()
    d = Deadline(None, qid=qid)
    if parent is not None:
        d.expires_at = parent.expires_at
        d.mem = parent.mem   # one query, one memory account
    return d


def current() -> Deadline | None:
    return getattr(_tls, "dl", None)


class scope:
    """Install `dl` as the thread's current deadline; None clears it
    (used by cancel fan-out, which must run even after expiry)."""

    def __init__(self, dl: Deadline | None):
        self.dl = dl
        self.prev: Deadline | None = None

    def __enter__(self):
        self.prev = getattr(_tls, "dl", None)
        _tls.dl = self.dl
        return self.dl

    def __exit__(self, *exc):
        _tls.dl = self.prev
        return False


def check_current() -> None:
    """Cheap cooperative checkpoint for inner loops (scan/decode/agg)."""
    dl = getattr(_tls, "dl", None)
    if dl is not None:
        dl.check()


def cap_current(timeout: float) -> float:
    dl = getattr(_tls, "dl", None)
    if dl is None:
        return timeout
    return dl.cap(timeout)


class CancelRegistry:
    """Node-side per-qid cancel flags.

    `register` remembers a Deadline working for qid (RPC handlers do this
    on dispatch); `cancel(qid)` flips every registered context and leaves
    a tombstone so work for that qid arriving shortly *after* the cancel
    (e.g. still sitting in a fault-injected delay) is rejected on
    dequeue instead of executed."""

    TOMBSTONE_TTL = 60.0

    def __init__(self):
        self._lock = lockwatch.Lock("deadline.cancels")
        self._working: dict[str, list[Deadline]] = {}
        self._tombstones: dict[str, float] = {}

    def _prune(self, now: float) -> None:
        dead = [q for q, t in self._tombstones.items()
                if now - t > self.TOMBSTONE_TTL]
        for q in dead:
            del self._tombstones[q]

    def register(self, qid: str, dl: Deadline) -> None:
        with self._lock:
            if qid in self._tombstones:
                dl.cancel("cancelled before dispatch")
            self._working.setdefault(qid, []).append(dl)

    def unregister(self, qid: str, dl: Deadline) -> None:
        with self._lock:
            lst = self._working.get(qid)
            if lst is not None:
                try:
                    lst.remove(dl)
                except ValueError:
                    pass
                if not lst:
                    del self._working[qid]

    def is_cancelled(self, qid: str) -> bool:
        with self._lock:
            return qid in self._tombstones

    def cancel(self, qid: str) -> int:
        now = time.monotonic()
        with self._lock:
            self._prune(now)
            self._tombstones[qid] = now
            victims = list(self._working.get(qid, ()))
        for dl in victims:
            dl.cancel("remote cancel")
        bump("cancel_scan_received")
        return len(victims)


CANCELS = CancelRegistry()
