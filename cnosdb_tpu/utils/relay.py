"""Degraded-TPU-relay guard, shared by bench.py and __graft_entry__.py.

In tunneled-TPU environments the accelerator plugin dials the relay at
`import jax` whenever PALLAS_AXON_POOL_IPS is set (even under
JAX_PLATFORMS=cpu), and a degraded relay hangs the import for minutes.
Clearing the var in-process is too late — sitecustomize registers the
dialing plugin at interpreter start — so the only safe probe is a child
process with a timeout, and the only safe fallback is re-running in a
child (or execve'd image) whose environment never had the var.
"""
from __future__ import annotations

import os
import subprocess
import sys


def probe_jax_importable(timeout: float = 120.0) -> str | None:
    """None when `import jax` can complete in this environment, else a
    short reason string (probe runs in a throwaway subprocess)."""
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return None
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, capture_output=True, text=True)
        if probe.returncode == 0:
            return None
        return (f"device probe failed (rc={probe.returncode}): "
                f"{(probe.stderr or '').strip()[-200:]}")
    except subprocess.TimeoutExpired:
        return "TPU relay unresponsive (probe timeout)"


def cleaned_cpu_env(extra: dict | None = None) -> dict:
    """A copy of the environment with the relay var stripped and jax
    pinned to CPU — what a clean-env fallback child should run under."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if extra:
        env.update(extra)
    return env
