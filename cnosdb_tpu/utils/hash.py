"""Hashes used for series placement and bloom filters.

BKDR (seed 1313) matches the reference's series hashing
(common/utils/src/bkdr_hash.rs:3-58, used for shard placement at
coordinator/src/service.rs:671). FNV-1a is the second, independent hash for
bloom double-hashing.
"""
from __future__ import annotations

import numpy as np

_BKDR_SEED = 1313
_MASK64 = (1 << 64) - 1

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3


def bkdr_hash(data: bytes, init: int = 0) -> int:
    """BKDR hash of bytes → u64 (wrapping mul-add, seed 1313)."""
    h = init
    for b in data:
        h = (h * _BKDR_SEED + b) & _MASK64
    return h


def bkdr_hash_u64(data: bytes) -> int:
    return bkdr_hash(data)


def fnv1a_64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK64
    return h


def split_id(id128: int) -> tuple[int, int]:
    """Split a (prefix<<64)|hash id into (prefix, hash)."""
    return id128 >> 64, id128 & _MASK64


def bkdr_hash_batch(items: list[bytes]) -> np.ndarray:
    """Vectorized-ish batch BKDR hash (python loop per item; items are short)."""
    out = np.empty(len(items), dtype=np.uint64)
    for i, it in enumerate(items):
        out[i] = bkdr_hash(it)
    return out
