"""The canonical crash-sweep workload: write→flush→compact→tier→matview.

Runs single-node, single-process, entirely under one root directory —
``python -m cnosdb_tpu.chaos.workload run <root>`` — so an injected
``crash`` (os._exit inside a faults.fire site) kills a *real* process at
an arbitrary point of the storage lifecycle. The run crosses every
node-scope fault point: WAL append/sync/roll, record-file append/sync,
flush, compaction, TSM finalize, cold tiering (object put/get + registry
rewrite), matview persist and the scrubber's read hook.

Every client-visible operation is recorded through chaos.history with
durable invoke records; a write is only acked (ok event) after its WAL
has been fsync'd, making the no-lost-acked-write check airtight against
os._exit. :func:`verify` reopens the same directories — which IS the
recovery path — measures crash→first-successful-read, and runs the
checker.

Timestamps are synthetic (~1970, one row per second) and the matview
refresh takes an explicit now_ns, so nothing depends on the wall clock
and the same seed + spec replays the same firing sequence.
"""
from __future__ import annotations

import json
import os
import sys
import time

from .. import faults
from ..errors import CnosError
from .checker import book, check_matview_parity, run_client_checks
from .history import History, HistoryRecorder
from ..utils import stages

SEC = 10**9
OWNER = "cnosdb.public"
HISTORY = "history.jsonl"
TRACE = "fault_trace.json"
# rows 0..179 are written by s1, 180..299 by s2; rows < DELETE_BEFORE
# are deleted; files wholly older than TIER_BOUNDARY age to cold — the
# boundary sits past the last row because major compaction leaves one
# file per vnode spanning the whole range, and the workload must cross
# the tier/objstore/cold-scan sites
DELETE_BEFORE = 60
TIER_BOUNDARY = 400 * SEC
NOW_NS = 900 * SEC


def _open_db(root: str):
    from ..parallel.coordinator import Coordinator
    from ..parallel.meta import MetaStore
    from ..sql.executor import QueryExecutor
    from ..storage.engine import TsKv
    from ..storage import backup, tiering

    os.environ.setdefault("CNOSDB_MATVIEW_AUTO", "0")
    tiering.configure(os.path.join(root, "bucket"))
    # DR plane shares the root: sealed WAL segments stream to archive/
    # from the moment each vnode opens, so the run crosses backup.archive
    # continuously and BACKUP/RESTORE below cross the other two sites
    backup.configure_archive(os.path.join(root, "archive"))
    meta = MetaStore(os.path.join(root, "meta.json"))
    engine = TsKv(os.path.join(root, "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    return engine, coord, ex


def _sync_wals(engine) -> None:
    """Make everything written so far durable — the ack barrier."""
    for v in engine.local_vnodes(OWNER):
        v.wal.sync()


def _keys(rows) -> list[str]:
    return [f"{h}:{ts}" for ts, h, _v in rows]


def _write(ex, engine, hist, session, rows) -> None:
    inv = hist.invoke(session, "write", keys=_keys(rows))
    vals = ", ".join(f"({ts}, '{h}', {v})" for ts, h, v in rows)
    ex.execute_one(f"INSERT INTO w (time, h, v) VALUES {vals}")
    _sync_wals(engine)
    hist.ok(session, inv)


def _read(ex, hist, session, mono: bool = True) -> set[str]:
    inv = hist.invoke(session, "read", durable=False, mono=mono)
    rows = ex.execute_one("SELECT h, time FROM w").rows()
    keys = sorted(f"{h}:{int(ts)}" for h, ts in rows)
    hist.ok(session, inv, keys=keys)
    return set(keys)


def _ddl(ex, hist, session, name: str, sql: str) -> None:
    inv = hist.invoke(session, "ddl", name=name)
    ex.execute_one(sql)
    hist.ok(session, inv)


def _batch(start: int, n: int):
    return [(i * SEC, f"h{i % 2}", f"{i}.5") for i in range(start, start + n)]


def run(root: str) -> None:
    """Execute the canonical workload to completion (or until an armed
    fault crashes the process). Exceptions propagate — the sweep treats
    any exit other than a clean 0 or the crash code as a bug."""
    os.makedirs(root, exist_ok=True)
    engine, coord, ex = _open_db(root)
    hist = HistoryRecorder(os.path.join(root, HISTORY))
    try:
        _ddl(ex, hist, "s1", "create_table",
             "CREATE TABLE w (v DOUBLE, TAGS(h))")
        _write(ex, engine, hist, "s1", _batch(0, 60))
        # shrink WAL segments so later appends cross the wal.roll site
        for v in engine.local_vnodes(OWNER):
            v.wal.max_segment_size = 2048
        _write(ex, engine, hist, "s1", _batch(60, 60))
        _write(ex, engine, hist, "s1", _batch(120, 60))
        _read(ex, hist, "s1")
        _spill_groupby(ex, hist)
        _ddl(ex, hist, "s1", "flush", "FLUSH")
        _write(ex, engine, hist, "s2", _batch(180, 60))
        _write(ex, engine, hist, "s2", _batch(240, 60))
        del_keys = _keys(_batch(0, DELETE_BEFORE))
        inv = hist.invoke("s2", "delete", keys=del_keys)
        ex.execute_one(f"DELETE FROM w WHERE time < {DELETE_BEFORE * SEC}")
        _sync_wals(engine)
        hist.ok("s2", inv)
        _read(ex, hist, "s2")
        _ddl(ex, hist, "s2", "flush", "FLUSH")
        _ddl(ex, hist, "s1", "compact", "COMPACT DATABASE public")
        _tier(engine, hist)
        _read(ex, hist, "s1")           # crosses the cold tier
        _ddl(ex, hist, "s1", "create_view",
             "CREATE MATERIALIZED VIEW mv WATERMARK DELAY '10s' AS "
             "SELECT date_bin(INTERVAL '1 minute', time) AS t, h, "
             "sum(v), count(v) FROM w GROUP BY t, h")
        ex.matview_engine().refresh("mv", now_ns=NOW_NS)
        _scrub(engine, hist)
        _backup_restore(ex, hist)
        _read(ex, hist, "s1")
        _read(ex, hist, "s2")
    finally:
        hist.close()
    # clean completion: dump the fired log — the probe pass reads this to
    # learn how many times each fault point was crossed
    with open(os.path.join(root, TRACE), "w", encoding="utf-8") as f:
        json.dump({"fired": [list(t) for t in faults.fired_log()]}, f)
    coord.close()


def _spill_groupby(ex, hist) -> None:
    """Cross the memory.spill site: squeeze the group budget so a wide
    group-by's accumulator spills (spill-vs-in-memory bit-identity is
    proven by tests/test_memory.py; here the point just needs a real
    crossing for the crash sweep). count(DISTINCT) forces the host
    accumulator path where the spiller lives."""
    from ..server import memory as memgov

    inv = hist.invoke("s1", "ddl", name="spill_groupby")
    saved = memgov.GROUP_BYTES
    memgov.GROUP_BYTES = 1
    try:
        ex.execute_one("SELECT h, count(DISTINCT v), sum(v) FROM w "
                       "GROUP BY h")
    finally:
        memgov.GROUP_BYTES = saved
    hist.ok("s1", inv)


def _tier(engine, hist) -> None:
    from ..storage import tiering

    inv = hist.invoke("s1", "ddl", name="tier")
    n = 0
    for v in engine.local_vnodes(OWNER):
        n += tiering.tier_vnode(v, TIER_BOUNDARY)
    hist.ok("s1", inv, files=n)


def _backup_restore(ex, hist) -> None:
    """Cross the DR plane's backup.manifest + restore.install sites: one
    consistent backup, then a restore into a parallel database. The
    source database must come through untouched — the post-restore reads
    and the checker prove it."""
    inv = hist.invoke("s1", "ddl", name="backup")
    ex.execute_one("BACKUP DATABASE public")
    hist.ok("s1", inv)
    inv = hist.invoke("s1", "ddl", name="restore")
    ex.execute_one("RESTORE DATABASE public AS public_r")
    hist.ok("s1", inv)


def _scrub(engine, hist) -> None:
    from ..storage import scrub

    inv = hist.invoke("s1", "ddl", name="scrub")
    out = scrub.scrub_engine(engine)
    hist.ok("s1", inv, files=out.get("files", 0))


def verify(root: str) -> dict:
    """Reopen the workload's directories (the recovery path), measure
    crash→first-successful-read, and run the consistency checker.

    → {"mttr_s", "observed", "results": [CheckResult...]} — verdicts are
    also booked into the chaos counters for /metrics."""
    from .. import chaos
    from ..storage import tiering

    t0 = time.monotonic()
    engine, coord, ex = _open_db(root)
    try:
        with stages.stage("chaos.mttr_ms"):
            try:
                rows = ex.execute_one("SELECT h, time FROM w").rows()
            except CnosError:
                # first read may trip over torn cold state; the
                # coordinator's recover-and-retry already ran once — a
                # second attempt proves recovery converged (or fails loud)
                rows = ex.execute_one("SELECT h, time FROM w").rows()
        mttr = time.monotonic() - t0
        chaos.note_recovery("crash_restart", mttr)
        observed = {f"{h}:{int(ts)}" for h, ts in rows}
        hist = History.load(os.path.join(root, HISTORY))
        results = run_client_checks(hist, observed)
        results.append(_matview_check(ex, hist))
        book(results)
        return {"mttr_s": mttr, "observed": len(observed),
                "results": results}
    finally:
        from ..storage import backup

        coord.close()
        tiering.configure(None)
        backup.configure_archive(None)


def _matview_check(ex, hist):
    """Matview-vs-scan parity after recovery — only judged when the view's
    creation was acked (an ambiguous CREATE may legitimately be absent)."""
    from .checker import CheckResult

    acked_view = any(o.op == "ddl" and o.data.get("name") == "create_view"
                     and o.acked for o in hist.ops)
    if not acked_view:
        return CheckResult("matview_parity", True, "view not acked: skipped")
    mv = ex.matview_engine()
    mv.sync_from_meta()        # fresh process: pull the replicated catalog
    mv.refresh("mv", now_ns=NOW_NS)
    q = ("SELECT date_bin(INTERVAL '1 minute', time) AS t, h, "
         "sum(v), count(v) FROM w GROUP BY t, h")
    ex.matview_rewrite_enabled = True
    view_rows = ex.execute_one(q).rows()
    ex.matview_rewrite_enabled = False
    scan_rows = ex.execute_one(q).rows()
    ex.matview_rewrite_enabled = True
    return check_matview_parity(view_rows, scan_rows)


def main(argv: list[str]) -> int:
    if len(argv) != 2 or argv[0] not in ("run", "verify"):
        print("usage: python -m cnosdb_tpu.chaos.workload run|verify <root>",
              file=sys.stderr)
        return 2
    if argv[0] == "run":
        run(argv[1])
        return 0
    out = verify(argv[1])
    ok = all(r.ok for r in out["results"])
    print(json.dumps({"mttr_s": out["mttr_s"], "ok": ok,
                      "results": [[r.name, r.ok, r.detail]
                                  for r in out["results"]]}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
