"""In-process straggler bed for the gray-failure tolerance plane.

One REAL storage engine (a single vnode of mixed-type data with NULL
columns, NaN floats and an unflushed delta on top of sealed files) is
exposed through N replica `RpcServer`s, each with a settable service
delay — the msgpack-over-HTTP wire, the coordinator's hedged `_scan_
remote` lane, the health scorer and the cancel fan-out all run for
real; only the *placement* is synthetic (every "replica" serves the
same local vnode, which is exactly the raft-converged-replicas
assumption hedging relies on). Used by tests/test_health.py for the
bit-identical parity + cancellation proofs and by bench_suites.
run_straggler for the p50/p99 tail numbers, so the benchmark measures
the very plane the tests pin down.
"""
from __future__ import annotations

import time

import numpy as np

from ..models.points import SeriesRows, WriteBatch
from ..models.predicate import ColumnDomains, TimeRanges
from ..models.schema import ValueType
from ..models.series import SeriesKey
from ..parallel.coordinator import Coordinator, PlacedSplit
from ..parallel.ipc import encode_scan_batch
from ..parallel.meta import MetaStore
from ..parallel.net import RpcServer
from ..sql.executor import QueryExecutor
from ..storage.engine import TsKv
from ..utils import deadline as deadline_mod

OWNER = "cnosdb.public"
TABLE = "sg"
SEC = 10**9


class ReplicaServer:
    """One synthetic replica: a real RpcServer whose scan_vnode handler
    serves the bed's vnode after `delay_s` of injected service time."""

    def __init__(self, bed: "StragglerBed", node_id: int):
        self.bed = bed
        self.node_id = node_id
        self.delay_s = 0.0
        self.scans = 0
        self.cancels: list[str] = []
        self.server = RpcServer("127.0.0.1", 0, {
            "scan_vnode": self._scan,
            "cancel_scan": self._cancel,
            "ping": lambda p: {"ok": True},
        }).start()
        self.addr = self.server.addr

    def _scan(self, p):
        self.scans += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        split = PlacedSplit(p["owner"], p["vnode_id"], p["table"],
                            TimeRanges.from_wire(p["trs"]),
                            ColumnDomains.from_wire(p["doms"]))
        b = self.bed.coord._scan_local(split, p.get("field_names"))
        return {"ipc": None if b is None else encode_scan_batch(b)}

    def _cancel(self, p):
        qid = str(p.get("qid") or "")
        self.cancels.append(qid)
        return {"ok": True, "cancelled": deadline_mod.CANCELS.cancel(qid)}

    def close(self):
        self.server.stop()


class StragglerBed:
    """Coordinator + `n_replicas` delayable replica servers over one
    vnode of NULL/NaN/delta-bearing data."""

    def __init__(self, root: str, rows: int = 2000, n_replicas: int = 2):
        self.meta = MetaStore(f"{root}/meta.json")
        self.engine = TsKv(f"{root}/data")
        self.coord = Coordinator(self.meta, self.engine)
        self.executor = QueryExecutor(self.meta, self.coord)
        self._load(rows)
        self.replicas = [ReplicaServer(self, 2 + i)
                         for i in range(n_replicas)]
        for r in self.replicas:
            self.meta.register_node(r.node_id, grpc_addr=r.addr)
        # remote-path trigger: placement says "not my node" for the split
        # built below, so scan goes through _scan_remote / _rpc / wire
        self.coord.distributed = True
        base = self.coord.table_vnodes("cnosdb", "public", TABLE,
                                       TimeRanges.all(),
                                       ColumnDomains.all())
        assert base, "bed table produced no vnodes"
        self.vnode_id = base[0].vnode_id

    def _load(self, rows: int):
        self.executor.execute_one(
            f"CREATE TABLE {TABLE} (v DOUBLE, extra DOUBLE, TAGS(h))")
        rng = np.random.default_rng(11)
        half = rows // 2
        # sealed half: both fields, a few NaNs in v
        v = rng.normal(50, 10, half)
        v[::97] = np.nan
        ts = (np.arange(half, dtype=np.int64) + 1) * SEC
        wb = WriteBatch()
        wb.add_series(TABLE, SeriesRows(
            SeriesKey(TABLE, {"h": "h0"}), ts,
            {"v": (int(ValueType.FLOAT), v),
             "extra": (int(ValueType.FLOAT), rng.normal(0, 1, half))}))
        self.coord.write_points("cnosdb", "public", wb)
        self.engine.flush_all()
        # unflushed delta on top: only `v` present → NULL `extra` after
        # merge, so the parity check crosses the delta-merge + NULL paths
        ts2 = ts + half * SEC
        v2 = rng.normal(50, 10, half)
        v2[::89] = np.nan
        wb = WriteBatch()
        wb.add_series(TABLE, SeriesRows(
            SeriesKey(TABLE, {"h": "h1"}), ts2,
            {"v": (int(ValueType.FLOAT), v2)}))
        self.coord.write_points("cnosdb", "public", wb)

    # ------------------------------------------------------------- scans
    def split(self) -> PlacedSplit:
        """A split whose candidates are the replica servers, in id order
        (the health ranker reorders them from there)."""
        first, rest = self.replicas[0], self.replicas[1:]
        return PlacedSplit(OWNER, self.vnode_id, TABLE,
                           TimeRanges.all(), ColumnDomains.all(),
                           node_id=first.node_id,
                           alternates=[(self.vnode_id, r.node_id)
                                       for r in rest])

    def warm_replicas(self, per_replica: int = 8):
        """Scan each replica directly (round-robin, bypassing the health
        ranker) so every replica's latency sketch holds honest warm
        samples — the steady state of a real cluster, where all replicas
        carry traffic. Without this, a lone cold-path first sample can
        anchor an otherwise-idle replica's score."""
        from ..parallel.net import rpc_call
        payload = {"owner": OWNER, "vnode_id": self.vnode_id,
                   "table": TABLE, "trs": TimeRanges.all().to_wire(),
                   "doms": ColumnDomains.all().to_wire(),
                   "field_names": None}
        for i in range(per_replica):
            for r in self.replicas:
                with deadline_mod.scope(
                        deadline_mod.Deadline(5.0, qid=f"warm-{r.node_id}-{i}")):
                    rpc_call(r.addr, "scan_vnode", payload, timeout=5.0)

    def scan_once(self, qid: str = "bed", timeout_s: float | None = 5.0,
                  field_names=None):
        """One remote scan through the coordinator's read plane (hedged
        or legacy depending on CNOSDB_HEDGE), under its own deadline."""
        with deadline_mod.scope(deadline_mod.Deadline(timeout_s, qid=qid)):
            return self.coord._scan_remote(self.split(), field_names)

    def close(self):
        for r in self.replicas:
            r.close()
        self.coord.close()


def batch_bytes(b) -> bytes:
    """Canonical byte form of a ScanBatch for bit-identity assertions."""
    return b"" if b is None else encode_scan_batch(b)
