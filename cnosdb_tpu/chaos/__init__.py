"""Nemesis plane: client-history consistency checking + crash-point sweep.

The fault grammar (cnosdb_tpu/faults.py) *injects* failures; this package
decides whether the system survived them from the only vantage point that
matters — what clients were told. Three parts:

  history.py   append-only invoke/ok/fail recorder for client operations
               (writes, reads, deletes, DDL), session-tagged, ordered by
               a logical event index — no wall-clock dependence, so
               verdicts replay identically across machines and runs.
  checker.py   invariants over a history + the post-recovery observed
               state: no-lost-acked-write, no-resurrection, per-session
               monotonic reads / read-your-writes, matview-vs-scan
               parity, checksum-group convergence.
  workload.py  the canonical single-node write→flush→compact→tier→matview
               workload, runnable as a subprocess so an injected ``crash``
               (os._exit) kills a real process mid-step; verify() reopens
               the same directories (recovery) and runs the checker.
  sweep.py     exhaustive crash-point sweep: a ``noop`` probe pass learns
               how many times each registered FAULT_POINT is crossed, then
               every (point, nth) pair gets its own fresh run with
               ``crash`` armed — restart, recover, check.
  nemesis.py   seeded deterministic fault schedules (partition,
               crash-restart, delay storm, corrupt) composed over the
               multi-process cluster harness via the `_faults` RPC.

Every verdict and recovery timing lands here, exported on /metrics as
``cnosdb_chaos_total{check,verdict}`` and recovery-time gauges.
"""
from __future__ import annotations

from ..utils import lockwatch

_lock = lockwatch.Lock("chaos.counters")
_verdicts: dict[tuple[str, str], int] = {}
_recovery: dict[str, float] = {}


def note_verdict(check: str, ok: bool) -> None:
    key = (check, "pass" if ok else "fail")
    with _lock:
        _verdicts[key] = _verdicts.get(key, 0) + 1


def note_recovery(kind: str, seconds: float) -> None:
    """Latest recovery duration per kind (e.g. crash→first successful
    full read) — a gauge, not a counter: the current answer to "how long
    does recovery take", refreshed by every measured recovery."""
    with _lock:
        _recovery[kind] = float(seconds)


def chaos_snapshot() -> dict[tuple[str, str], int]:
    """(check, verdict) → count, for /metrics cnosdb_chaos_total."""
    with _lock:
        return dict(_verdicts)


def recovery_snapshot() -> dict[str, float]:
    """kind → seconds, for the /metrics recovery gauges."""
    with _lock:
        return dict(_recovery)


def counters_reset() -> None:
    with _lock:
        _verdicts.clear()
        _recovery.clear()
