"""Consistency checks over a client history + post-recovery observations.

Every check is a pure function: history (and observed state) in, a
CheckResult out — no I/O, no clocks, so a failing verdict replays
identically from the recorded artifacts alone.

Key model (kept deliberately small so verdicts are airtight):
  - a row is identified by an opaque string key chosen by the workload
    (e.g. "h1:120000000000"); each key is written at most once and
    deleted at most once across the whole history (the workloads
    guarantee this), so "the write of key k" is unambiguous.
  - write/delete invokes carry {"keys": [...]}; read oks carry
    {"keys": [...]} (what the client actually saw).
  - an invoke with no outcome is ambiguous: its effects are allowed in
    the observed state but never required.

Checks:
  no-lost-acked-write    every acked write's keys survive to the final
                         observed state unless a delete targeted them
  no-resurrection        acked-deleted keys never reappear; nor do keys
                         no write (even an ambiguous one) ever produced
  read-your-writes       a session's read sees every key that session
                         acked-wrote earlier (minus delete targets)
  monotonic-reads        within a session, each read over the monotonic
                         probe space contains the previous one (minus
                         delete targets)
  matview-parity         view-rewrite rows == raw-scan rows, bit-exact
  checksum-convergence   all replicas report the same per-group checksum
"""
from __future__ import annotations

from dataclasses import dataclass

from . import note_verdict
from .history import History
from ..utils import stages


@dataclass
class CheckResult:
    name: str
    ok: bool
    detail: str = ""

    def __bool__(self) -> bool:
        return self.ok


def _sample(keys, n: int = 5) -> str:
    ks = sorted(keys)
    extra = f" (+{len(ks) - n} more)" if len(ks) > n else ""
    return ", ".join(ks[:n]) + extra


def _delete_targets(history: History, before_e: int | None = None) -> set:
    """Keys any delete *attempted* (invoke, acked or not) — a key in this
    set may legitimately be absent later, whatever the delete's fate."""
    out: set = set()
    for op in history.by_op("delete"):
        if before_e is None or op.invoke_e < before_e:
            out.update(op.data.get("keys", ()))
    return out


def check_no_lost_acked_writes(history: History, observed: set,
                               before_ts: float | None = None) -> CheckResult:
    """`before_ts` (wall seconds) restricts the obligation to writes
    acked at-or-before that instant — the point-in-time-restore form: a
    restore to T (or to the archived watermark after total node loss)
    owes only the writes acked by then. The bound is conservative: an
    ok event's stamp lands *after* the durable append it acknowledges,
    so ok_ts ≤ watermark implies the write's entries are archived. An
    ok event with no stamp (older history format) stays required."""
    acked: set = set()
    for op in history.by_op("write"):
        if not op.acked:
            continue
        if before_ts is not None and op.outcome_ts is not None \
                and op.outcome_ts > before_ts:
            continue
        acked.update(op.data.get("keys", ()))
    lost = acked - observed - _delete_targets(history)
    return CheckResult(
        "no_lost_acked_writes", not lost,
        f"{len(lost)} acked keys missing after recovery: {_sample(lost)}"
        if lost else f"{len(acked)} acked keys all present")


def check_no_resurrection(history: History, observed: set) -> CheckResult:
    # every key any write may have produced — even a "fail"/ambiguous
    # write may have partially landed before its error surfaced, so rows
    # from it are not resurrections
    written: set = set()
    for op in history.by_op("write"):
        written.update(op.data.get("keys", ()))
    acked_deleted: set = set()
    for op in history.by_op("delete"):
        if op.acked:
            acked_deleted.update(op.data.get("keys", ()))
    undead = observed & acked_deleted
    from_nowhere = observed - written
    bad = undead | from_nowhere
    detail = []
    if undead:
        detail.append(f"{len(undead)} acked-deleted keys reappeared: "
                      f"{_sample(undead)}")
    if from_nowhere:
        detail.append(f"{len(from_nowhere)} keys observed that no write "
                      f"produced: {_sample(from_nowhere)}")
    return CheckResult("no_resurrection", not bad,
                       "; ".join(detail) or
                       f"{len(acked_deleted)} deleted keys stayed gone")


def check_read_your_writes(history: History) -> CheckResult:
    bad: list[str] = []
    for session in history.sessions():
        mine = [o for o in history.ops if o.session == session]
        for read in mine:
            if read.op != "read" or not read.acked:
                continue
            seen = set(read.ok_data.get("keys", ()))
            due: set = set()
            for w in mine:
                if w.op == "write" and w.acked \
                        and w.outcome_e < read.invoke_e:
                    due.update(w.data.get("keys", ()))
            missing = due - seen - _delete_targets(history, read.invoke_e)
            if missing:
                bad.append(f"session {session} read e={read.invoke_e} "
                           f"missed own acked keys {_sample(missing)}")
    return CheckResult("read_your_writes", not bad, "; ".join(bad[:3]))


def check_monotonic_reads(history: History) -> CheckResult:
    """Reads tagged mono=True in their invoke form each session's probe
    sequence; each must contain its predecessor (minus delete targets)."""
    bad: list[str] = []
    for session in history.sessions():
        prev: set | None = None
        prev_e = -1
        for read in history.ops:
            if read.session != session or read.op != "read" \
                    or not read.data.get("mono") or not read.acked:
                continue
            seen = set(read.ok_data.get("keys", ()))
            if prev is not None:
                gone = prev - seen - _delete_targets(history)
                if gone:
                    bad.append(f"session {session}: read e={read.invoke_e}"
                               f" lost keys seen at e={prev_e}: "
                               f"{_sample(gone)}")
            prev, prev_e = seen, read.invoke_e
    return CheckResult("monotonic_reads", not bad, "; ".join(bad[:3]))


def check_matview_parity(view_rows, scan_rows) -> CheckResult:
    a = sorted(map(repr, view_rows))
    b = sorted(map(repr, scan_rows))
    ok = a == b
    detail = "" if ok else (f"view={len(a)} rows, scan={len(b)} rows; "
                            f"first diff: "
                            f"{next((x for x, y in zip(a, b) if x != y), 'length')}")
    return CheckResult("matview_parity", ok, detail)


def check_checksum_convergence(per_node: dict) -> CheckResult:
    """per_node: node_id → {group_key → checksum}. All nodes holding a
    group must agree on its checksum (anti-entropy has converged)."""
    diverged = []
    groups: set = set()
    for sums in per_node.values():
        groups.update(sums)
    for g in sorted(groups):
        vals = {n: sums[g] for n, sums in per_node.items() if g in sums}
        if len(set(vals.values())) > 1:
            diverged.append(f"{g}: {vals}")
    return CheckResult("checksum_convergence", not diverged,
                       "; ".join(diverged[:3]) or
                       f"{len(groups)} groups converged")


def run_client_checks(history: History, observed: set,
                      before_ts: float | None = None) -> list[CheckResult]:
    """The four history-only invariants, in severity order. `before_ts`
    bounds the no-lost-acked-writes obligation for point-in-time
    restores (see check_no_lost_acked_writes)."""
    return [check_no_lost_acked_writes(history, observed, before_ts),
            check_no_resurrection(history, observed),
            check_read_your_writes(history),
            check_monotonic_reads(history)]


def book(results: list[CheckResult]) -> list[CheckResult]:
    """Fold verdicts into the chaos counters (→ /metrics) and the stage
    counter; returns `results` unchanged for chaining."""
    for r in results:
        note_verdict(r.name, r.ok)
        stages.count("chaos.checks")
    return results
