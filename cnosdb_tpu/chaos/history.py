"""Client-op history: an append-only JSONL of invoke/ok/fail events.

The recorder is the only wall-clock-free ground truth a consistency
checker can trust: each event carries a monotonically increasing logical
index `e` (file order == happens-before as the client saw it), a session
id `s`, and for outcome events the index `of` of the invoke they resolve.
Mutating invokes are fsync'd *before* the operation executes — otherwise
a crash could apply a write whose invoke record died in the page cache,
and the checker would misread the surviving row as a resurrection.

The file itself is crash-exposed (that is the point), so the loader
tolerates a torn tail: a trailing line that does not parse is dropped,
anything before it must parse. An invoke with no outcome is *ambiguous* —
the operation may or may not have been applied — and every check treats
it that way (allowed but never required).
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


class HistoryRecorder:
    """Append-only writer. Not thread-safe per instance by design — one
    recorder per client session thread, or callers serialize; the nemesis
    driver gives each session its own recorder over the same file via
    `shared_lock`."""

    def __init__(self, path: str, lock=None):
        self.path = path
        self._lock = lock
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")
        # continue the index after a restart: events already on disk keep
        # their positions, new ones append after them
        self._n = sum(1 for _ in History.load(path).events)

    def _emit(self, ev: dict, durable: bool) -> int:
        if self._lock is not None:
            with self._lock:
                return self._emit_locked(ev, durable)
        return self._emit_locked(ev, durable)

    def _emit_locked(self, ev: dict, durable: bool) -> int:
        ev["e"] = self._n
        # wall-clock stamp: checks never ORDER by it (the index `e` is
        # happens-before), but the DR checker compares ok-event times
        # against the archived watermark to bound which acked writes a
        # point-in-time restore must preserve
        import time

        ev["ts"] = time.time()
        self._n += 1
        self._f.write(json.dumps(ev, separators=(",", ":")).encode() + b"\n")
        self._f.flush()
        if durable:
            os.fsync(self._f.fileno())
        return ev["e"]

    def invoke(self, session: str, op: str, durable: bool = True,
               **data) -> int:
        """Record an operation about to start; returns its event index.
        `durable` must stay True for mutating ops (see module doc)."""
        return self._emit({"s": session, "t": "invoke", "op": op, **data},
                          durable)

    def ok(self, session: str, of: int, **data) -> int:
        return self._emit({"s": session, "t": "ok", "of": of, **data},
                          durable=False)

    def fail(self, session: str, of: int, err: str = "") -> int:
        return self._emit({"s": session, "t": "fail", "of": of,
                           "err": err[:200]}, durable=False)

    def close(self) -> None:
        self._f.close()


@dataclass
class Op:
    """One invoke joined to its outcome (if any)."""
    op: str
    session: str
    invoke_e: int
    data: dict
    outcome: str | None = None      # "ok" | "fail" | None (ambiguous)
    outcome_e: int = -1
    outcome_ts: float | None = None  # wall time of the outcome event
    ok_data: dict = field(default_factory=dict)

    @property
    def acked(self) -> bool:
        return self.outcome == "ok"


class History:
    """Parsed history: raw `events` plus invoke/outcome-joined `ops`."""

    def __init__(self, events: list[dict]):
        self.events = events
        by_e: dict[int, Op] = {}
        for ev in events:
            if ev.get("t") == "invoke":
                data = {k: v for k, v in ev.items()
                        if k not in ("e", "s", "t", "op", "ts")}
                by_e[ev["e"]] = Op(op=ev.get("op", "?"), session=ev["s"],
                                   invoke_e=ev["e"], data=data)
        for ev in events:
            t = ev.get("t")
            if t not in ("ok", "fail"):
                continue
            inv = by_e.get(ev.get("of", -1))
            if inv is None or inv.outcome is not None:
                continue
            inv.outcome = t
            inv.outcome_e = ev["e"]
            inv.outcome_ts = ev.get("ts")
            if t == "ok":
                inv.ok_data = {k: v for k, v in ev.items()
                               if k not in ("e", "s", "t", "of", "ts")}
        self.ops = sorted(by_e.values(), key=lambda o: o.invoke_e)

    @classmethod
    def load(cls, path: str) -> "History":
        events: list[dict] = []
        try:
            with open(path, "rb") as f:
                lines = f.read().split(b"\n")
        except FileNotFoundError:
            return cls([])
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                # only the final line may be torn — a parse failure with
                # more data after it means the file is corrupt, not torn,
                # and the checker must not silently drop evidence
                if any(l.strip() for l in lines[i + 1:]):
                    raise
                break
        return cls(events)

    def by_op(self, *names: str) -> list[Op]:
        return [o for o in self.ops if o.op in names]

    def sessions(self) -> list[str]:
        return sorted({o.session for o in self.ops})
