"""Exhaustive crash-point sweep over the FAULT_POINTS registry.

Two passes. The **probe** pass arms the ``noop`` action at every
node-scope registered point and runs the canonical workload once to
completion: the fired log it leaves behind is the exact ordered sequence
of fault-point crossings, i.e. for each point the number k of times the
workload crosses it. The **crash** pass then runs one fresh workload per
(point, nth ≤ k) pair with ``crash`` armed — the subprocess dies with
os._exit at precisely that crossing — and recovery is judged by
reopening the directory and running the consistency checker.

Coverage is a gate, not a report: a node-scope point the probe never
crosses means the canonical workload silently stopped exercising part of
the storage lifecycle, and the sweep fails. Cluster-scope points (RPC,
meta raft) cannot crash a single-process workload meaningfully; they are
exercised by the nemesis suite in tests/test_chaos_cluster.py.

Every run's spec is a one-command reproduction::

    CNOSDB_FAULTS='seed=7;wal.append:crash:nth=3' \
        python -m cnosdb_tpu.chaos.workload run /tmp/dir
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

from .. import faults
from ..utils import stages
from . import workload

CRASH_RC = 137          # faults.fire's os._exit code
RUN_TIMEOUT = 180.0
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# points whose first crossing happens early in the workload (pre-matview,
# so the subprocess stays cheap) — the fast tier-1 subset; the issue's
# named torn-state candidates (tiering registry, matview persist) ride in
# the full sweep
FAST_POINTS = ("wal.append", "flush.run", "tiering.registry",
               "backup.archive", "memory.spill")


def node_points() -> list[str]:
    """All node-scope registered fault points, importing every hook module
    so their register_point calls have run."""
    import cnosdb_tpu.parallel.net                 # noqa: F401
    import cnosdb_tpu.parallel.meta_service        # noqa: F401
    import cnosdb_tpu.server.serving               # noqa: F401
    import cnosdb_tpu.sql.executor                 # noqa: F401
    import cnosdb_tpu.sql.matview                  # noqa: F401
    import cnosdb_tpu.storage.backup               # noqa: F401
    import cnosdb_tpu.storage.compaction           # noqa: F401
    import cnosdb_tpu.storage.flush                # noqa: F401
    import cnosdb_tpu.storage.record_file          # noqa: F401
    import cnosdb_tpu.storage.scrub                # noqa: F401
    import cnosdb_tpu.storage.tiering              # noqa: F401
    import cnosdb_tpu.storage.tsm                  # noqa: F401
    import cnosdb_tpu.storage.wal                  # noqa: F401
    import cnosdb_tpu.utils.objstore               # noqa: F401

    return sorted(faults.registered_points(scope="node"))


def repro_command(spec: str, root: str) -> str:
    return (f"CNOSDB_FAULTS='{spec}' {os.path.basename(sys.executable)} "
            f"-m cnosdb_tpu.chaos.workload run {root}")


def _run_workload(root: str, spec: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["CNOSDB_FAULTS"] = spec
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("CNOSDB_MATVIEW_AUTO", "0")
    return subprocess.run(
        [sys.executable, "-m", "cnosdb_tpu.chaos.workload", "run", root],
        env=env, cwd=_REPO, capture_output=True, text=True,
        timeout=RUN_TIMEOUT)


def probe(base: str, seed: int = 7,
          points: list[str] | None = None) -> dict[str, int]:
    """Run the workload once with noop armed everywhere → point → number
    of crossings. Raises on an unclean probe (it must run to completion
    with noop faults: they change nothing)."""
    pts = points if points is not None else node_points()
    spec = f"seed={seed};" + ";".join(f"{p}:noop" for p in pts)
    root = os.path.join(base, "probe")
    p = _run_workload(root, spec)
    if p.returncode != 0:
        raise RuntimeError(
            f"probe workload failed rc={p.returncode}\n"
            f"repro: {repro_command(spec, root)}\n{p.stdout}\n{p.stderr}")
    with open(os.path.join(root, workload.TRACE), encoding="utf-8") as f:
        fired = json.load(f)["fired"]
    hits: dict[str, int] = {pt: 0 for pt in pts}
    for point, _action, _hit in fired:
        hits[point] = hits.get(point, 0) + 1
    return hits


def run_one(base: str, point: str, nth: int, seed: int = 7) -> dict:
    """One crash run: fresh dir, crash armed at (point, nth), then verify
    (recovery + checker) in-process."""
    spec = f"seed={seed};{point}:crash:nth={nth}"
    root = os.path.join(base, f"{point.replace('.', '_')}_{nth}")
    p = _run_workload(root, spec)
    stages.count("chaos.crash_sites")
    out = {"point": point, "nth": nth, "spec": spec, "root": root,
           "rc": p.returncode, "crashed": p.returncode == CRASH_RC,
           "repro": repro_command(spec, root)}
    if p.returncode not in (0, CRASH_RC):
        out.update(ok=False, error=(p.stderr or p.stdout)[-2000:])
        return out
    v = workload.verify(root)
    out.update(ok=all(r.ok for r in v["results"]),
               mttr_s=round(v["mttr_s"], 3), observed=v["observed"],
               results=[[r.name, r.ok, r.detail] for r in v["results"]])
    return out


def run_sweep(base: str, points: list[str] | None = None,
              nth_cap: int = 2, seed: int = 7) -> dict:
    """Probe, then crash every (point, nth ≤ min(k, nth_cap)) pair.

    → {"seed", "coverage": {...}, "runs": [...], "failed": [...]} where
    `failed` collects runs whose recovery or checker went wrong, each
    carrying its one-command repro string."""
    registered = points if points is not None else node_points()
    hits = probe(base, seed=seed, points=registered)
    uncovered = sorted(p for p in registered if hits.get(p, 0) == 0)
    runs = []
    for point in registered:
        for nth in range(1, min(hits.get(point, 0), nth_cap) + 1):
            runs.append(run_one(base, point, nth, seed=seed))
    failed = [r for r in runs if not r.get("ok") or not r.get("crashed")]
    return {"seed": seed,
            "coverage": {"registered": len(registered),
                         "crossed": sum(1 for p in registered
                                        if hits.get(p, 0)),
                         "hits": hits, "uncovered": uncovered},
            "runs": runs, "failed": failed}


def restore_bench(base: str, rows: int = 2000) -> dict:
    """Disaster-recovery MTTR for bench.py: seed a small database with
    WAL archiving on, BACKUP it, destroy the data directory (total node
    loss), RESTORE from the archive, and report the restore wall time.
    This is the recovery-time half of the DR story; the data-loss half
    is bounded by the archive_lag_seconds gauge."""
    import shutil
    import time

    from ..parallel.coordinator import Coordinator
    from ..parallel.meta import MetaStore
    from ..sql.executor import QueryExecutor
    from ..storage import backup
    from ..storage.engine import TsKv

    root = os.path.join(base, "restore_bench")
    data = os.path.join(root, "data")
    backup.configure_archive(os.path.join(root, "archive"))
    try:
        meta = MetaStore(os.path.join(root, "meta.json"))
        engine = TsKv(data)
        ex = QueryExecutor(meta, Coordinator(meta, engine))
        ex.execute_one("CREATE TABLE r (v DOUBLE, TAGS(h))")
        step = 500
        for lo in range(0, rows, step):
            vals = ",".join(f"({t},'h',{float(t)})"
                            for t in range(lo, min(lo + step, rows)))
            ex.execute_one(f"INSERT INTO r (time, h, v) VALUES {vals}")
        ex.execute_one("BACKUP DATABASE public")
        for a in backup.archivers():
            a.wal.seal_active()
            a.catch_up()
        engine.close()
        shutil.rmtree(data)
        t0 = time.monotonic()
        engine2 = TsKv(data)
        ex2 = QueryExecutor(meta, Coordinator(meta, engine2))
        ex2.coord.restore_database("cnosdb", "public")
        restore_s = time.monotonic() - t0
        rs = ex2.execute_one("SELECT COUNT(v) FROM r")
        n = int(rs.columns[0][0])
        engine2.close()
        return {"rows": rows, "restored_rows": n,
                "restore_mttr_s": round(restore_s, 3), "ok": n == rows}
    finally:
        backup.configure_archive(None)


def bench_block(base: str, seed: int = 7) -> dict:
    """Compact summary for bench.py's final JSON: the fast subset's MTTR
    and checker verdicts, plus the total-loss restore MTTR."""
    runs = [run_one(base, p, 1, seed=seed) for p in FAST_POINTS]
    verdicts: dict[str, str] = {}
    for r in runs:
        for name, ok, _detail in r.get("results", ()):
            if verdicts.get(name) != "fail":
                verdicts[name] = "pass" if ok else "fail"
    mttrs = [r["mttr_s"] for r in runs if "mttr_s" in r]
    try:
        restore = restore_bench(base)
    except Exception as e:   # DR bench failure must not sink the block
        stages.count_error("swallow.sweep.restore_bench")
        restore = {"error": repr(e)[:200]}
    return {"seed": seed, "crash_sites": len(runs),
            "all_crashed": all(r["crashed"] for r in runs),
            "mttr_s_max": max(mttrs) if mttrs else None,
            "verdicts": verdicts, "restore": restore,
            "failed": [r["repro"] for r in runs
                       if not r.get("ok") or not r.get("crashed")]}
