"""Seeded nemesis schedules: deterministic fault plans for a live cluster.

A plan is a pure function of (seed, n_nodes, steps, kinds) — the same
seed always yields the same event sequence, so a failure printed with its
seed is a one-line reproduction. Events are *applied* by the caller (the
cluster suite in tests/test_chaos_cluster.py) because only it holds the
harness: partitions and delay storms become CNOSDB_FAULTS specs pushed
over the `_faults` runtime RPC, crash-restarts use the harness's
kill/start, disk corruption arms the scrub.read corrupt action. This
module renders those specs; it never talks to a process itself.
"""
from __future__ import annotations

import random
from dataclasses import dataclass

KINDS = ("partition", "crash_restart", "delay_storm", "corrupt",
         "slow_replica", "memory_pressure", "device_loss")
# disaster-recovery kinds, never mixed into the default rotation: both
# destroy data on purpose (total_loss wipes a node's data dir,
# operator_error drops a whole database) and are only survivable when
# the DR plane (storage/backup.py) is configured — the harness restores
# from the archive store and the checker judges RPO against the
# archived watermark
DR_KINDS = ("total_loss", "operator_error")


@dataclass(frozen=True)
class NemesisEvent:
    step: int
    kind: str       # one of KINDS
    node: int       # victim data-node index
    param: int      # kind-specific: delay ms / bytes to corrupt


def generate_plan(seed: int, n_nodes: int, steps: int = 6,
                  kinds: tuple[str, ...] = KINDS) -> list[NemesisEvent]:
    """Deterministic event sequence; `seed` fully determines it."""
    for k in kinds:
        if k not in KINDS and k not in DR_KINDS:
            raise ValueError(f"unknown nemesis kind {k!r}")
    rng = random.Random(seed)
    plan = []
    for i in range(steps):
        kind = kinds[rng.randrange(len(kinds))]
        plan.append(NemesisEvent(step=i, kind=kind,
                                 node=rng.randrange(n_nodes),
                                 param=rng.choice((20, 50, 120))))
    return plan


def event_specs(ev: NemesisEvent, victim_addr: str,
                seed: int) -> tuple[str, str]:
    """→ (victim node's CNOSDB_FAULTS spec, every other node's spec) for
    the duration of the event; ("", "") means the harness acts directly
    (crash_restart = kill + start, no injection needed)."""
    prefix = f"seed={seed + ev.step};"
    if ev.kind == "partition":
        # victim drops all outbound sends; peers drop sends to the victim
        # — a symmetric partition around one node
        return (prefix + "rpc.send:fail",
                prefix + f"rpc.send:fail:if={victim_addr}")
    if ev.kind == "delay_storm":
        return (prefix + f"rpc.send:delay({ev.param}):prob=0.5",
                prefix + f"rpc.send:delay({ev.param}):prob=0.2,"
                         f"if={victim_addr}")
    if ev.kind == "slow_replica":
        # gray failure: the victim keeps answering every RPC, just
        # slowly — server-side delay before dispatch, deterministic
        # (prob=1) so tail-latency bounds are measurable. Peers stay
        # clean; this is the scenario the hedged-scan plane exists for.
        return (prefix + f"rpc.server:delay({ev.param})", "")
    if ev.kind == "device_loss":
        # kill a mesh participant mid-collective: the mesh exec lane's
        # merge kernel dies on the victim, which must book device_loss
        # and answer through the legacy host/RPC merge — clients see the
        # same answers throughout (the checker holds them to it)
        return (prefix + "mesh.collective:fail", "")
    if ev.kind == "corrupt":
        # flip bytes of the next file the victim's scrubber verifies —
        # at-rest corruption the integrity plane must catch and repair
        return (prefix + f"scrub.read:corrupt({max(1, ev.param // 20)})"
                         f":once", "")
    if ev.kind == "crash_restart" or ev.kind == "memory_pressure" \
            or ev.kind in DR_KINDS:
        # the harness acts directly: kill+start, rm -rf the victim's
        # data dir (total_loss), DROP DATABASE (operator_error) with
        # RESTORE from the archive store, or squeeze/restore the
        # victim's memory-broker budget over the `_memory` runtime RPC
        # (memory_pressure) — no fault-spec injection needed
        return ("", "")
    raise ValueError(f"unknown nemesis kind {ev.kind!r}")


def heal_spec(seed: int, ev: NemesisEvent) -> str:
    """Spec that clears the event's injection but keeps faults armed (the
    harness keeps CNOSDB_FAULTS in the env, so "" would disarm the
    control surface on the next restart — send the bare seed instead)."""
    return f"seed={seed + ev.step}"


def describe(plan: list[NemesisEvent], seed: int) -> str:
    head = f"nemesis seed={seed} ({len(plan)} steps): "
    return head + ", ".join(
        f"#{e.step} {e.kind}@n{e.node}(p={e.param})" for e in plan)
