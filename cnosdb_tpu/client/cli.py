"""`cnosdb-tpu-cli` — interactive SQL REPL over the HTTP API.

Counterpart of the reference's `client/` crate (cnosdb-cli,
client/src/main.rs:188, exec.rs). Grows with the HTTP service.
"""
from __future__ import annotations

import argparse
import sys


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cnosdb-tpu-cli", description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8902)
    p.add_argument("-u", "--user", default="root")
    p.add_argument("-p", "--password", default="")
    p.add_argument("-d", "--database", default="public")
    p.add_argument("--file", help="execute statements from file and exit")
    p.add_argument("-c", "--command", help="execute one statement and exit")
    p.add_argument("--format", default="table",
                   choices=["table", "csv", "tsv", "json"])
    p.add_argument("--dump-ddl", action="store_true",
                   help="print CREATE statements for every database/table "
                        "and exit (reference cnosdb-cli --dump-ddl)")
    return p


def dump_ddl(client) -> int:
    """Emit re-runnable DDL for all databases and tables (reference
    client/src/exec.rs --dump-ddl restore path)."""
    dbs = [r[0] for r in client.sql_rows("SHOW DATABASES")]
    for db in dbs:
        if db in ("usage_schema",):
            continue
        opts = client.sql_rows(f"DESCRIBE DATABASE {db}")
        if opts:
            ttl, shard, vnode_dur, replica, precision = opts[0][:5]
            print(f"CREATE DATABASE IF NOT EXISTS {db} WITH TTL '{ttl}' "
                  f"SHARD {shard} VNODE_DURATION '{vnode_dur}' "
                  f"REPLICA {replica} PRECISION '{precision}';")
        for (tbl,) in client.sql_rows(f"SHOW TABLES ON {db}"):
            cols = client.sql_rows(f"DESCRIBE TABLE {db}.{tbl}")
            tags = [c[0] for c in cols if c[2] == "TAG"]
            fields = [f"{c[0]} {c[1]} CODEC({c[3]})" for c in cols
                      if c[2] == "FIELD"]
            body = ", ".join(fields + [f"TAGS({', '.join(tags)})"])
            print(f"CREATE TABLE IF NOT EXISTS {db}.{tbl} ({body});")
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    from .repl import Client, run_repl

    if args.dump_ddl:
        try:
            return dump_ddl(Client(args.host, args.port, args.user,
                                   args.password, args.database, "csv"))
        except RuntimeError as e:
            print(f"dump-ddl failed: {e}", file=sys.stderr)
            return 1
    return run_repl(args)


if __name__ == "__main__":
    sys.exit(main())
