"""`cnosdb-tpu-cli` — interactive SQL REPL over the HTTP API.

Counterpart of the reference's `client/` crate (cnosdb-cli,
client/src/main.rs:188, exec.rs). Grows with the HTTP service.
"""
from __future__ import annotations

import argparse
import sys


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cnosdb-tpu-cli", description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8902)
    p.add_argument("-u", "--user", default="root")
    p.add_argument("-p", "--password", default="")
    p.add_argument("-d", "--database", default="public")
    p.add_argument("--file", help="execute statements from file and exit")
    p.add_argument("-c", "--command", help="execute one statement and exit")
    p.add_argument("--format", default="table",
                   choices=["table", "csv", "tsv", "json"])
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    from .repl import run_repl

    return run_repl(args)


if __name__ == "__main__":
    sys.exit(main())
