"""Interactive SQL REPL over the HTTP API.

Role-parity with the reference cnosdb-cli (client/src/exec.rs:21-270):
line editing, `\\c db`, `\\w file` line-protocol import, output formats,
file/one-shot execution.
"""
from __future__ import annotations

import base64
import sys
import urllib.error
import urllib.request


class Client:
    def __init__(self, host: str, port: int, user: str, password: str,
                 database: str, fmt: str = "table"):
        self.base = f"http://{host}:{port}"
        self.user = user
        self.password = password
        self.database = database
        self.fmt = fmt

    def _headers(self) -> dict:
        token = base64.b64encode(f"{self.user}:{self.password}".encode()).decode()
        accept = {"table": "text/table", "csv": "application/csv",
                  "tsv": "application/csv", "json": "application/json"}[self.fmt]
        return {"Authorization": f"Basic {token}", "Accept": accept}

    def sql(self, query: str) -> tuple[int, str]:
        req = urllib.request.Request(
            f"{self.base}/api/v1/sql?db={self.database}",
            data=query.encode(), headers=self._headers(), method="POST")
        try:
            with urllib.request.urlopen(req, timeout=300) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()
        except urllib.error.URLError as e:
            return 0, f"connection error: {e}"

    def sql_rows(self, query: str) -> list[list[str]]:
        """CSV-parsed result rows (header stripped) — used by --dump-ddl.
        Failures RAISE: a silent empty result would let a backup script
        store an empty dump with exit code 0."""
        saved, self.fmt = self.fmt, "csv"
        try:
            status, out = self.sql(query)
        finally:
            self.fmt = saved
        if status != 200:
            raise RuntimeError(f"query failed ({status}): {out.strip()}")
        import csv as _csv
        import io as _io

        rows = list(_csv.reader(_io.StringIO(out)))
        return rows[1:] if rows else []

    def write_lines(self, lines: str) -> tuple[int, str]:
        req = urllib.request.Request(
            f"{self.base}/api/v1/write?db={self.database}",
            data=lines.encode(), headers=self._headers(), method="POST")
        try:
            with urllib.request.urlopen(req, timeout=300) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()


def run_repl(args) -> int:
    client = Client(args.host, args.port, args.user, args.password,
                    args.database, args.format)
    if args.command:
        status, out = client.sql(args.command)
        print(out)
        return 0 if status == 200 else 1
    if args.file:
        with open(args.file) as f:
            for stmt in f.read().split(";"):
                if stmt.strip():
                    status, out = client.sql(stmt)
                    print(out)
                    if status != 200:
                        return 1
        return 0
    print(f"cnosdb-tpu-cli connected to {client.base} (db {client.database})")
    print("Type SQL, \\c <db> to switch database, \\w <file> to import line "
          "protocol, \\q to quit.")
    try:
        import readline  # noqa: F401 - enables history/editing
    except ImportError:
        pass
    buf = []
    while True:
        prompt = f"{client.database} ❯ " if not buf else "... "
        try:
            line = input(prompt)
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        s = line.strip()
        if not buf and s.startswith("\\"):
            parts = s.split()
            if parts[0] in ("\\q", "\\quit", "\\exit"):
                return 0
            if parts[0] == "\\c" and len(parts) > 1:
                client.database = parts[1]
                continue
            if parts[0] == "\\w" and len(parts) > 1:
                with open(parts[1]) as f:
                    status, out = client.write_lines(f.read())
                print("ok" if status == 200 else out)
                continue
            if parts[0] == "\\format" and len(parts) > 1:
                client.fmt = parts[1]
                continue
            print(f"unknown command {parts[0]}")
            continue
        buf.append(line)
        if s.endswith(";") or (s and not buf[:-1] and not s.endswith("\\")):
            query = "\n".join(buf).rstrip(";")
            buf = []
            if query.strip():
                _status, out = client.sql(query)
                print(out)
