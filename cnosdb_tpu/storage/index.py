"""Per-vnode series index.

Role-parity with the reference's TSIndex (tskv/src/index/ts_index.rs:84-660):
- forward map: series_id → SeriesKey
- inverted map: (table, tag_key, tag_value) → series-id postings
- `get_series_ids_by_domains` evaluates tag ColumnDomains to a series-id
  array (ts_index.rs:397), the entry point of every tag-filtered scan.

Storage design (the reference uses heed/LMDB + roaring bitmaps,
index/engine2.rs): a periodic CHECKPOINT file holds the whole index as
columnar sections — sorted series-id array, concatenated encoded keys with
offsets, sorted key-hash array for O(log n) id lookup, and per-(table,tag)
sorted value dictionaries pointing into one big u64 postings region. The
file is mmapped; postings and value dictionaries are np.frombuffer slices
materialized lazily, so opening a vnode with 1M series reads only the
small header. Mutations append to a CRC'd binlog (storage/record_file.py)
and live in small overlay dicts; open = load checkpoint + replay the
binlog TAIL (rotated at each checkpoint), not the full history — the
incremental-checkpoint contract of the reference's LMDB write-back cache.

Postings math uses sorted numpy arrays end to end, which is the shape the
scan layer wants anyway (roaring-style compression is unnecessary: 64-bit
sorted arrays beat python sets by ~20× memory and vectorize).
"""
from __future__ import annotations

import mmap
import os
import struct

import msgpack
import numpy as np

from ..errors import IndexError_
from ..models.predicate import (
    AllDomain, ColumnDomains, Domain, LikeDomain, NoneDomain, RangeDomain,
    SetDomain,
)
from ..models.series import SeriesKey
from .record_file import RecordReader, RecordWriter

_OP_ADD = 1
_OP_DEL = 2

_CKPT_MAGIC = 0x1D45C0DE
_CKPT_VERSION = 1
CKPT_NAME = "index.ckpt"

# binlog tail entries that trigger a background-ish checkpoint on the
# write path (amortized: rewriting N series costs O(N) once per threshold)
CKPT_THRESHOLD = 200_000


class _Checkpoint:
    """Read view over one checkpoint file (mmap + lazy numpy slices)."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        self.mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        magic, version, hlen = struct.unpack_from("<III", self.mm, 0)
        if magic != _CKPT_MAGIC:
            raise IndexError_(f"bad index checkpoint magic in {path}")
        if version != _CKPT_VERSION:
            raise IndexError_(f"unsupported index checkpoint v{version}")
        self.h = msgpack.unpackb(self.mm[12:12 + hlen], raw=False)
        self.n = self.h["n"]
        self.next_sid = self.h["next_sid"]
        base = 12 + hlen
        sec = self.h["sections"]

        def arr(name, dtype):
            off, ln = sec[name]
            return np.frombuffer(self.mm, dtype=dtype, count=ln,
                                 offset=base + off)

        self.sids = arr("sids", np.uint64)            # sorted
        self.key_offs = arr("key_offs", np.uint64)    # [n+1]
        kb_off, kb_len = sec["key_blob"]
        self._kb_base = base + kb_off
        self.hashes = arr("hashes", np.uint64)        # sorted
        self.hash_perm = arr("hash_perm", np.uint32)  # hash idx → row idx
        self._post_base = base + sec["postings"][0]
        self.tables = self.h["tables"]
        # lazy caches
        self._tag_dict_cache: dict = {}

    def close(self):
        try:
            self.mm.close()
        except BufferError:
            # numpy views over the mmap are still alive (postings handed to
            # a scan); the map is reclaimed when the last view dies
            pass
        self._f.close()

    # -- forward ----------------------------------------------------------
    def key_bytes_at(self, row: int) -> bytes:
        lo, hi = int(self.key_offs[row]), int(self.key_offs[row + 1])
        return self.mm[self._kb_base + lo:self._kb_base + hi]

    def row_of_sid(self, sid: int) -> int | None:
        i = int(np.searchsorted(self.sids, np.uint64(sid)))
        if i < self.n and self.sids[i] == sid:
            return i
        return None

    def lookup(self, key: SeriesKey) -> int | None:
        kb = key.encode()
        h = np.uint64(key.hash_id())
        i = int(np.searchsorted(self.hashes, h))
        while i < self.n and self.hashes[i] == h:
            row = int(self.hash_perm[i])
            if self.key_bytes_at(row) == kb:
                return int(self.sids[row])
            i += 1
        return None

    # -- postings ---------------------------------------------------------
    def postings(self, off: int, cnt: int) -> np.ndarray:
        return np.frombuffer(self.mm, dtype=np.uint64, count=cnt,
                             offset=self._post_base + off * 8)

    def table_sids(self, table: str) -> np.ndarray:
        t = self.tables.get(table)
        if t is None:
            return np.empty(0, dtype=np.uint64)
        off, cnt = t["all"]
        return self.postings(off, cnt)

    def _tag(self, table: str, tag_key: str):
        """→ (value_offsets u64[V+1], values_blob memoryview,
        posting_offsets u64[V+1], base_posting_off) or None."""
        ck = (table, tag_key)
        hit = self._tag_dict_cache.get(ck)
        if hit is not None:
            return hit
        t = self.tables.get(table)
        if t is None or tag_key not in t["tags"]:
            return None
        m = t["tags"][tag_key]
        base = 12 + struct.unpack_from("<I", self.mm, 8)[0]
        voff = np.frombuffer(self.mm, dtype=np.uint64, count=m["nv"] + 1,
                             offset=base + m["voffs"])
        poff = np.frombuffer(self.mm, dtype=np.uint64, count=m["nv"] + 1,
                             offset=base + m["poffs"])
        entry = (voff, base + m["vblob"], poff)
        self._tag_dict_cache[ck] = entry
        return entry

    def _value_at(self, voff, vblob_base, i: int) -> str:
        lo, hi = int(voff[i]), int(voff[i + 1])
        return self.mm[vblob_base + lo:vblob_base + hi].decode()

    def tag_value_sids(self, table: str, tag_key: str, value: str) -> np.ndarray:
        tag = self._tag(table, tag_key)
        if tag is None:
            return np.empty(0, dtype=np.uint64)
        voff, vb, poff = tag
        nv = len(voff) - 1
        lo, hi = 0, nv
        while lo < hi:  # binary search over the sorted value dictionary
            mid = (lo + hi) // 2
            if self._value_at(voff, vb, mid) < value:
                lo = mid + 1
            else:
                hi = mid
        if lo < nv and self._value_at(voff, vb, lo) == value:
            return self.postings(int(poff[lo]), int(poff[lo + 1] - poff[lo]))
        return np.empty(0, dtype=np.uint64)

    def tag_all_sids(self, table: str, tag_key: str) -> np.ndarray:
        """Union of every value's postings = one contiguous slice."""
        tag = self._tag(table, tag_key)
        if tag is None:
            return np.empty(0, dtype=np.uint64)
        voff, _vb, poff = tag
        out = self.postings(int(poff[0]), int(poff[-1] - poff[0]))
        return np.unique(out)

    def tag_values(self, table: str, tag_key: str) -> list[str]:
        tag = self._tag(table, tag_key)
        if tag is None:
            return []
        voff, vb, _poff = tag
        return [self._value_at(voff, vb, i) for i in range(len(voff) - 1)]

    def tag_keys(self, table: str) -> list[str]:
        t = self.tables.get(table)
        return sorted(t["tags"].keys()) if t else []

    def has_tag(self, table: str, tag_key: str) -> bool:
        t = self.tables.get(table)
        return t is not None and tag_key in t["tags"]

    def tag_items(self, table: str, tag_key: str):
        """Iterate (value, postings) pairs — range-domain evaluation."""
        tag = self._tag(table, tag_key)
        if tag is None:
            return
        voff, vb, poff = tag
        for i in range(len(voff) - 1):
            yield (self._value_at(voff, vb, i),
                   self.postings(int(poff[i]), int(poff[i + 1] - poff[i])))


class TSIndex:
    def __init__(self, dir_path: str):
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        self._binlog_path = os.path.join(dir_path, "index.binlog")
        self._ckpt_path = os.path.join(dir_path, CKPT_NAME)
        self._ckpt: _Checkpoint | None = None
        # overlay: mutations since the checkpoint
        self._forward: dict[int, SeriesKey] = {}
        self._by_key: dict[SeriesKey, int] = {}
        self._inverted: dict[str, dict[str, dict[str, set[int]]]] = {}
        self._by_table: dict[str, set[int]] = {}
        self._deleted: set[int] = set()          # deleted checkpoint sids
        self._key_cache: dict[int, SeriesKey] = {}  # decoded ckpt keys
        self._next_sid = 1
        self._tail_count = 0
        if os.path.exists(self._ckpt_path):
            self._ckpt = _Checkpoint(self._ckpt_path)
            self._next_sid = self._ckpt.next_sid
        if os.path.exists(self._binlog_path):
            self._replay()
        self._binlog = RecordWriter(self._binlog_path)

    # -- recovery --------------------------------------------------------
    def _replay(self):
        if os.path.getsize(self._binlog_path) == 0:
            return  # crash-window artifact of a binlog rotation: harmless
        for payload in RecordReader(self._binlog_path):
            op, sid, key_b = msgpack.unpackb(payload, raw=False)
            if op == _OP_ADD:
                self._insert_mem(sid, SeriesKey.decode(key_b))
            else:
                self._remove_mem(sid)
            self._tail_count += 1

    def _insert_mem(self, sid: int, key: SeriesKey):
        self._deleted.discard(sid)
        self._forward[sid] = key
        self._by_key[key] = sid
        self._by_table.setdefault(key.table, set()).add(sid)
        tbl = self._inverted.setdefault(key.table, {})
        for t in key.tags:
            tbl.setdefault(t.key, {}).setdefault(t.value, set()).add(sid)
        self._next_sid = max(self._next_sid, sid + 1)

    def _remove_mem(self, sid: int):
        key = self._forward.pop(sid, None)
        if key is None:
            # may live in the checkpoint
            key = self._ckpt_key(sid)
            if key is not None:
                self._deleted.add(sid)
                self._key_cache.pop(sid, None)
            return
        self._by_key.pop(key, None)
        self._by_table.get(key.table, set()).discard(sid)
        tbl = self._inverted.get(key.table, {})
        for t in key.tags:
            vals = tbl.get(t.key, {})
            s = vals.get(t.value)
            if s is not None:
                s.discard(sid)
                if not s:
                    del vals[t.value]
        # a sid can live in BOTH overlay and checkpoint (re-keyed after a
        # checkpoint); removing the overlay copy must not let the stale
        # checkpoint row resurrect it
        if self._ckpt is not None and self._ckpt.row_of_sid(sid) is not None:
            self._deleted.add(sid)
            self._key_cache.pop(sid, None)

    def _ckpt_key(self, sid: int) -> SeriesKey | None:
        if self._ckpt is None or sid in self._deleted:
            return None
        hit = self._key_cache.get(sid)
        if hit is not None:
            return hit
        row = self._ckpt.row_of_sid(sid)
        if row is None:
            return None
        key = SeriesKey.decode(self._ckpt.key_bytes_at(row))
        self._key_cache[sid] = key
        return key

    # -- checkpoint ------------------------------------------------------
    def checkpoint(self):
        """Rewrite the full index into a fresh checkpoint + empty binlog
        (incremental-recovery contract: open cost is the tail, not the
        history)."""
        # materialize every live series: checkpoint rows + overlay
        entries: list[tuple[int, bytes]] = []
        if self._ckpt is not None:
            for row in range(self._ckpt.n):
                sid = int(self._ckpt.sids[row])
                if sid in self._deleted or sid in self._forward:
                    continue
                entries.append((sid, bytes(self._ckpt.key_bytes_at(row))))
        for sid, key in self._forward.items():
            entries.append((sid, key.encode()))
        entries.sort()
        n = len(entries)

        sids = np.array([e[0] for e in entries], dtype=np.uint64)
        key_lens = np.array([len(e[1]) for e in entries], dtype=np.uint64)
        key_offs = np.zeros(n + 1, dtype=np.uint64)
        np.cumsum(key_lens, out=key_offs[1:])
        key_blob = b"".join(e[1] for e in entries)

        keys = [SeriesKey.decode(e[1]) for e in entries]
        hashes = np.array([k.hash_id() for k in keys], dtype=np.uint64)
        hash_perm = np.argsort(hashes, kind="stable").astype(np.uint32)
        hashes_sorted = hashes[hash_perm]

        # postings: (table, tag_key, tag_value) → sorted sid arrays, plus
        # per-table all-series postings
        inv: dict[str, dict[str, dict[str, list[int]]]] = {}
        by_table: dict[str, list[int]] = {}
        for (sid, _), k in zip(entries, keys):
            by_table.setdefault(k.table, []).append(sid)
            tbl = inv.setdefault(k.table, {})
            for t in k.tags:
                tbl.setdefault(t.key, {}).setdefault(t.value, []).append(sid)

        postings_parts: list[np.ndarray] = []
        post_off = 0
        tables_meta: dict = {}
        aux = bytearray()   # value dictionaries region (after header)

        def push_postings(sid_list) -> tuple[int, int]:
            nonlocal post_off
            a = np.array(sorted(sid_list), dtype=np.uint64)
            postings_parts.append(a)
            off = post_off
            post_off += len(a)
            return off, len(a)

        for table in sorted(inv):
            t_meta = {"tags": {}}
            t_meta["all"] = list(push_postings(by_table[table]))
            for tag_key in sorted(inv[table]):
                vals = inv[table][tag_key]
                sorted_vals = sorted(vals)
                voffs = np.zeros(len(sorted_vals) + 1, dtype=np.uint64)
                vblob = bytearray()
                poffs = np.zeros(len(sorted_vals) + 1, dtype=np.uint64)
                for i, v in enumerate(sorted_vals):
                    vb = v.encode()
                    vblob += vb
                    voffs[i + 1] = voffs[i] + len(vb)
                    off, cnt = push_postings(vals[v])
                    poffs[i] = off
                    poffs[i + 1] = off + cnt
                tag_meta = {"nv": len(sorted_vals), "voffs": len(aux)}
                aux += voffs.tobytes()
                tag_meta["vblob"] = len(aux)
                aux += bytes(vblob)
                tag_meta["poffs"] = len(aux)
                aux += poffs.tobytes()
                t_meta["tags"][tag_key] = tag_meta
            tables_meta[table] = t_meta

        postings = (np.concatenate(postings_parts) if postings_parts
                    else np.empty(0, dtype=np.uint64))

        # assemble sections after the aux region
        sections = {}
        body = bytearray(aux)

        def add_section(name, raw: bytes, count: int):
            sections[name] = [len(body), count]
            body.extend(raw)

        add_section("sids", sids.tobytes(), n)
        add_section("key_offs", key_offs.tobytes(), n + 1)
        add_section("key_blob", key_blob, len(key_blob))
        add_section("hashes", hashes_sorted.tobytes(), n)
        add_section("hash_perm", hash_perm.tobytes(), n)
        add_section("postings", postings.tobytes(), len(postings))

        header = msgpack.packb({
            "n": n, "next_sid": self._next_sid,
            "tables": tables_meta, "sections": sections,
        }, use_bin_type=True)

        tmp = self._ckpt_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(struct.pack("<III", _CKPT_MAGIC, _CKPT_VERSION,
                                len(header)))
            f.write(header)
            f.write(bytes(body))
            f.flush()
            os.fsync(f.fileno())
        old = self._ckpt
        os.replace(tmp, self._ckpt_path)
        # rotate the binlog: everything up to here is in the checkpoint.
        # The replacement file gets its FILE_MAGIC header and an fsync
        # BEFORE the rename (and the directory after), so a crash in this
        # window can never leave an unopenable header-less binlog
        self._binlog.close()
        blt = self._binlog_path + ".tmp"
        w = RecordWriter(blt)
        w.sync()
        w.close()
        os.replace(blt, self._binlog_path)
        dirfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
        self._binlog = RecordWriter(self._binlog_path)
        self._tail_count = 0
        if old is not None:
            old.close()
        self._ckpt = _Checkpoint(self._ckpt_path)
        # overlay is now fully contained in the checkpoint
        self._forward.clear()
        self._by_key.clear()
        self._inverted.clear()
        self._by_table.clear()
        self._deleted.clear()
        self._key_cache.clear()

    def _maybe_checkpoint(self):
        # adaptive: rewrite cost is O(total), so demand the tail be a
        # constant fraction of it — amortized O(log n) rewrites per series
        # instead of O(n/threshold)
        total = self._ckpt.n if self._ckpt is not None else 0
        if self._tail_count >= max(CKPT_THRESHOLD, total // 2):
            self.checkpoint()

    # -- write path ------------------------------------------------------
    def add_series_if_not_exists(self, key: SeriesKey) -> int:
        """→ series id (existing or newly assigned).
        Reference ts_index.rs:148."""
        sid = self.get_series_id(key)
        if sid is not None:
            return sid
        sid = self._next_sid
        self._binlog.append(msgpack.packb([_OP_ADD, sid, key.encode()]))
        self._insert_mem(sid, key)
        self._tail_count += 1
        self._maybe_checkpoint()
        return sid

    def add_batch(self, keys: list[SeriesKey]) -> np.ndarray:
        return np.array([self.add_series_if_not_exists(k) for k in keys],
                        dtype=np.uint64)

    def del_series(self, sid: int):
        if sid in self._forward or (self._ckpt is not None
                                    and self._ckpt_key(sid) is not None):
            self._binlog.append(msgpack.packb([_OP_DEL, sid, b""]))
            self._remove_mem(sid)
            self._tail_count += 1
            self._maybe_checkpoint()

    def rename_series(self, sid: int, new_key: SeriesKey):
        """Re-key an existing series id (UPDATE <tag> path)."""
        if self.get_series_key(sid) is None:
            raise IndexError_(f"unknown series id {sid}")
        self._binlog.append(msgpack.packb([_OP_DEL, sid, b""]))
        self._remove_mem(sid)
        self._binlog.append(msgpack.packb([_OP_ADD, sid, new_key.encode()]))
        self._insert_mem(sid, new_key)
        self._tail_count += 2
        self._maybe_checkpoint()

    def sync(self):
        self._binlog.sync()

    def close(self):
        self._binlog.close()
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None

    # -- read path -------------------------------------------------------
    def get_series_key(self, sid: int) -> SeriesKey | None:
        key = self._forward.get(sid)
        if key is not None:
            return key
        return self._ckpt_key(sid)

    def get_series_id(self, key: SeriesKey) -> int | None:
        sid = self._by_key.get(key)
        if sid is not None:
            return sid
        if self._ckpt is not None:
            sid = self._ckpt.lookup(key)
            if sid is not None and sid not in self._deleted \
                    and sid not in self._forward:
                return sid
        return None

    def series_count(self) -> int:
        n = len(self._forward)
        if self._ckpt is not None:
            # overlay may re-key checkpoint sids; count distinct live ids
            ck = self._ckpt.n - len(self._deleted)
            overlap = sum(1 for s in self._forward
                          if self._ckpt.row_of_sid(s) is not None
                          and s not in self._deleted)
            n += ck - overlap
        return n

    def _combine(self, ckpt_arr: np.ndarray, overlay: set[int]) -> np.ndarray:
        """checkpoint postings − deleted/re-keyed + overlay → sorted u64."""
        parts = []
        if len(ckpt_arr):
            # checkpoint sids that were deleted OR re-keyed since (their
            # postings live in the overlay now) must not surface
            drop = self._deleted
            if self._forward:
                drop = drop | self._forward.keys()
            if drop:
                drop_a = np.fromiter(drop, dtype=np.uint64, count=len(drop))
                ckpt_arr = ckpt_arr[~np.isin(ckpt_arr, drop_a)]
            parts.append(np.asarray(ckpt_arr))
        if overlay:
            parts.append(np.fromiter(overlay, dtype=np.uint64,
                                     count=len(overlay)))
        if not parts:
            return np.empty(0, dtype=np.uint64)
        return np.unique(np.concatenate(parts))

    def table_series_ids(self, table: str) -> np.ndarray:
        ck = (self._ckpt.table_sids(table) if self._ckpt is not None
              else np.empty(0, dtype=np.uint64))
        return self._combine(ck, self._by_table.get(table, set()))

    def tag_values(self, table: str, tag_key: str) -> list[str]:
        vals = set(self._inverted.get(table, {}).get(tag_key, {}).keys())
        if self._ckpt is not None:
            ck_vals = self._ckpt.tag_values(table, tag_key)
            if not self._deleted and not self._forward:
                vals.update(ck_vals)   # nothing can have emptied a value
            else:
                for v in ck_vals:
                    if len(self._value_sids(table, tag_key, v)):
                        vals.add(v)
        return sorted(vals)

    def tag_keys(self, table: str) -> list[str]:
        keys = set(self._inverted.get(table, {}).keys())
        if self._ckpt is not None:
            keys.update(self._ckpt.tag_keys(table))
        return sorted(keys)

    def _value_sids(self, table: str, tag_key: str, value: str) -> np.ndarray:
        ck = (self._ckpt.tag_value_sids(table, tag_key, value)
              if self._ckpt is not None else np.empty(0, dtype=np.uint64))
        ov = self._inverted.get(table, {}).get(tag_key, {}).get(value, set())
        return self._combine(ck, ov)

    def get_series_ids_by_domains(self, table: str,
                                  domains: ColumnDomains) -> np.ndarray:
        """Evaluate tag-column domains → sorted series-id array
        (reference ts_index.rs:397)."""
        if domains.is_none:
            return np.empty(0, dtype=np.uint64)
        if domains.is_all:
            return self.table_series_ids(table)
        result: np.ndarray | None = None
        for tag_key, dom in domains.domains.items():
            known = (tag_key in self._inverted.get(table, {})
                     or (self._ckpt is not None
                         and self._ckpt.has_tag(table, tag_key)))
            if not known:
                # unknown tag constrained: rows have no such tag → for an
                # equality/set constraint nothing matches unless the domain
                # admits absent (we treat absent as no-match, like reference
                # tag=NULL semantics)
                if isinstance(dom, AllDomain):
                    continue
                return np.empty(0, dtype=np.uint64)
            matched = self._eval_tag_domain(table, tag_key, dom)
            result = matched if result is None else \
                np.intersect1d(result, matched, assume_unique=True)
            if not len(result):
                return np.empty(0, dtype=np.uint64)
        if result is None:
            return self.table_series_ids(table)
        return result

    def _eval_tag_domain(self, table: str, tag_key: str,
                         dom: Domain) -> np.ndarray:
        if isinstance(dom, NoneDomain):
            return np.empty(0, dtype=np.uint64)
        if isinstance(dom, SetDomain):
            parts = [self._value_sids(table, tag_key, v) for v in dom.values]
            parts = [p for p in parts if len(p)]
            if not parts:
                return np.empty(0, dtype=np.uint64)
            return np.unique(np.concatenate(parts))
        if isinstance(dom, AllDomain):
            ck = (self._ckpt.tag_all_sids(table, tag_key)
                  if self._ckpt is not None else np.empty(0, dtype=np.uint64))
            ov: set[int] = set()
            for s in self._inverted.get(table, {}).get(tag_key, {}).values():
                ov |= s
            return self._combine(ck, ov)
        if isinstance(dom, RangeDomain):
            vals = set(self._inverted.get(table, {}).get(tag_key, {}).keys())
            if self._ckpt is not None:
                vals.update(self._ckpt.tag_values(table, tag_key))
            parts = [self._value_sids(table, tag_key, v)
                     for v in vals if dom.contains_value(v)]
            parts = [p for p in parts if len(p)]
            if not parts:
                return np.empty(0, dtype=np.uint64)
            return np.unique(np.concatenate(parts))
        if isinstance(dom, LikeDomain):
            # tag LIKE '%x%': the tag value set IS a dictionary — one
            # vectorized per-unique mask (ops/strkernels), then sid unions
            # for the matching values only
            vals = set(self._inverted.get(table, {}).get(tag_key, {}).keys())
            if self._ckpt is not None:
                vals.update(self._ckpt.tag_values(table, tag_key))
            if not vals:
                return np.empty(0, dtype=np.uint64)
            varr = np.empty(len(vals), dtype=object)
            varr[:] = sorted(vals)
            try:
                from ..ops import strkernels

                mask, _reason = strkernels.unique_mask(varr, dom.pattern)
            except ImportError:   # host-only deploy: scalar per-unique
                mask = np.fromiter(
                    (dom.contains_value(v) for v in varr),
                    dtype=bool, count=len(varr))
            parts = [self._value_sids(table, tag_key, v)
                     for v in varr[mask]]
            parts = [p for p in parts if len(p)]
            if not parts:
                return np.empty(0, dtype=np.uint64)
            return np.unique(np.concatenate(parts))
        raise IndexError_(f"unsupported domain {type(dom).__name__}")
