"""Per-vnode series index.

Role-parity with the reference's TSIndex (tskv/src/index/ts_index.rs:84-660):
- forward map: series_id → SeriesKey
- inverted map: (table, tag_key, tag_value) → set of series ids
- `get_series_ids_by_domains` evaluates tag ColumnDomains to a series-id
  array (ts_index.rs:397), the entry point of every tag-filtered scan.

The reference persists through heed/LMDB with roaring bitmaps; here the
index is an in-memory dict-of-sets (vnode series cardinality is bounded by
sharding) persisted via a CRC'd binlog (storage/record_file.py) replayed on
open — same recovery contract, no external KV dependency. Bitmap math uses
sorted numpy arrays at query time, which is the shape the scan layer wants
anyway.
"""
from __future__ import annotations

import os

import msgpack
import numpy as np

from ..errors import IndexError_
from ..models.predicate import (
    AllDomain, ColumnDomains, Domain, NoneDomain, RangeDomain, SetDomain,
)
from ..models.series import SeriesKey
from .record_file import RecordReader, RecordWriter

_OP_ADD = 1
_OP_DEL = 2


class TSIndex:
    def __init__(self, dir_path: str):
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        self._binlog_path = os.path.join(dir_path, "index.binlog")
        self._forward: dict[int, SeriesKey] = {}
        self._by_key: dict[SeriesKey, int] = {}
        self._inverted: dict[str, dict[str, dict[str, set[int]]]] = {}
        self._by_table: dict[str, set[int]] = {}
        self._next_sid = 1
        if os.path.exists(self._binlog_path):
            self._replay()
        self._binlog = RecordWriter(self._binlog_path)

    # -- recovery --------------------------------------------------------
    def _replay(self):
        for payload in RecordReader(self._binlog_path):
            op, sid, key_b = msgpack.unpackb(payload, raw=False)
            if op == _OP_ADD:
                self._insert_mem(sid, SeriesKey.decode(key_b))
            else:
                self._remove_mem(sid)

    def _insert_mem(self, sid: int, key: SeriesKey):
        self._forward[sid] = key
        self._by_key[key] = sid
        self._by_table.setdefault(key.table, set()).add(sid)
        tbl = self._inverted.setdefault(key.table, {})
        for t in key.tags:
            tbl.setdefault(t.key, {}).setdefault(t.value, set()).add(sid)
        self._next_sid = max(self._next_sid, sid + 1)

    def _remove_mem(self, sid: int):
        key = self._forward.pop(sid, None)
        if key is None:
            return
        self._by_key.pop(key, None)
        self._by_table.get(key.table, set()).discard(sid)
        tbl = self._inverted.get(key.table, {})
        for t in key.tags:
            vals = tbl.get(t.key, {})
            s = vals.get(t.value)
            if s is not None:
                s.discard(sid)
                if not s:
                    del vals[t.value]

    # -- write path ------------------------------------------------------
    def add_series_if_not_exists(self, key: SeriesKey) -> int:
        """→ series id (existing or newly assigned).
        Reference ts_index.rs:148."""
        sid = self._by_key.get(key)
        if sid is not None:
            return sid
        sid = self._next_sid
        self._binlog.append(msgpack.packb([_OP_ADD, sid, key.encode()]))
        self._insert_mem(sid, key)
        return sid

    def add_batch(self, keys: list[SeriesKey]) -> np.ndarray:
        return np.array([self.add_series_if_not_exists(k) for k in keys],
                        dtype=np.uint64)

    def del_series(self, sid: int):
        if sid in self._forward:
            self._binlog.append(msgpack.packb([_OP_DEL, sid, b""]))
            self._remove_mem(sid)

    def rename_series(self, sid: int, new_key: SeriesKey):
        """Re-key an existing series id (UPDATE <tag> path)."""
        if sid not in self._forward:
            raise IndexError_(f"unknown series id {sid}")
        self._binlog.append(msgpack.packb([_OP_DEL, sid, b""]))
        self._remove_mem(sid)
        self._binlog.append(msgpack.packb([_OP_ADD, sid, new_key.encode()]))
        self._insert_mem(sid, new_key)

    def sync(self):
        self._binlog.sync()

    def close(self):
        self._binlog.close()

    # -- read path -------------------------------------------------------
    def get_series_key(self, sid: int) -> SeriesKey | None:
        return self._forward.get(sid)

    def get_series_id(self, key: SeriesKey) -> int | None:
        return self._by_key.get(key)

    def series_count(self) -> int:
        return len(self._forward)

    def table_series_ids(self, table: str) -> np.ndarray:
        return _to_sorted_array(self._by_table.get(table, set()))

    def tag_values(self, table: str, tag_key: str) -> list[str]:
        return sorted(self._inverted.get(table, {}).get(tag_key, {}).keys())

    def tag_keys(self, table: str) -> list[str]:
        return sorted(self._inverted.get(table, {}).keys())

    def get_series_ids_by_domains(self, table: str,
                                  domains: ColumnDomains) -> np.ndarray:
        """Evaluate tag-column domains → sorted series-id array
        (reference ts_index.rs:397)."""
        if domains.is_none:
            return np.empty(0, dtype=np.uint64)
        all_sids = self._by_table.get(table, set())
        if domains.is_all:
            return _to_sorted_array(all_sids)
        result: set[int] | None = None
        tbl_inv = self._inverted.get(table, {})
        for tag_key, dom in domains.domains.items():
            if tag_key not in tbl_inv:
                # unknown tag constrained: rows have no such tag → for an
                # equality/set constraint nothing matches unless the domain
                # admits absent (we treat absent as no-match, like reference
                # tag=NULL semantics)
                if isinstance(dom, AllDomain):
                    continue
                return np.empty(0, dtype=np.uint64)
            matched = _eval_tag_domain(tbl_inv[tag_key], dom)
            result = matched if result is None else (result & matched)
            if not result:
                return np.empty(0, dtype=np.uint64)
        if result is None:
            result = all_sids
        return _to_sorted_array(result)


def _eval_tag_domain(value_map: dict[str, set[int]], dom: Domain) -> set[int]:
    if isinstance(dom, AllDomain):
        out: set[int] = set()
        for s in value_map.values():
            out |= s
        return out
    if isinstance(dom, NoneDomain):
        return set()
    if isinstance(dom, SetDomain):
        out = set()
        for v in dom.values:
            out |= value_map.get(v, set())
        return out
    if isinstance(dom, RangeDomain):
        out = set()
        for v, sids in value_map.items():
            if dom.contains_value(v):
                out |= sids
        return out
    raise IndexError_(f"unsupported domain {type(dom).__name__}")


def _to_sorted_array(s: set[int]) -> np.ndarray:
    a = np.fromiter(s, dtype=np.uint64, count=len(s))
    a.sort()
    return a
