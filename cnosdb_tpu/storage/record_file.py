"""Generic CRC'd append-only record file.

Role-parity with reference tskv/src/record_file/ (format doc mod.rs:1-34):
the common container under the WAL and the Summary manifest. A file is
[8B magic header] then records of [len u32 | crc32 u32 | payload]. Reads
stop cleanly at truncation or corruption (torn tail after crash), which is
exactly the recovery contract the WAL needs.
"""
from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator

from ..errors import StorageError

FILE_MAGIC = b"CNOSREC1"
_HDR = struct.Struct("<II")


class RecordWriter:
    def __init__(self, path: str):
        self.path = path
        exists = os.path.exists(path) and os.path.getsize(path) >= len(FILE_MAGIC)
        self._f = open(path, "ab")
        if not exists:
            self._f.write(FILE_MAGIC)
            self._f.flush()

    def append(self, payload: bytes) -> int:
        """Append one record, return its file offset."""
        off = self._f.tell()
        self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        return off

    def sync(self):
        self._f.flush()
        os.fsync(self._f.fileno())

    @property
    def size(self) -> int:
        self._f.flush()
        return self._f.tell()

    def close(self):
        try:
            self.sync()
        finally:
            self._f.close()


class RecordReader:
    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            self._buf = f.read()
        if self._buf[:len(FILE_MAGIC)] != FILE_MAGIC:
            raise StorageError("bad record file magic", path=path)

    def __iter__(self) -> Iterator[bytes]:
        off = len(FILE_MAGIC)
        buf = self._buf
        n = len(buf)
        while off + _HDR.size <= n:
            ln, crc = _HDR.unpack_from(buf, off)
            start = off + _HDR.size
            end = start + ln
            if end > n:
                break  # torn tail
            payload = buf[start:end]
            if zlib.crc32(payload) != crc:
                break  # corruption: stop replay here
            yield payload
            off = end

    def records(self) -> list[bytes]:
        return list(self)
