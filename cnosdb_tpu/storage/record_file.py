"""Generic CRC'd append-only record file.

Role-parity with reference tskv/src/record_file/ (format doc mod.rs:1-34):
the common container under the WAL and the Summary manifest. A file is
[8B magic header] then records of [len u32 | crc32 u32 | payload]. Reads
stop cleanly at truncation or corruption (torn tail after crash), which is
exactly the recovery contract the WAL needs.
"""
from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator

from .. import faults
from ..errors import StorageError

FILE_MAGIC = b"CNOSREC1"
_HDR = struct.Struct("<II")

faults.register_point("record.append", __name__,
                      desc="record-file append (torn-write site)")
faults.register_point("record.sync", __name__,
                      desc="record-file fsync")


def _valid_prefix_len(path: str) -> int:
    """Byte length of the longest valid [magic + records] prefix, 0 when
    the magic itself is unreadable."""
    with open(path, "rb") as f:
        buf = f.read()
    if buf[:len(FILE_MAGIC)] != FILE_MAGIC:
        return 0
    off = len(FILE_MAGIC)
    n = len(buf)
    while off + _HDR.size <= n:
        ln, crc = _HDR.unpack_from(buf, off)
        end = off + _HDR.size + ln
        if end > n or zlib.crc32(buf[off + _HDR.size:end]) != crc:
            break
        off = end
    return off


class RecordWriter:
    def __init__(self, path: str):
        self.path = path
        exists = os.path.exists(path) and os.path.getsize(path) >= len(FILE_MAGIC)
        if exists:
            # Crash recovery: a torn tail (partial record from an
            # interrupted write) must be truncated BEFORE appending —
            # readers stop at the tear, so anything appended after it
            # would be durably written yet invisible to replay.
            valid = _valid_prefix_len(path)
            if valid and valid < os.path.getsize(path):
                with open(path, "r+b") as f:
                    f.truncate(valid)
        elif os.path.exists(path):
            # shorter than the magic: a segment creation that died
            # mid-header — restart it from scratch rather than appending
            # the magic after garbage
            with open(path, "r+b") as f:
                f.truncate(0)
        self._f = open(path, "ab")
        if not exists:
            self._f.write(FILE_MAGIC)
            self._f.flush()

    def append(self, payload: bytes) -> int:
        """Append one record, return its file offset."""
        off = self._f.tell()
        rec = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        if faults.ENABLED:
            hit = faults.fire("record.append", path=self.path)
            if hit and hit[0] == "torn":
                # crash mid-write: leave a truncated record on disk and die
                # the way the kernel would — readers must stop at the tear
                cut = min(int(hit[1]) if hit[1] else max(1, len(rec) // 2),
                          len(rec))
                self._f.write(rec[:len(rec) - cut])
                self._f.flush()
                raise faults.FaultInjected(
                    f"injected torn write ({cut}B short) at {self.path}")
        self._f.write(rec)
        return off

    def sync(self):
        if faults.ENABLED:
            faults.fire("record.sync", path=self.path)
        self._f.flush()
        os.fsync(self._f.fileno())

    @property
    def size(self) -> int:
        self._f.flush()
        return self._f.tell()

    def close(self):
        try:
            self.sync()
        finally:
            self._f.close()


def iter_records(buf: bytes) -> Iterator[bytes]:
    """Yield record payloads from an in-memory record-file image with the
    same stop-at-tear/corruption semantics as RecordReader. The DR plane
    (storage/backup.py) decodes archived WAL segments straight from
    object-store bytes through this."""
    off = len(FILE_MAGIC)
    n = len(buf)
    while off + _HDR.size <= n:
        ln, crc = _HDR.unpack_from(buf, off)
        start = off + _HDR.size
        end = start + ln
        if end > n:
            break  # torn tail
        payload = buf[start:end]
        if zlib.crc32(payload) != crc:
            break  # corruption: stop replay here
        yield payload
        off = end


class RecordReader:
    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            self._buf = f.read()
        if self._buf[:len(FILE_MAGIC)] != FILE_MAGIC:
            raise StorageError("bad record file magic", path=path)

    def __iter__(self) -> Iterator[bytes]:
        yield from iter_records(self._buf)

    def records(self) -> list[bytes]:
        return list(self)
