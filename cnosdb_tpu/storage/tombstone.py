"""Tombstones: row-range deletes against immutable TSM files.

Role-parity with reference tskv/src/tsm/tombstone.rs (`.tombstone` file per
TSM file): DELETE FROM / DROP SERIES record (table, series-set, time-range)
exclusions; readers subtract them, compaction drops the rows for good.
"""
from __future__ import annotations

import os
from dataclasses import dataclass

import msgpack
import numpy as np

from .record_file import RecordReader, RecordWriter


@dataclass(frozen=True)
class TombstoneEntry:
    table: str | None       # None = any table
    series_id: int | None   # None = all series
    min_ts: int
    max_ts: int

    def matches_series(self, table: str, sid: int) -> bool:
        return ((self.table is None or self.table == table)
                and (self.series_id is None or self.series_id == sid))


def tombstone_path(tsm_path: str) -> str:
    return tsm_path + ".tombstone"


class TsmTombstone:
    def __init__(self, tsm_path: str):
        self.path = tombstone_path(tsm_path)
        self.entries: list[TombstoneEntry] = []
        if os.path.exists(self.path):
            for payload in RecordReader(self.path):
                t, s, lo, hi = msgpack.unpackb(payload, raw=False)
                self.entries.append(TombstoneEntry(t, s, lo, hi))

    def add(self, entries: list[TombstoneEntry]):
        w = RecordWriter(self.path)
        for e in entries:
            w.append(msgpack.packb([e.table, e.series_id, e.min_ts, e.max_ts]))
        w.close()
        self.entries.extend(entries)

    @property
    def is_empty(self) -> bool:
        return not self.entries

    def mask_for(self, table: str, sid: int, ts: np.ndarray) -> np.ndarray | None:
        """→ boolean keep-mask over ts, or None if untouched."""
        hit = [e for e in self.entries if e.matches_series(table, sid)]
        if not hit:
            return None
        keep = np.ones(len(ts), dtype=bool)
        for e in hit:
            keep &= (ts < e.min_ts) | (ts > e.max_ts)
        return keep

    def remove_file(self):
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
