"""Scan assembly: merge memcaches + TSM files into device-ready batches.

Role-parity with the reference's read pipeline (tskv/src/reader/
iterator.rs:94-121 reader tree: SeriesReader → DataMerger → DataFilter →
Chunk/MemcacheReader), re-shaped for TPU: instead of a per-series stream
tree pulling one RecordBatch at a time, the scan materializes ONE large
columnar batch per vnode — timestamps, a series-ordinal segment array and
field columns with validity masks, already concatenated across series —
which is exactly the padded/masked layout `ops.tpu_exec` stages over PCIe.

Dedup priority on duplicate timestamps (low→high): L4..L1 files, L0 delta
files by ascending file id, immutable memcaches (oldest first), active
memcache. Within a priority, later rows win per FIELD (same rule as
memcache.materialize / compaction merge).
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..models.predicate import TimeRange, TimeRanges
from ..models.schema import TskvTableSchema, ValueType
from ..models.strcol import DictArray, as_dict_part as _as_dict_part, \
    unify_dictionaries
from .memcache import _group_starts
from .vnode import VnodeStorage


@dataclass
class ScanBatch:
    """One vnode's scan result, columnar, concatenated across series."""

    table: str
    series_ids: np.ndarray          # u64 [S]
    series_keys: list               # SeriesKey per ordinal (tags for GROUP BY)
    ts: np.ndarray                  # i64 [N]
    sid_ordinal: np.ndarray         # i32 [N] — segment id per row
    fields: dict[str, tuple[ValueType, np.ndarray, np.ndarray]] = field(default_factory=dict)
    # name → (vt, values [N], valid [N])

    @property
    def n_rows(self) -> int:
        return len(self.ts)

    @property
    def n_series(self) -> int:
        return len(self.series_ids)


def _time_mask(ts: np.ndarray, trs: TimeRanges) -> np.ndarray | None:
    if trs.is_all:
        return None
    m = np.zeros(len(ts), dtype=bool)
    for r in trs.ranges:
        m |= (ts >= r.min_ts) & (ts <= r.max_ts)
    return m


def _series_parts(vnode: VnodeStorage, table: str, sid: int,
                  field_names: list[str], trs: TimeRanges):
    """Collect (ts, {field: (vt, vals, valid)}) parts in priority order."""
    parts = []
    version = vnode.summary.version
    # files: L4..L1 then L0, ascending file_id within level ⇒ ascending priority
    for level in (4, 3, 2, 1, 0):
        fms = sorted(version.levels[level].values(), key=lambda f: f.file_id)
        for fm in fms:
            if not trs.is_all and not trs.overlaps(TimeRange(fm.min_ts, fm.max_ts)):
                continue
            r = version.reader(fm)
            cm = r.chunk(table, sid)
            if cm is None:
                continue
            ts = r.read_series_timestamps(table, sid)
            keep = version.tombstone(fm).mask_for(table, sid, ts)
            tmask = _time_mask(ts, trs)
            if keep is None and tmask is None:
                sel = None
            else:
                sel = np.ones(len(ts), dtype=bool)
                if keep is not None:
                    sel &= keep
                if tmask is not None:
                    sel &= tmask
                if not sel.any():
                    continue
            fields = {}
            for name in field_names:
                col = cm.column(name)
                if col is None:
                    continue
                vt = ValueType(col.pages[0].value_type)
                vals, valid = r.read_series_column(table, sid, name)
                if sel is not None:
                    vals, valid = vals[sel], valid[sel]
                fields[name] = (vt, vals, valid)
            parts.append(((ts[sel] if sel is not None else ts), fields))
    # memcaches: immutables old→new, then active
    for cache in [*vnode.immutables, vnode.active]:
        sd = cache.series.get((table, sid))
        if sd is None:
            continue
        ts, mfields, _ = sd.materialize()
        tmask = _time_mask(ts, trs)
        if tmask is not None:
            if not tmask.any():
                continue
            ts = ts[tmask]
        fields = {}
        for name in field_names:
            if name not in mfields:
                continue
            vt, vals, valid = mfields[name]
            if tmask is not None:
                vals, valid = vals[tmask], valid[tmask]
            fields[name] = (vt, vals, valid)
        parts.append((ts, fields))
    return parts


def merge_parts(parts, field_names: list[str]):
    """Merge priority-ordered parts → (ts, {field: (vt, vals, valid)})."""
    if not parts:
        return np.empty(0, dtype=np.int64), {}
    if len(parts) == 1:
        ts, fields = parts[0]
        return ts, fields
    # fast path: compacted output chunks are time-partitioned — when the
    # parts are individually strictly increasing and pairwise DISJOINT
    # after ordering by first timestamp, the merge is a concatenation
    # (no argsort, no dedup — the dominant cold-scan shape)
    nonempty = [p for p in parts if len(p[0])]
    if len(nonempty) > 1:
        ordered = sorted(nonempty, key=lambda p: int(p[0][0]))
        ok = all(bool((p[0][1:] > p[0][:-1]).all()) for p in ordered)
        if ok:
            for a, b in zip(ordered, ordered[1:]):
                if int(a[0][-1]) >= int(b[0][0]):
                    ok = False
                    break
        if ok:
            ts = np.concatenate([p[0] for p in ordered])
            out = {}
            for name in field_names:
                vt = next((f[name][0] for _, f in ordered if name in f),
                          None)
                if vt is None:
                    continue
                np_dtype = vt.numpy_dtype()
                if np_dtype is object:
                    break   # dictionary columns: generic path unifies
                vals_parts, valid_parts = [], []
                for ts_p, f in ordered:
                    if name in f:
                        vals_parts.append(f[name][1])
                        valid_parts.append(f[name][2])
                    else:
                        vals_parts.append(
                            np.zeros(len(ts_p), dtype=np_dtype))
                        valid_parts.append(
                            np.zeros(len(ts_p), dtype=bool))
                out[name] = (vt, np.concatenate(vals_parts),
                             np.concatenate(valid_parts))
            else:
                return ts, out
    ts_all = np.concatenate([p[0] for p in parts])
    total = len(ts_all)
    order = np.argsort(ts_all, kind="stable")
    ts_sorted = ts_all[order]
    group_starts = _group_starts(ts_sorted)
    uts = ts_sorted[group_starts]
    idx = np.arange(total, dtype=np.int64)
    out = {}
    for name in field_names:
        vt = None
        for _, fields in parts:
            if name in fields:
                vt = fields[name][0]
                break
        if vt is None:
            continue
        np_dtype = vt.numpy_dtype()
        is_str = np_dtype is object
        union = None
        if is_str:
            # strings merge as int32 codes under one union dictionary —
            # the dedup pick below is pure integer indexing either way
            das = {id(f): _as_dict_part(f[name][1])
                   for _, f in parts if name in f}
            union = unify_dictionaries(list(das.values()))
            vals_all = np.zeros(total, dtype=np.int32)
        else:
            vals_all = np.zeros(total, dtype=np_dtype)
        valid_all = np.zeros(total, dtype=bool)
        off = 0
        for ts_p, fields in parts:
            n = len(ts_p)
            if name in fields:
                _, vals, valid = fields[name]
                vals_all[off:off + n] = (das[id(fields)].remap_to(union)
                                         if is_str else vals)
                valid_all[off:off + n] = valid
            off += n
        vals_s = vals_all[order]
        valid_s = valid_all[order]
        score = np.where(valid_s, idx, -1)
        last_valid = np.maximum.reduceat(score, group_starts)
        valid_out = last_valid >= 0
        vals_out = vals_s[np.clip(last_valid, 0, None)]
        if is_str:
            vals_out = DictArray(vals_out, union)
        out[name] = (vt, vals_out, valid_out)
    return uts, out


def scan_vnode(vnode: VnodeStorage, table: str,
               series_ids: np.ndarray | None = None,
               time_ranges: TimeRanges | None = None,
               field_names: list[str] | None = None) -> ScanBatch:
    """Materialize a vnode scan into one ScanBatch."""
    trs = time_ranges if time_ranges is not None else TimeRanges.all()
    if series_ids is None:
        file_sids = set()
        for fm in vnode.summary.version.all_files():
            r = vnode.summary.version.reader(fm)
            file_sids.update(int(s) for s in r.series_ids(table))
        mem_sids = {sid for (t, sid) in vnode.active.series if t == table}
        for c in vnode.immutables:
            mem_sids |= {sid for (t, sid) in c.series if t == table}
        series_ids = np.array(sorted(file_sids | mem_sids), dtype=np.uint64)
    if field_names is None:
        field_names = _discover_fields(vnode, table)

    ts_parts, ord_parts = [], []
    fparts: dict[str, list[tuple[int, np.ndarray, np.ndarray]]] = {n: [] for n in field_names}
    ftypes: dict[str, ValueType] = {}
    keys = []
    kept_sids = []
    total = 0
    for ordinal, sid in enumerate(series_ids):
        sid = int(sid)
        parts = _series_parts(vnode, table, sid, field_names, trs)
        ts, fields = merge_parts(parts, field_names)
        if len(ts) == 0:
            continue
        ts_parts.append(ts)
        ord_parts.append(np.full(len(ts), len(kept_sids), dtype=np.int32))
        for name in field_names:
            if name in fields:
                vt, vals, valid = fields[name]
                ftypes.setdefault(name, vt)
                fparts[name].append((total, vals, valid))
        kept_sids.append(sid)
        keys.append(vnode.index.get_series_key(sid))
        total += len(ts)

    if total == 0:
        return ScanBatch(table, np.empty(0, dtype=np.uint64), [],
                         np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int32), {})
    ts_all = np.concatenate(ts_parts)
    ord_all = np.concatenate(ord_parts)
    out_fields = {}
    for name, parts in fparts.items():
        if not parts:
            continue
        vt = ftypes[name]
        np_dtype = vt.numpy_dtype()
        if np_dtype is object:
            das = [_as_dict_part(vals) for _, vals, _ in parts]
            union = unify_dictionaries(das)
            vals_all = np.zeros(total, dtype=np.int32)
            valid_all = np.zeros(total, dtype=bool)
            for (off, vals, valid), d in zip(parts, das):
                vals_all[off:off + len(d)] = d.remap_to(union)
                valid_all[off:off + len(valid)] = valid
            out_fields[name] = (vt, DictArray(vals_all, union), valid_all)
            continue
        vals_all = np.zeros(total, dtype=np_dtype)
        valid_all = np.zeros(total, dtype=bool)
        for off, vals, valid in parts:
            vals_all[off:off + len(vals)] = vals
            valid_all[off:off + len(valid)] = valid
        out_fields[name] = (vt, vals_all, valid_all)
    return ScanBatch(table, np.array(kept_sids, dtype=np.uint64), keys,
                     ts_all, ord_all, out_fields)


def _discover_fields(vnode: VnodeStorage, table: str) -> list[str]:
    names: set[str] = set()
    schema = vnode.schemas.get(table)
    if schema is not None:
        return schema.field_names()
    for fm in vnode.summary.version.all_files():
        r = vnode.summary.version.reader(fm)
        g = r.groups.get(table)
        if g:
            for cm in g.chunks.values():
                names.update(c.name for c in cm.columns)
    for cache in [vnode.active, *vnode.immutables]:
        for (t, sid), sd in cache.series.items():
            if t == table:
                names.update(sd.field_chunks.keys())
    return sorted(names)
