"""Scan assembly: merge memcaches + TSM files into device-ready batches.

Role-parity with the reference's read pipeline (tskv/src/reader/
iterator.rs:94-121 reader tree: SeriesReader → DataMerger → DataFilter →
Chunk/MemcacheReader), re-shaped for TPU: instead of a per-series stream
tree pulling one RecordBatch at a time, the scan materializes ONE large
columnar batch per vnode — timestamps, a series-ordinal segment array and
field columns with validity masks, already concatenated across series —
which is exactly the padded/masked layout `ops.tpu_exec` stages over PCIe.

Dedup priority on duplicate timestamps (low→high): L4..L1 files, L0 delta
files by ascending file id, immutable memcaches (oldest first), active
memcache. Within a priority, later rows win per FIELD (same rule as
memcache.materialize / compaction merge).
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..models.predicate import TimeRange, TimeRanges
from ..models.schema import TskvTableSchema, ValueType
from ..utils import deadline as deadline_mod
from ..models.strcol import DictArray, as_dict_part as _as_dict_part, \
    unify_dictionaries
from .memcache import MemCache, _group_starts
from .vnode import VnodeStorage
from ..server import memory as memgov
from ..utils import lockwatch
from ..utils import stages
from . import compressed_domain


def _charge_decoded(batch):
    """Per-query accounting for one assembled vnode batch (decode-pool
    bytes): an oversized query dies here with MemoryExceeded before the
    next vnode materializes."""
    nb = batch.ts.nbytes + batch.sid_ordinal.nbytes
    for _vt, vals, valid in batch.fields.values():
        nb += int(getattr(vals, "nbytes", 0) or 0)
        nb += int(getattr(valid, "nbytes", 0) or 0)
    memgov.charge_query(nb, "decode")
    return batch


@dataclass
class ScanBatch:
    """One vnode's scan result, columnar, concatenated across series."""

    table: str
    series_ids: np.ndarray          # u64 [S]
    series_keys: list               # SeriesKey per ordinal (tags for GROUP BY)
    ts: np.ndarray                  # i64 [N]
    sid_ordinal: np.ndarray         # i32 [N] — segment id per row
    fields: dict[str, tuple[ValueType, np.ndarray, np.ndarray]] = field(default_factory=dict)
    # name → (vt, values [N], valid [N])

    @property
    def n_rows(self) -> int:
        return len(self.ts)

    @property
    def n_series(self) -> int:
        return len(self.series_ids)


def _time_mask(ts: np.ndarray, trs: TimeRanges) -> np.ndarray | None:
    if trs.is_all:
        return None
    m = np.zeros(len(ts), dtype=bool)
    for r in trs.ranges:
        m |= (ts >= r.min_ts) & (ts <= r.max_ts)
    return m


def _series_parts(vnode: VnodeStorage, table: str, sid: int,
                  field_names: list[str], trs: TimeRanges):
    """Collect (ts, {field: (vt, vals, valid)}) parts in priority order."""
    parts = []
    targets = _field_targets(vnode, table, field_names)
    version = vnode.summary.version
    # files: L4..L1 then L0, ascending file_id within level ⇒ ascending priority
    for level in (4, 3, 2, 1, 0):
        fms = sorted(version.levels[level].values(), key=lambda f: f.file_id)
        for fm in fms:
            if not trs.is_all and not trs.overlaps(TimeRange(fm.min_ts, fm.max_ts)):
                continue
            r = version.reader(fm)
            cm = r.chunk(table, sid)
            if cm is None:
                continue
            ts = r.read_series_timestamps(table, sid)
            keep = version.tombstone(fm).mask_for(table, sid, ts)
            tmask = _time_mask(ts, trs)
            if keep is None and tmask is None:
                sel = None
            else:
                sel = np.ones(len(ts), dtype=bool)
                if keep is not None:
                    sel &= keep
                if tmask is not None:
                    sel &= tmask
                if not sel.any():
                    continue
            fields = {}
            maps = _chunk_maps(cm)
            for name in field_names:
                cid, cands = targets[name]
                col = _resolve_chunk_col(maps, cid, cands)
                if col is None:
                    continue
                vt = ValueType(col.pages[0].value_type)
                vals, valid = r.read_series_column(table, sid, col.name)
                if sel is not None:
                    vals, valid = vals[sel], valid[sel]
                fields[name] = (vt, vals, valid)
            parts.append(((ts[sel] if sel is not None else ts), fields))
    # memcaches: immutables old→new, then active
    for cache in [*vnode.immutables, vnode.active]:
        sd = cache.series.get((table, sid))
        if sd is None:
            continue
        ts, mfields, _ = sd.materialize()
        tmask = _time_mask(ts, trs)
        if tmask is not None:
            if not tmask.any():
                continue
            ts = ts[tmask]
        fields = {}
        for name in field_names:
            src = next((c for c in targets[name][1]
                        if c in mfields), None)
            if src is None:
                continue
            vt, vals, valid = mfields[src]
            if tmask is not None:
                vals, valid = vals[tmask], valid[tmask]
            fields[name] = (vt, vals, valid)
        parts.append((ts, fields))
    return parts


def merge_parts(parts, field_names: list[str]):
    """Merge priority-ordered parts → (ts, {field: (vt, vals, valid)})."""
    if not parts:
        return np.empty(0, dtype=np.int64), {}
    if len(parts) == 1:
        ts, fields = parts[0]
        return ts, fields
    # fast path: compacted output chunks are time-partitioned — when the
    # parts are individually strictly increasing and pairwise DISJOINT
    # after ordering by first timestamp, the merge is a concatenation
    # (no argsort, no dedup — the dominant cold-scan shape)
    nonempty = [p for p in parts if len(p[0])]
    if len(nonempty) > 1:
        ordered = sorted(nonempty, key=lambda p: int(p[0][0]))
        ok = all(bool((p[0][1:] > p[0][:-1]).all()) for p in ordered)
        if ok:
            for a, b in zip(ordered, ordered[1:]):
                if int(a[0][-1]) >= int(b[0][0]):
                    ok = False
                    break
        if ok:
            ts = np.concatenate([p[0] for p in ordered])
            out = {}
            for name in field_names:
                vt = next((f[name][0] for _, f in ordered if name in f),
                          None)
                if vt is None:
                    continue
                np_dtype = vt.numpy_dtype()
                if np_dtype is object:
                    break   # dictionary columns: generic path unifies
                vals_parts, valid_parts = [], []
                for ts_p, f in ordered:
                    if name in f:
                        vals_parts.append(f[name][1])
                        valid_parts.append(f[name][2])
                    else:
                        vals_parts.append(
                            np.zeros(len(ts_p), dtype=np_dtype))
                        valid_parts.append(
                            np.zeros(len(ts_p), dtype=bool))
                out[name] = (vt, np.concatenate(vals_parts),
                             np.concatenate(valid_parts))
            else:
                return ts, out
    ts_all = np.concatenate([p[0] for p in parts])
    total = len(ts_all)
    order = np.argsort(ts_all, kind="stable")
    ts_sorted = ts_all[order]
    group_starts = _group_starts(ts_sorted)
    uts = ts_sorted[group_starts]
    idx = np.arange(total, dtype=np.int64)
    out = {}
    for name in field_names:
        vt = None
        for _, fields in parts:
            if name in fields:
                vt = fields[name][0]
                break
        if vt is None:
            continue
        np_dtype = vt.numpy_dtype()
        is_str = np_dtype is object
        union = None
        if is_str:
            # strings merge as int32 codes under one union dictionary —
            # the dedup pick below is pure integer indexing either way
            das = {id(f): _as_dict_part(f[name][1])
                   for _, f in parts if name in f}
            union = unify_dictionaries(list(das.values()))
            vals_all = np.zeros(total, dtype=np.int32)
        else:
            vals_all = np.zeros(total, dtype=np_dtype)
        valid_all = np.zeros(total, dtype=bool)
        off = 0
        for ts_p, fields in parts:
            n = len(ts_p)
            if name in fields:
                _, vals, valid = fields[name]
                vals_all[off:off + n] = (das[id(fields)].remap_to(union)
                                         if is_str else vals)
                valid_all[off:off + n] = valid
            off += n
        vals_s = vals_all[order]
        valid_s = valid_all[order]
        score = np.where(valid_s, idx, -1)
        last_valid = np.maximum.reduceat(score, group_starts)
        valid_out = last_valid >= 0
        vals_out = vals_s[np.clip(last_valid, 0, None)]
        if is_str:
            vals_out = DictArray(vals_out, union)
        out[name] = (vt, vals_out, valid_out)
    return uts, out


# ---------------------------------------------------------------------------
# delta rescan: decode only what a ScanToken doesn't cover
# ---------------------------------------------------------------------------


class _DeltaVersion:
    """Version facade whose levels hold ONLY `new_fids`; readers,
    tombstones and paths delegate to the live Version (same caches)."""

    def __init__(self, version, new_fids: frozenset):
        self._version = version
        self.levels = [
            {fid: fm for fid, fm in lvl.items() if fid in new_fids}
            for lvl in version.levels]

    def reader(self, fm):
        return self._version.reader(fm)

    def tombstone(self, fm):
        return self._version.tombstone(fm)

    def file_path(self, fm):
        return self._version.file_path(fm)

    def all_files(self):
        out = []
        for lvl in self.levels:
            out.extend(lvl.values())
        return out


class _DeltaSummary:
    def __init__(self, version):
        self.version = version


class DeltaVnodeView:
    """Vnode facade exposing only data NEWER than a ScanToken: the TSM
    files in `new_fids` plus memcache rows with WAL seq > `after_seq`.
    scan_vnode runs against it unchanged — the result is the delta batch
    that merge_scan_batches folds into the cached snapshot. Index and
    schemas are the live ones (valid because the coordinator only takes
    this path when destructive_version matched)."""

    def __init__(self, vnode: VnodeStorage, new_fids: frozenset,
                 after_seq: int):
        self.vnode_id = vnode.vnode_id
        self.summary = _DeltaSummary(
            _DeltaVersion(vnode.summary.version, new_fids))
        self.index = vnode.index
        self.schemas = vnode.schemas
        act = vnode.active.suffix_view(after_seq)
        self.active = act if act is not None \
            else MemCache(vnode.vnode_id)
        self.immutables = [sv for c in list(vnode.immutables)
                           if (sv := c.suffix_view(after_seq)) is not None]


def merge_scan_batches(cached: ScanBatch, delta: ScanBatch):
    """Fold a delta decode into a cached snapshot.

    → (merged, append_gather) or None when the batches disagree on a
    field's type (schema drift the caller resolves with a full rescan).
    `append_gather` is an int64 row-gather into concat(cached, delta)
    producing the merged batch, present iff no (series, ts) pair occurs
    in both inputs — the pure-append case the device twin can replay
    with one gather per column (ops/device_cache.merged_device_batch).

    Dedup semantics match a full rescan: every delta source (a freshly
    flushed L0 file, newer memcache chunks) outranks every cached source,
    and rows the delta re-decodes after a flush carry identical values,
    so per-field latest-valid-wins over [cached, delta] is exactly the
    scan's merge rule. Output is canonical: series ids ascending (the
    index returns sorted sid arrays), ts ascending and unique per series.
    """
    n_c, n_d = cached.n_rows, delta.n_rows
    for name, (vt, _v, _m) in delta.fields.items():
        cf = cached.fields.get(name)
        if cf is not None and cf[0] != vt:
            return None
    all_sids = np.union1d(cached.series_ids, delta.series_ids)
    sid_all = np.concatenate([cached.series_ids[cached.sid_ordinal],
                              delta.series_ids[delta.sid_ordinal]])
    ts_all = np.concatenate([cached.ts, delta.ts])
    n = n_c + n_d
    # stable (ts, sid) lexsort: within a duplicate (sid, ts) group the
    # cached rows precede the delta rows, so "last valid wins" = delta
    order = np.lexsort((ts_all, sid_all))
    sid_s = sid_all[order]
    ts_s = ts_all[order]
    newgrp = np.empty(n, dtype=bool)
    newgrp[0] = True
    newgrp[1:] = (sid_s[1:] != sid_s[:-1]) | (ts_s[1:] != ts_s[:-1])
    group_starts = np.nonzero(newgrp)[0]
    pure_append = len(group_starts) == n
    uts = ts_s[group_starts]
    usid = sid_s[group_starts]
    sid_ordinal = np.searchsorted(all_sids, usid).astype(np.int32)
    idx = np.arange(n, dtype=np.int64)
    out_fields: dict = {}
    names = list(cached.fields)
    names += [nm for nm in delta.fields if nm not in cached.fields]
    for name in names:
        vt = (cached.fields.get(name) or delta.fields[name])[0]
        np_dtype = vt.numpy_dtype()
        is_str = np_dtype is object
        if is_str:
            das = [_as_dict_part(b.fields[name][1])
                   if name in b.fields else None
                   for b in (cached, delta)]
            union = unify_dictionaries([d for d in das if d is not None])
            vals_all = np.zeros(n, dtype=np.int32)
        else:
            vals_all = np.zeros(n, dtype=np_dtype)
        valid_all = np.zeros(n, dtype=bool)
        off = 0
        for bi, b in enumerate((cached, delta)):
            m = b.n_rows
            if name in b.fields:
                _vt, vals, valid = b.fields[name]
                vals_all[off:off + m] = (das[bi].remap_to(union)
                                         if is_str else vals)
                valid_all[off:off + m] = valid
            off += m
        vals_s = vals_all[order]
        valid_s = valid_all[order]
        score = np.where(valid_s, idx, -1)
        last_valid = np.maximum.reduceat(score, group_starts)
        valid_out = last_valid >= 0
        vals_out = vals_s[np.clip(last_valid, 0, None)]
        if is_str:
            vals_out = DictArray(vals_out, union)
        out_fields[name] = (vt, vals_out, valid_out)
    keymap = {int(s): k for s, k in zip(cached.series_ids,
                                        cached.series_keys)}
    keymap.update((int(s), k) for s, k in zip(delta.series_ids,
                                              delta.series_keys))
    merged = ScanBatch(cached.table, all_sids.astype(np.uint64),
                       [keymap[int(s)] for s in all_sids],
                       uts, sid_ordinal, out_fields)
    return merged, (order[group_starts] if pure_append else None)


def _field_targets(vnode: VnodeStorage, table: str,
                   field_names: list[str]) -> dict:
    """name → (column_id | None, [name, *prior_names]).

    TSM chunk columns are resolved by column id when both sides carry
    one: ids are never reused (models/schema.py), so data written under
    a renamed-away name can never conflate with a newer column that
    later took the name. The name-lineage candidates are the fallback
    for id-less chunks (flushed without a schema) and for name-keyed
    memcache rows."""
    schema = vnode.schemas.get(table)
    out = {}
    for n in field_names:
        cands = [n]
        cid = None
        if schema is not None:
            c = schema.column(n) if schema.contains_column(n) else None
            if c is not None:
                cid = c.id
                if getattr(c, "prior_names", None):
                    cands += list(c.prior_names)
        out[n] = (cid, cands)
    return out


def _chunk_maps(cm) -> tuple[dict, dict]:
    """Build one (by_id, by_name) lookup per chunk — resolve all query
    columns against it rather than re-scanning cm.columns per field."""
    by_id: dict = {}
    by_name: dict = {}
    for c in cm.columns:
        if c.column_id:
            by_id.setdefault(c.column_id, c)
        by_name.setdefault(c.name, c)
    return by_id, by_name


def _resolve_chunk_col(maps, cid, cands):
    """→ ColumnMeta for one query column inside one chunk, id-first.

    Name fallback only considers chunk columns WITHOUT an id when the
    query column's id is known — a chunk column carrying a different id
    is provably another (renamed/dropped) column, even if its name
    matches."""
    by_id, by_name = maps
    if cid is not None:
        c = by_id.get(cid)
        if c is not None:
            return c
    for nm in cands:
        c = by_name.get(nm)
        if c is not None and (cid is None or not c.column_id):
            return c
    return None


def scan_vnode(vnode: VnodeStorage, table: str,
               series_ids: np.ndarray | None = None,
               time_ranges: TimeRanges | None = None,
               field_names: list[str] | None = None,
               page_filter=None, page_constraints: dict | None = None,
               n_threads: int = 1, upload_hook=None,
               decode_hook=None, compressed_spec=None) -> ScanBatch:
    """Materialize a vnode scan into one ScanBatch.

    `page_filter` (an sql.expr tree, optional) enables predicate page
    pruning: pages whose statistics prove no row can satisfy a
    conjunct are never decoded. The resulting batch is only valid for
    queries applying that same filter — the coordinator keys its scan
    cache accordingly, and passes the constraints it already extracted
    as `page_constraints` so the tree is walked once per query, not per
    vnode. `n_threads` sizes the native decoder's pool (the coordinator
    divides the host's cores across concurrent vnode scans).
    `upload_hook`, when given, is `hook(total_rows) -> uploader`: as each
    field column finishes decoding cleanly it is handed to
    `uploader.put(...)` so device transfer overlaps the decode of the
    remaining columns (the double-buffer half of the pipeline; storage
    stays jax-free — the hook comes from ops/device_cache).
    `decode_hook`, when given, is `hook() -> DeviceDecodeLane | None`
    (ops/device_decode): pages whose codec has a device kernel stop host
    work at the byte container and decode as batched kernels on the
    accelerator — the third lane beside native pagedec and per-page
    Python.
    `compressed_spec` (storage/compressed_domain.CompressedSpec), when
    given, engages the compressed-domain lane AHEAD of the decode lanes:
    merge-free pages provably skippable/answerable from their encoded
    representation leave the plan entirely (contributions ride
    `batch.compressed_partials`), and mixed string/bool predicate pages
    decode but gather only surviving rows (late materialization). The
    batch is only valid for queries with that exact spec — the
    coordinator keys its cache accordingly.
    """
    trs = time_ranges if time_ranges is not None else TimeRanges.all()
    if series_ids is None:
        file_sids = set()
        for fm in vnode.summary.version.all_files():
            r = vnode.summary.version.reader(fm)
            file_sids.update(int(s) for s in r.series_ids(table))
        series_ids = np.array(
            sorted(file_sids | _mem_series_ids(vnode, table)),
            dtype=np.uint64)
    if field_names is None:
        field_names = _discover_fields(vnode, table)

    import os

    if not os.environ.get("CNOSDB_NO_NATIVE_SCAN"):
        if page_constraints is None and page_filter is not None:
            page_constraints = _page_constraints(page_filter, field_names)
        batch = _scan_vnode_native(vnode, table, series_ids, trs,
                                   field_names, page_constraints or {},
                                   n_threads, upload_hook, decode_hook,
                                   compressed_spec)
        if batch is not None:
            return _charge_decoded(batch)

    ts_parts, ord_parts = [], []
    fparts: dict[str, list[tuple[int, np.ndarray, np.ndarray]]] = {n: [] for n in field_names}
    ftypes: dict[str, ValueType] = {}
    keys = []
    kept_sids = []
    total = 0
    for ordinal, sid in enumerate(series_ids):
        # cooperative checkpoint: a killed/expired request stops between
        # series instead of materializing the rest of the vnode
        deadline_mod.check_current()
        sid = int(sid)
        parts = _series_parts(vnode, table, sid, field_names, trs)
        ts, fields = merge_parts(parts, field_names)
        if len(ts) == 0:
            continue
        ts_parts.append(ts)
        ord_parts.append(np.full(len(ts), len(kept_sids), dtype=np.int32))
        for name in field_names:
            if name in fields:
                vt, vals, valid = fields[name]
                ftypes.setdefault(name, vt)
                fparts[name].append((total, vals, valid))
        kept_sids.append(sid)
        keys.append(vnode.index.get_series_key(sid))
        total += len(ts)

    if total == 0:
        return ScanBatch(table, np.empty(0, dtype=np.uint64), [],
                         np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int32), {})
    ts_all = np.concatenate(ts_parts)
    ord_all = np.concatenate(ord_parts)
    out_fields = {}
    for name, parts in fparts.items():
        if not parts:
            continue
        vt = ftypes[name]
        np_dtype = vt.numpy_dtype()
        if np_dtype is object:
            das = [_as_dict_part(vals) for _, vals, _ in parts]
            union = unify_dictionaries(das)
            vals_all = np.zeros(total, dtype=np.int32)
            valid_all = np.zeros(total, dtype=bool)
            for (off, vals, valid), d in zip(parts, das):
                vals_all[off:off + len(d)] = d.remap_to(union)
                valid_all[off:off + len(valid)] = valid
            out_fields[name] = (vt, DictArray(vals_all, union), valid_all)
            continue
        vals_all = np.zeros(total, dtype=np_dtype)
        valid_all = np.zeros(total, dtype=bool)
        for off, vals, valid in parts:
            vals_all[off:off + len(vals)] = vals
            valid_all[off:off + len(valid)] = valid
        out_fields[name] = (vt, vals_all, valid_all)
    return _charge_decoded(
        ScanBatch(table, np.array(kept_sids, dtype=np.uint64), keys,
                  ts_all, ord_all, out_fields))


# ---------------------------------------------------------------------------
# native batch scan: the cold-path fast lane
# ---------------------------------------------------------------------------
# Most scans hit fully-compacted vnodes: per series, a handful of chunks
# whose time ranges are provably disjoint FROM METADATA ALONE (no decode
# needed to know the merge is a concatenation). For those, the whole
# vnode's page set is planned up front — output row offsets computed from
# chunk metadata — and decoded by native/pagedec.cpp in one GIL-free
# multithreaded call per (file, column), writing straight into the final
# concatenated arrays. Series that need real merging (memcache overlap,
# tombstones, overlapping L0 chunks) fall back to the per-series Python
# path and splice into their reserved span. This replaces the role of the
# reference's reader tree (tskv/src/reader/iterator.rs:94-121) for the
# dominant compacted-read shape, with page-statistics predicate pruning
# (reference column_group/statistics.rs) applied before any byte decodes.

_NATIVE_NUMERIC = {
    int(ValueType.FLOAT): 1,      # pagedec kind: gorilla f64
    int(ValueType.INTEGER): 2,    # delta i64
    int(ValueType.UNSIGNED): 2,   # delta (u64 bit pattern rides i64)
    int(ValueType.BOOLEAN): 3,    # bitpack u8
}
_NATIVE_ENC = {1: {6}, 2: {2, 11}, 3: {10}}   # kind → decodable encodings

# Why pages miss the native pagedec fast lane, by reason — the
# observability half of the decode plane (surfaced on /metrics as
# cnosdb_decode_fallback_total{reason=...}). A hot reason is actionable:
#   string        value type has no native lane (dictionary decode)
#   value_type    numeric type pagedec doesn't cover
#   encoding      codec outside the native decoder's set
#   schema_change page typed differently than the column (cast path)
#   native_reject native decoder refused the page at runtime
#   native_unavailable  no native library and the device lane declined
#   cold_tier     page lives in the object store; the native mmap lane
#                 cannot touch it (decodes via Python over the block cache)
#   device_decode.*     device lane examined the page but declined
#                       (reason suffix from codecs.split_for_device)
import threading as _threading

_FALLBACK_LOCK = lockwatch.Lock("scan.fallback")
_FALLBACK: dict[str, int] = {}


def _count_fallback(reason: str, n: int = 1) -> None:
    with _FALLBACK_LOCK:
        _FALLBACK[reason] = _FALLBACK.get(reason, 0) + n


def decode_fallback_snapshot() -> dict[str, int]:
    with _FALLBACK_LOCK:
        return dict(sorted(_FALLBACK.items()))


def _count_cold_pruned(n: int) -> None:
    """Pages of a COLD file skipped by local zone-map/constraint pruning:
    each one is a page whose bytes were never downloaded."""
    from . import tiering

    stages.count("cold.pages_pruned", n)
    tiering._count_cold("prune", "pages_pruned", n)


def _mem_series_ids(vnode: VnodeStorage, table: str) -> set:
    """Series ids with unflushed rows for `table` (active + immutables)."""
    sids = {sid for (t, sid) in vnode.active.series if t == table}
    for c in vnode.immutables:
        sids |= {sid for (t, sid) in c.series if t == table}
    return sids


def _page_constraints(page_filter, field_names) -> dict:
    """Extract per-column interval conjuncts usable for page pruning.

    Walks AND nodes only; each supported conjunct (col CMP literal,
    BETWEEN, IN) contributes. Unsupported subtrees are simply ignored —
    pruning by any one conjunct is sound because a row dropped by it
    fails the whole conjunction (NULL rows fail comparisons too, and
    page stats exclude only NaNs, which satisfy no comparison).
    → {col: [("op", value) | ("between", (lo, hi)) | ("in", values)]}
    """
    import os

    from ..sql.expr import Between, BinOp, Column, InList, Like, Literal

    fields = set(field_names)
    out: dict[str, list] = {}
    ngram_on = os.environ.get("CNOSDB_NGRAM_SKIP", "1").lower() \
        not in ("0", "off", "false")

    def numeric(v):
        return isinstance(v, (int, float, np.integer, np.floating)) \
            and not isinstance(v, bool)

    def add_ngram(col, tris):
        # a subset of required trigrams only admits MORE pages — sound
        if ngram_on and tris:
            out.setdefault(col, []).append(("ngram", tris))

    def walk(e):
        if isinstance(e, BinOp):
            if e.op == "and":
                walk(e.left)
                walk(e.right)
                return
            if e.op in ("=", "!=", "<", "<=", ">", ">="):
                col = lit = op = None
                if isinstance(e.left, Column) and isinstance(e.right, Literal):
                    col, lit, op = e.left.name, e.right.value, e.op
                elif isinstance(e.right, Column) and isinstance(e.left, Literal):
                    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                            "=": "=", "!=": "!="}
                    col, lit, op = e.right.name, e.left.value, flip[e.op]
                if col in fields and numeric(lit):
                    out.setdefault(col, []).append((op, lit))
                elif col in fields and op == "=" and isinstance(lit, str):
                    from ..ops import strkernels

                    add_ngram(col, strkernels.value_trigrams(lit))
            return
        if isinstance(e, Like) and not e.negated \
                and isinstance(e.expr, Column) and isinstance(e.pattern, str) \
                and e.expr.name in fields:
            from ..ops import strkernels

            add_ngram(e.expr.name, strkernels.required_trigrams(e.pattern))
            return
        if isinstance(e, Between) and not e.negated \
                and isinstance(e.expr, Column) \
                and isinstance(e.low, Literal) and isinstance(e.high, Literal) \
                and e.expr.name in fields \
                and numeric(e.low.value) and numeric(e.high.value):
            out.setdefault(e.expr.name, []).append(
                ("between", (e.low.value, e.high.value)))
            return
        if isinstance(e, InList) and not e.negated \
                and isinstance(e.expr, Column) and e.expr.name in fields \
                and e.values and all(numeric(v) for v in e.values):
            out.setdefault(e.expr.name, []).append(("in", list(e.values)))
            return

    try:
        walk(page_filter)
    except Exception:
        return {}
    return out


def _page_admits(cols: dict, i: int, constraints: dict) -> bool:
    """Can page i of this chunk contain a row satisfying every constrained
    conjunct? Column absent from the chunk → all-NULL → no match."""
    for cname, cons in constraints.items():
        col = cols.get(cname)
        if col is None:
            return False
        pm = col.pages[i]
        ngram_cons = [c for c in cons if c[0] == "ngram"]
        if ngram_cons:
            # checked before the stats gate: string pages carry no
            # min/max (the `continue` below) but do carry signatures
            sig = getattr(pm, "ngram", None)
            if sig is not None:
                from ..ops import strkernels

                for _op, tris in ngram_cons:
                    if not strkernels.signature_admits(sig, tris):
                        stages.count("ngram_pages_skipped", 1)
                        strkernels.note_path("ngram_skip", "page")
                        return False
            cons = [c for c in cons if c[0] != "ngram"]
        lo, hi = pm.stat_min, pm.stat_max
        if lo is None or hi is None:
            continue   # no stats (e.g. all-null page): cannot prune
        if pm.value_type == int(ValueType.FLOAT) \
                and getattr(pm, "stats_version", 0) < 1:
            # legacy finite-only float stats: an ±inf row may lie outside
            # the recorded interval, so pruning on it could drop rows
            continue
        for op, val in cons:
            if op == ">":
                ok = hi > val
            elif op == ">=":
                ok = hi >= val
            elif op == "<":
                ok = lo < val
            elif op == "<=":
                ok = lo <= val
            elif op == "=":
                ok = lo <= val <= hi
            elif op == "!=":
                # cannot prune: page stats exclude NaN, and NaN rows DO
                # satisfy != (sql 3VL evaluates it as ~(a == b)); a
                # constant page [v..v] may still hide a matching NaN row
                ok = True
            elif op == "between":
                ok = hi >= val[0] and lo <= val[1]
            else:   # "in"
                ok = any(lo <= v <= hi for v in val)
            if not ok:
                return False
    return True


def _submit_device_page(dev_lane, r, pm, colname, out_off, vt,
                        numeric_cols, string_parts, string_valid,
                        ts_all) -> bool:
    """Try to queue one page on the device-decode lane. True = queued;
    False = the caller routes the page to a host lane, with the decline
    reason already booked on both counters (decode_fallback_snapshot's
    device_decode.* reasons and cnosdb_device_decode_total)."""
    from . import codecs as _codecs

    try:
        if colname is None:
            block, nm = r._read_page(pm), None
        else:
            block, nm = r.read_field_page_split(pm)
        plan, reason = _codecs.split_for_device(
            block, vt if colname is not None else ValueType.INTEGER)
    except Exception:
        plan, reason = None, "read_error"
    if plan is None:
        _count_fallback("device_decode." + reason)
        dev_lane.declined(reason)
        return False
    n = pm.n_rows
    token = (r, pm, colname, out_off, vt)
    if colname is None:
        dev_lane.submit(plan, token, None, ValueType.INTEGER, out_off, n,
                        None, ts_all, None)
        return True
    if vt in (ValueType.STRING, ValueType.GEOMETRY):
        parts, sv = string_parts[colname], string_valid[colname]
        values = plan["values"]

        def _sink(dense, _off=out_off, _n=n, _nm=nm, _values=values):
            if _nm is None:
                codes = dense.astype(np.int32, copy=False)
                valid_p = np.ones(_n, dtype=bool)
            else:
                codes = np.zeros(_n, dtype=np.int32)
                codes[~_nm] = dense
                valid_p = ~_nm
            parts.append((_off, DictArray(codes, _values)))
            sv[_off:_off + _n] = valid_p

        dev_lane.submit(plan, token, colname, vt, out_off, n, nm,
                        None, None, sink=_sink)
        return True
    out_vals, out_valid = numeric_cols[colname]
    dev_lane.submit(plan, token, colname, vt, out_off, n, nm,
                    out_vals, out_valid)
    return True


def _scan_vnode_native(vnode: VnodeStorage, table: str,
                       series_ids, trs: TimeRanges,
                       field_names: list[str], constraints: dict,
                       n_threads: int,
                       upload_hook=None,
                       decode_hook=None,
                       compressed_spec=None) -> ScanBatch | None:
    from . import native

    dev_lane = decode_hook() if decode_hook is not None else None
    native_ok = native.pagedec_available()
    if not native_ok and dev_lane is None and compressed_spec is None:
        # no fast decode lane and no compressed-domain work: the simple
        # per-series fallback below is equivalent and cheaper to plan.
        # With a spec the page-level plan is still worth building — the
        # lane skips/answers pages before any decode, and survivors fall
        # through to the per-page Python jobs
        return None
    version = vnode.summary.version
    files = []
    for level in (4, 3, 2, 1, 0):
        fms = sorted(version.levels[level].values(), key=lambda f: f.file_id)
        for fm in fms:
            if not trs.is_all and not trs.overlaps(
                    TimeRange(fm.min_ts, fm.max_ts)):
                continue
            files.append((fm, version.reader(fm)))
    mem_sids = _mem_series_ids(vnode, table)
    targets = _field_targets(vnode, table, field_names)

    # ---------------------------------------------------------------- plan
    # per series: ("n", sid, [(reader, chunk, cols, [page idx])], n_rows,
    #             needs_trim, pruned) or ("f", sid, ts, fields)
    plan = []
    total = 0
    any_trim = False
    any_pruned = False
    for sid in series_ids:
        sid = int(sid)
        entry = _plan_series(vnode, table, sid, files, mem_sids, trs,
                             constraints, field_names, targets)
        if entry is None:
            continue
        if entry[0] == "p":   # series pruned away entirely by constraints
            any_pruned = True
            continue
        plan.append(entry)
        if entry[0] == "n":
            total += entry[3]
            any_trim = any_trim or entry[4]
            any_pruned = any_pruned or entry[5]
        else:
            total += len(entry[2])

    # --------------------------------------------- compressed-domain lane
    # lane zero: before any bytes move, pages provably skippable or
    # answerable from their encoded representation leave the plan; their
    # aggregate contributions ride the batch as pre-aggregated partials
    lane = None
    if compressed_spec is not None:
        lane = compressed_domain.ScanLane(compressed_spec, trs,
                                          vnode.index)
        with stages.stage("compressed_ms"):
            plan = lane.filter_plan(plan)
        if lane.engaged:
            any_pruned = True
            total = sum(e[3] if e[0] == "n" else len(e[2]) for e in plan)

    if total == 0:
        b = ScanBatch(table, np.empty(0, dtype=np.uint64), [],
                      np.empty(0, dtype=np.int64),
                      np.empty(0, dtype=np.int32), {})
        b._pages_pruned = any_pruned
        if lane is not None:
            lane_wants: dict[int, tuple] = {}
            lane.extend_cold_wants(lane_wants)
            for r, pms in lane_wants.values():
                r.fetch_pages(pms)
            with stages.stage("compressed_ms"):
                lane.run_jobs()
            lane.attach(b)
        return b

    # ------------------------------------------------- cold-tier prefetch
    # every page that survived pruning on a cold reader is fetched up
    # front in one coalesced ranged-GET pass, so the decode lanes below
    # hit the block cache instead of issuing a GET per page
    cold_wants: dict[int, tuple] = {}
    for entry in plan:
        if entry[0] != "n":
            continue
        for r, cm, cols, idx in entry[2]:
            if not getattr(r, "is_cold", False):
                continue
            lst = cold_wants.setdefault(id(r), (r, []))[1]
            for i in idx:
                lst.append(cm.time_pages[i])
                for name in field_names:
                    col = cols.get(name)
                    if col is not None:
                        lst.append(col.pages[i])
    if lane is not None:
        # closed-form jobs read only the pages they need (often just the
        # time page) — those ranges join the same coalesced GET pass, so
        # answered pages' VALUE bytes are never downloaded
        lane.extend_cold_wants(cold_wants)
    for r, pms in cold_wants.values():
        r.fetch_pages(pms)
    if lane is not None and lane.jobs:
        with stages.stage("compressed_ms"):
            lane.run_jobs()

    # ------------------------------------------------------- column typing
    ftypes: dict[str, ValueType] = {}
    for entry in plan:
        if entry[0] == "n":
            for _r, _cm, cols, _idx in entry[2]:
                for name, col in cols.items():
                    if name in field_names and name not in ftypes \
                            and col.pages:
                        ftypes[name] = ValueType(col.pages[0].value_type)
        else:
            for name, (vt, _v, _m) in entry[3].items():
                ftypes.setdefault(name, vt)

    # ----------------------------------------------------------- allocate
    ts_all = np.empty(total, dtype=np.int64)
    numeric_cols: dict[str, tuple[np.ndarray, np.ndarray]] = {}
    string_parts: dict[str, list] = {}
    string_valid: dict[str, np.ndarray] = {}
    for name, vt in ftypes.items():
        if vt in (ValueType.STRING, ValueType.GEOMETRY):
            string_parts[name] = []
            string_valid[name] = np.zeros(total, dtype=bool)
            continue
        dt = vt.numpy_dtype()
        numeric_cols[name] = (np.zeros(total, dtype=dt),
                              np.zeros(total, dtype=bool))

    # ------------------------------------------- descriptors per (file, col)
    # groups[id(reader)] = {"base": u8 view, "cols": {key: (desc, jobs)}}
    # key None = time column
    groups: dict[int, dict] = {}
    py_jobs: list = []   # (reader, pm, colname|None, out_off, vt)

    def _group(r):
        g = groups.get(id(r))
        if g is None:
            g = groups[id(r)] = {"base": r.buffer_array(), "cols": {},
                                 "reader": r}
        return g

    def _add_page(r, pm, colname, out_off, kind):
        g = _group(r)
        lst = g["cols"].setdefault(colname, ([], []))
        lst[0].append((pm.offset, pm.size, out_off, pm.n_rows, kind,
                       pm.n_values))
        lst[1].append((pm, out_off))

    kept_sids: list[int] = []
    keys = []
    counts: list[int] = []
    fallback_writes = []   # (entry, base_off)
    bytes_materialized = 0   # page bytes routed into ANY decode lane
    off = 0
    for entry in plan:
        if entry[0] == "f":
            _tag, sid, ts, fields = entry
            n = len(ts)
            fallback_writes.append((entry, off))
            kept_sids.append(sid)
            keys.append(vnode.index.get_series_key(sid))
            counts.append(n)
            off += n
            continue
        _tag, sid, chunks, n_rows, _trim, _pruned = entry
        kept_sids.append(sid)
        keys.append(vnode.index.get_series_key(sid))
        counts.append(n_rows)
        for r, cm, cols, idx in chunks:
            for i in idx:
                tp = cm.time_pages[i]
                if not (dev_lane is not None
                        and dev_lane.accepts(int(ValueType.INTEGER),
                                             tp.encoding)
                        and _submit_device_page(
                            dev_lane, r, tp, None, off, ValueType.INTEGER,
                            numeric_cols, string_parts, string_valid,
                            ts_all)):
                    if native_ok and not getattr(r, "is_cold", False):
                        _add_page(r, tp, None, off, 0)
                    else:
                        py_jobs.append((r, tp, None, off, None))
                for name in field_names:
                    col = cols.get(name)
                    if col is None:
                        continue   # absent column: stays zero/invalid
                    pm = col.pages[i]
                    vt = ftypes.get(name)
                    if dev_lane is not None and pm.value_type == int(vt) \
                            and (vt in (ValueType.STRING,
                                        ValueType.GEOMETRY)
                                 or dev_lane.accepts(pm.value_type,
                                                     pm.encoding)) \
                            and _submit_device_page(
                                dev_lane, r, pm, name, off, vt,
                                numeric_cols, string_parts, string_valid,
                                ts_all):
                        continue
                    if vt in (ValueType.STRING, ValueType.GEOMETRY):
                        _count_fallback("string")
                        py_jobs.append((r, pm, name, off, vt))
                        continue
                    if not native_ok:
                        # device lane declined and there is no native
                        # decoder in this build: per-page Python path
                        _count_fallback("native_unavailable")
                        py_jobs.append((r, pm, name, off, vt))
                        continue
                    if getattr(r, "is_cold", False):
                        # the native writer reads pages out of a local
                        # mmap (buffer_array) — cold pages have no local
                        # bytes, so they decode via the Python lane over
                        # the block cache
                        _count_fallback("cold_tier")
                        py_jobs.append((r, pm, name, off, vt))
                        continue
                    kind = _NATIVE_NUMERIC.get(pm.value_type)
                    if kind is None or pm.encoding not in _NATIVE_ENC[kind] \
                            or pm.value_type != int(vt):
                        # the last case: schema evolution changed the
                        # column's type between chunks — the output array
                        # is typed by ftypes, so a differently-typed page
                        # must go through the casting Python path, never
                        # the width-blind native writer
                        _count_fallback(
                            "value_type" if kind is None else
                            "encoding" if pm.encoding not in _NATIVE_ENC[kind]
                            else "schema_change")
                        py_jobs.append((r, pm, name, off, vt))
                        continue
                    _add_page(r, pm, name, off, kind)
                bytes_materialized += tp.size + sum(
                    cols[name].pages[i].size for name in field_names
                    if name in cols)
                if lane is not None:
                    lane.apply_page_masks(cm, i, off, total)
                off += tp.n_rows

    # ------------------------------------------------------ device decode
    # the third lane runs BEFORE the native tasks: device writebacks land
    # in the shared output arrays first, so a column split between lanes
    # is already complete when _finish's eager upload sees it, and kernel
    # failures join py_jobs before dirty_cols is computed
    if dev_lane is not None and dev_lane.pending():
        with stages.stage("device_decode_ms"):
            py_jobs.extend(dev_lane.run())

    # ------------------------------------------------------- native decode
    # one task per (file, column): pages of one column across files write
    # DISJOINT row ranges of the same output array, so tasks run
    # concurrently on the shared decode pool. Eager upload: once every
    # task of a column has finished cleanly, its final array is handed to
    # the uploader while the remaining columns still decode (decode N+1
    # overlaps device_put of N — device_put enqueues are async).
    tasks = []
    col_remaining: dict[str, int] = {}
    for g in groups.values():
        for colname, (desc_list, jobs) in g["cols"].items():
            desc = np.array(desc_list, dtype=np.int64).reshape(-1, 6)
            if colname is None:
                out_vals, out_valid = ts_all, None
            else:
                out_vals, out_valid = numeric_cols[colname]
                col_remaining[colname] = col_remaining.get(colname, 0) + 1
            tasks.append((g, colname, desc, out_vals, out_valid, jobs))

    uploader = None
    if upload_hook is not None and not fallback_writes \
            and not (any_trim and not trs.is_all) \
            and (lane is None or not lane.has_masks):
        # fallback series splice into every column after decode, a time
        # trim re-slices the arrays, and compressed-domain survivor masks
        # gather a subset — all would invalidate an eagerly shipped copy,
        # so only clean scans pipeline uploads
        uploader = upload_hook(total)
    dirty_cols = {j[2] for j in py_jobs}
    if uploader is not None and dev_lane is not None:
        # columns whose every page decoded on-device attach as device
        # arrays — decoded values never re-cross the PCIe pipe
        dev_lane.attach_device_columns(uploader, total)

    def _run(task):
        g, _colname, desc, out_vals, out_valid, _jobs = task
        return native.decode_pages(g["base"], desc, out_vals, out_valid,
                                   n_threads=per_task_threads)

    def _finish(task, status) -> bool:
        """Fold one task's result back in (main thread); False = abort."""
        g, colname, _desc, _ov, _om, jobs = task
        if status is None:
            return False   # library vanished mid-flight: legacy path
        for bi in np.nonzero(status)[0]:
            pm, out_off = jobs[bi]
            _count_fallback("native_reject")
            py_jobs.append((g["reader"], pm, colname, out_off,
                            ftypes.get(colname)))
            dirty_cols.add(colname)
        if colname is None:
            return True
        col_remaining[colname] -= 1
        if uploader is not None and col_remaining[colname] == 0 \
                and colname not in dirty_cols:
            vals, valid = numeric_cols[colname]
            uploader.put(colname, ftypes[colname], vals, valid)
        return True

    if len(tasks) > 1:
        from concurrent.futures import as_completed

        from ..utils.executor import submit as _submit

        per_task_threads = 1 if len(tasks) >= n_threads \
            else max(1, n_threads // len(tasks))
        futs = {_submit("decode", _run, t): t for t in tasks}
        aborted = False
        for f in as_completed(futs):
            if not _finish(futs[f], f.result()):
                aborted = True
        if aborted:
            return None
    else:
        per_task_threads = n_threads
        for t in tasks:
            if not _finish(t, _run(t)):
                return None

    # ------------------------------------------------ python page fallbacks
    for r, pm, colname, out_off, vt in py_jobs:
        deadline_mod.check_current()
        n = pm.n_rows
        if colname is None:
            ts_all[out_off:out_off + n] = r.read_time_page(pm)
            continue
        dense, nm = r.read_field_page(pm)
        if vt in (ValueType.STRING, ValueType.GEOMETRY):
            da = _as_dict_part(dense)
            if nm is None:
                codes = da.codes.astype(np.int32)
                valid_p = np.ones(n, dtype=bool)
            else:
                codes = np.zeros(n, dtype=np.int32)
                codes[~nm] = da.codes
                valid_p = ~nm
            string_parts[colname].append(
                (out_off, DictArray(codes, da.values)))
            string_valid[colname][out_off:out_off + n] = valid_p
            continue
        vals, valid = numeric_cols[colname]
        if nm is None:
            vals[out_off:out_off + n] = dense
            valid[out_off:out_off + n] = True
        else:
            vals[out_off:out_off + n][~nm] = dense
            valid[out_off:out_off + n] = ~nm

    # ------------------------------------------------ fallback series write
    for entry, base_off in fallback_writes:
        _tag, sid, ts, fields = entry
        n = len(ts)
        ts_all[base_off:base_off + n] = ts
        for name, (vt, vals_p, valid_p) in fields.items():
            if name not in ftypes:
                continue
            if vt in (ValueType.STRING, ValueType.GEOMETRY):
                da = _as_dict_part(vals_p)
                string_parts[name].append(
                    (base_off, DictArray(da.codes.astype(np.int32),
                                         da.values)))
                string_valid[name][base_off:base_off + n] = valid_p
            else:
                vals, valid = numeric_cols[name]
                vals[base_off:base_off + n] = vals_p
                valid[base_off:base_off + n] = valid_p

    sid_ordinal = np.repeat(
        np.arange(len(kept_sids), dtype=np.int32),
        np.asarray(counts, dtype=np.int64))

    # --------------------------------------------------- assemble + trim
    out_fields: dict = {}
    for name, (vals, valid) in numeric_cols.items():
        out_fields[name] = (ftypes[name], vals, valid)
    for name, parts in string_parts.items():
        das = [p[1] for p in parts]
        union = unify_dictionaries(das) if das else np.array([""],
                                                            dtype=object)
        codes_all = np.zeros(total, dtype=np.int32)
        for (p_off, da), d in zip(parts, das):
            codes_all[p_off:p_off + len(da.codes)] = d.remap_to(union)
        out_fields[name] = (ftypes[name], DictArray(codes_all, union),
                            string_valid[name])

    row_mask = lane.row_mask if lane is not None else None
    if (any_trim and not trs.is_all) or row_mask is not None:
        keep = _time_mask(ts_all, trs) if (any_trim and not trs.is_all) \
            else None
        if row_mask is not None:
            # late materialization: only rows surviving every
            # compressed-domain predicate mask are gathered
            keep = row_mask if keep is None else (keep & row_mask)
        if keep is not None and not keep.all():
            ts_all = ts_all[keep]
            sid_ordinal = sid_ordinal[keep]
            out_fields = {
                name: (vt,
                       (DictArray(v.codes[keep], v.values)
                        if isinstance(v, DictArray) else v[keep]),
                       m[keep])
                for name, (vt, v, m) in out_fields.items()}
            # drop series trimmed to zero rows and renumber ordinals
            pres = np.bincount(sid_ordinal, minlength=len(kept_sids))
            if (pres == 0).any():
                keep_s = np.nonzero(pres > 0)[0]
                remap = np.full(len(kept_sids), -1, dtype=np.int32)
                remap[keep_s] = np.arange(len(keep_s), dtype=np.int32)
                sid_ordinal = remap[sid_ordinal]
                kept_sids = [kept_sids[i] for i in keep_s]
                keys = [keys[i] for i in keep_s]

    b = ScanBatch(table, np.array(kept_sids, dtype=np.uint64), keys,
                  ts_all, sid_ordinal, out_fields)
    b._pages_pruned = any_pruned
    if lane is not None:
        bytes_materialized += lane.bytes_materialized
        lane.attach(b)
    if bytes_materialized:
        stages.count("compressed.bytes_materialized", bytes_materialized)
    if uploader is not None:
        uploader.attach(b)
    return b


def _plan_series(vnode, table, sid, files, mem_sids, trs, constraints,
                 field_names, targets):
    """→ ("n", sid, [(reader, chunk, cols, admitted idx)], n_rows, trim,
    pruned) | ("f", sid, ts, fields) | ("p",) (rows existed but every
    page was constraint-pruned) | None (no rows). `cols` maps QUERY
    column names to each chunk's ColumnMeta (id-resolved — see
    _resolve_chunk_col), so constraint pruning and page decode stay
    correct across RENAME COLUMN."""
    fallback = sid in mem_sids
    chunks = []
    if not fallback:
        version = vnode.summary.version
        for fm, r in files:
            cm = r.chunk(table, sid)
            if cm is None:
                continue
            tb = version.tombstone(fm)
            if not tb.is_empty and any(
                    e.matches_series(table, sid) for e in tb.entries):
                fallback = True
                break
            chunks.append((r, cm))
    if not fallback and len(chunks) > 1:
        chunks.sort(key=lambda rc: rc[1].min_ts)
        for (_ra, a), (_rb, b) in zip(chunks, chunks[1:]):
            if a.max_ts >= b.min_ts:
                fallback = True
                break
    if not fallback:
        for _r, cm in chunks:
            P = len(cm.time_pages)
            if any(len(c.pages) != P
                   or any(cp.n_rows != tp.n_rows for cp, tp
                          in zip(c.pages, cm.time_pages))
                   for c in cm.columns):
                fallback = True   # misaligned pages (defensive)
                break
    if fallback:
        parts = _series_parts(vnode, table, sid, field_names, trs)
        ts, fields = merge_parts(parts, field_names)
        if len(ts) == 0:
            return None
        return ("f", sid, ts, fields)
    admitted = []
    n_rows = 0
    trim = False
    pruned = False
    time_admitted = 0
    for r, cm in chunks:
        cols = {}
        maps = _chunk_maps(cm)
        for qname in field_names:
            cid, cands = targets[qname]
            c = _resolve_chunk_col(maps, cid, cands)
            if c is not None:
                cols[qname] = c
        idx = []
        cold = getattr(r, "is_cold", False)
        cold_pruned = 0
        for i, tp in enumerate(cm.time_pages):
            if not trs.is_all and not trs.overlaps(
                    TimeRange(tp.min_ts, tp.max_ts)):
                if cold:
                    cold_pruned += 1
                continue
            time_admitted += 1
            if constraints and not _page_admits(cols, i, constraints):
                pruned = True
                if cold:
                    cold_pruned += 1
                continue
            idx.append(i)
            n_rows += tp.n_rows
            # a page fully inside ONE range needs no row-level trim (all
            # its rows pass); anything else trims conservatively
            if not trs.is_all and not any(
                    r0.min_ts <= tp.min_ts and tp.max_ts <= r0.max_ts
                    for r0 in trs.ranges):
                trim = True
        if cold_pruned:
            _count_cold_pruned(cold_pruned)
        if idx:
            admitted.append((r, cm, cols, idx))
    if n_rows == 0:
        return ("p",) if pruned and time_admitted else None
    return ("n", sid, admitted, n_rows, trim, pruned)


def _discover_fields(vnode: VnodeStorage, table: str) -> list[str]:
    names: set[str] = set()
    schema = vnode.schemas.get(table)
    if schema is not None:
        return schema.field_names()
    for fm in vnode.summary.version.all_files():
        r = vnode.summary.version.reader(fm)
        g = r.groups.get(table)
        if g:
            for cm in g.chunks.values():
                names.update(c.name for c in cm.columns)
    for cache in [vnode.active, *vnode.immutables]:
        for (t, sid), sd in cache.series.items():
            if t == table:
                names.update(sd.field_chunks.keys())
    return sorted(names)
