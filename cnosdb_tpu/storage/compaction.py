"""Leveled compaction: picker + merge executor.

Role-parity with the reference's compaction subsystem
(tskv/src/compaction/: picker.rs LevelCompactionPicker/DeltaCompactionPicker,
compact.rs merge, job.rs): L0 holds overlapping delta files from flushes;
when enough accumulate they merge (plus overlapping L1 files) into L1;
levels 1..4 are size-bounded and spill upward. Merging is per-series with
per-field latest-file-wins on duplicate timestamps (same rule as memcache),
vectorized with numpy — no row-at-a-time k-way heap.
"""
from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from .. import faults
from ..models.schema import ValueType
from ..models.codec import Encoding
from ..models.strcol import DictArray, as_dict_part as _as_dict_part, \
    unify_dictionaries
from .memcache import _group_starts, _typed_array
from .summary import FileMeta, Version, VersionEdit, MAX_LEVEL

faults.register_point("compaction.run", __name__,
                      desc="merge compaction, before the version edit")
from .tombstone import tombstone_path
from .tsm import TsmWriter


@dataclass
class CompactReq:
    """One unit of compaction work."""

    files: list[FileMeta]
    target_level: int


class Picker:
    """Decides what to compact (reference picker.rs:17-300)."""

    def __init__(self, l0_trigger: int = 4,
                 level_base_size: int = 256 * 1024 * 1024,
                 level_size_multiplier: int = 4,
                 max_compact_files: int = 8,
                 max_output_file_size: int = 128 * 1024 * 1024):
        self.l0_trigger = l0_trigger
        self.level_base_size = level_base_size
        self.level_size_multiplier = level_size_multiplier
        self.max_compact_files = max_compact_files
        # bound per-output-file size (reference kv_option.rs:56-59
        # level_max_file_size): merges split into time-partitioned files so
        # later L0→L1 rounds rewrite only the overlapping window, not one
        # ever-growing level file (O(n²) write amplification otherwise)
        self.max_output_file_size = max_output_file_size
        # L0 files at least this big skip the merge rewrite entirely and
        # promote to L1 by metadata (a healthy flush is one of these; only
        # dribble-sized tails are worth physically combining)
        self.promote_file_size = max(1 << 20, max_output_file_size // 32)

    def level_max_size(self, level: int) -> int:
        return self.level_base_size * (self.level_size_multiplier ** max(0, level - 1))

    def pick(self, version: Version,
             exclude: frozenset = frozenset()) -> CompactReq | None:
        """`exclude`: file_ids compaction must not rewrite (cold-tiered
        files have no local bytes — storage/tiering.py). Exclusion keeps
        the oldest-first-prefix ordering rule by truncating at the first
        excluded file rather than skipping over it."""
        # delta compaction first: L0 count trigger
        l0 = sorted(version.levels[0].values(), key=lambda f: f.file_id)
        l0 = self._prefix_before_excluded(l0, exclude)
        if len(l0) >= self.l0_trigger:
            picked = l0[:self.max_compact_files]
            return CompactReq(
                picked + self._include_overlap(version, 1, picked, exclude),
                1)
        # level compaction: size overflow spills oldest files upward
        for level in range(1, MAX_LEVEL):
            if version.level_size(level) > self.level_max_size(level):
                files = sorted(version.levels[level].values(), key=lambda f: f.file_id)
                picked = self._prefix_before_excluded(
                    files, exclude)[: self.max_compact_files]
                if not picked:
                    continue   # level frozen behind cold files
                return CompactReq(
                    picked + self._include_overlap(version, level + 1,
                                                   picked, exclude),
                    level + 1)
        return None

    @staticmethod
    def _prefix_before_excluded(files: list[FileMeta],
                                exclude: frozenset) -> list[FileMeta]:
        if not exclude:
            return files
        out = []
        for f in files:
            if f.file_id in exclude:
                break
            out.append(f)
        return out

    def pick_promotions(self, version: Version,
                        exclude: frozenset = frozenset()) \
            -> list[tuple[FileMeta, int]]:
        """Files that can move one level up by METADATA ONLY (zero bytes
        re-encoded): flush-sized L0 files, and oldest files of an
        over-budget level.

        Order-preservation rules (dedup priority is level-then-file_id):
        - oldest-first PREFIX of the source level only — everything left
          behind must be newer than everything promoted;
        - promoted id must exceed every id at the TARGET level, so the
          moved rows keep outranking the data they outranked before (a
          rewrite-merge output at the target could otherwise carry a
          newer id than data that is logically older).
        Rewrites during steady bulk load thus reduce to flush + one final
        major pass; the mid-load level cascade is pointer moves."""
        # L0 → L1: flush-sized files skip the merge entirely
        max1 = max(version.levels[1], default=0)
        out = []
        for f in sorted(version.levels[0].values(), key=lambda x: x.file_id):
            if f.file_id in exclude:
                break
            if f.size >= self.promote_file_size and f.file_id > max1:
                out.append((f, 1))
            else:
                break
        if out:
            return out
        # over-budget level: move the oldest files up until under budget
        for level in range(1, MAX_LEVEL):
            excess = version.level_size(level) - self.level_max_size(level)
            if excess <= 0:
                continue
            max_t = max(version.levels[level + 1], default=0)
            for f in sorted(version.levels[level].values(),
                            key=lambda x: x.file_id):
                if f.file_id <= max_t or f.file_id in exclude:
                    break
                out.append((f, level + 1))
                max_t = f.file_id
                excess -= f.size
                if excess <= 0:
                    break
            if out:
                return out
        return out

    def _include_overlap(self, version: Version, target: int,
                         picked: list[FileMeta],
                         exclude: frozenset = frozenset()) -> list[FileMeta]:
        """Target-level files to rewrite alongside `picked` — ALL of the
        overlapping ones, or NONE.

        All-or-none is a correctness rule: dedup priority within a level
        is ascending file_id, so merging only SOME overlapping files would
        launder old rows into a new (highest) file_id and flip
        last-write-wins against the excluded files. None (tiering: the
        output lands as overlapping time-split files, ordered by id) is
        chosen when the overlap is big relative to the picked set —
        series-major ingest otherwise rewrites the whole level on every
        round, O(n²) write amplification (the reference bounds this the
        same way via level_max_file_size + picker cost heuristics)."""
        lo = min(f.min_ts for f in picked)
        hi = max(f.max_ts for f in picked)
        overlapped = [f for f in version.levels[target].values()
                      if f.overlaps(lo, hi)]
        if not overlapped:
            return []
        if exclude and any(f.file_id in exclude for f in overlapped):
            # a cold file overlaps: rewriting the rest would violate
            # all-or-none, so choose "none" (time-split output is legal)
            return []
        picked_sz = sum(f.size for f in picked)
        if sum(f.size for f in overlapped) > 2 * max(picked_sz, 1) \
                or len(overlapped) > self.max_compact_files:
            return []
        return overlapped


# ---------------------------------------------------------------------------
# merge executor
# ---------------------------------------------------------------------------
def run_compaction(version: Version, req: CompactReq, out_file_id: int,
                   alloc_id=None, max_out_bytes: int = 0,
                   schemas: dict | None = None) -> VersionEdit | None:
    """Merge req.files → time-partitioned file(s) at req.target_level;
    returns the edit (caller applies it via Summary). Tombstoned rows are
    dropped for good.

    With `alloc_id` (extra-file-id allocator) and `max_out_bytes` > 0 the
    output splits into ceil(input_bytes / max_out_bytes) contiguous TIME
    windows — files at the target level then cover disjoint ranges, so a
    later merge over a narrow time window rewrites only the overlapping
    files (the reference bounds per-level file size the same way,
    kv_option.rs level_max_file_size; without the bound every L0 round
    rewrites the whole level: O(n²) ingest amplification)."""
    if faults.ENABLED:
        faults.fire("compaction.run", out_file_id=out_file_id,
                    level=req.target_level)
    # priority must match scan._series_parts: higher level = older data =
    # lower priority (L4..L1 then L0), ascending file_id within a level.
    # Readers/tombstones come from the Version caches; Version._apply evicts
    # and closes them when the edit lands.
    readers = [(fm, version.reader(fm), version.tombstone(fm))
               for fm in req.files]
    readers.sort(key=lambda t: (-t[0].level, t[0].file_id))

    lo = min(fm.min_ts for fm in req.files)
    hi = max(fm.max_ts for fm in req.files)
    n_out = 1
    if alloc_id is not None and max_out_bytes > 0 and hi > lo:
        total_bytes = sum(fm.size for fm in req.files)
        n_out = int(max(1, min(64, -(-total_bytes // max_out_bytes))))
    # window k covers [bounds[k], bounds[k+1])
    bounds = [lo + (hi - lo + 1) * k // n_out for k in range(n_out + 1)]

    out_dir = "tsm" if req.target_level > 0 else "delta"
    writers: list[TsmWriter | None] = [None] * n_out
    # pre-assign ids in WINDOW order (unused windows waste an id, which is
    # harmless): output ids must ascend with time or pick_promotions'
    # id-ordering rules would refuse to ever promote these files
    fids: list[int] = [out_file_id] + [alloc_id() for _ in range(n_out - 1)]

    def writer(k: int) -> TsmWriter:
        if writers[k] is None:
            path = os.path.join(version.dir, out_dir, f"_{fids[k]:06d}.tsm")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            writers[k] = TsmWriter(path)
        return writers[k]

    tables: list[str] = sorted({t for _, r, _ in readers for t in r.tables()})
    for table in tables:
        schema = schemas.get(table) if schemas else None
        sids = sorted({int(s) for _, r, _ in readers for s in r.series_ids(table)})
        for sid in sids:
            merged = _merge_series(table, sid, readers, schema)
            if merged is None:
                continue
            ts, cols = merged
            if len(ts) == 0:
                continue
            if n_out == 1:
                writer(0).write_series(table, sid, ts, cols)
                continue
            cuts = np.searchsorted(ts, bounds[1:-1]).tolist()
            prev = 0
            for k, cut in enumerate(cuts + [len(ts)]):
                if cut > prev:
                    sliced = {
                        name: (cid, vt, enc, vals[prev:cut],
                               None if nm is None else nm[prev:cut])
                        for name, (cid, vt, enc, vals, nm) in cols.items()}
                    writer(k).write_series(table, sid, ts[prev:cut], sliced)
                prev = cut

    edit_del = [fm.file_id for fm, _, _ in readers]
    add_files = []
    for k, w in enumerate(writers):
        if w is None:
            continue
        footer = w.finish()
        path = os.path.join(version.dir, out_dir, f"_{fids[k]:06d}.tsm")
        add_files.append(FileMeta(fids[k], req.target_level, footer.min_ts,
                                  footer.max_ts, os.path.getsize(path),
                                  footer.series_count))
    # old tombstones die with their files (caller deletes files after apply)
    return VersionEdit(add_files=add_files, del_files=edit_del)


def _merge_series(table: str, sid: int, readers,
                  schema=None) -> tuple[np.ndarray, dict] | None:
    """Vectorized k-file merge of one series.

    Concatenate rows from all files (priority = position in `readers`,
    ascending file_id), stable-sort by ts, then per field pick the last
    valid value within each timestamp group — identical semantics to
    memcache.materialize.

    Columns unify by COLUMN ID (name only for id-less legacy chunks):
    after RENAME COLUMN reuses a name, same-named chunk columns from
    different schema eras are different columns and must not merge.
    The output column is written under the id's current schema name.
    """
    ts_parts: list[np.ndarray] = []
    col_parts: dict[object, list[tuple[int, np.ndarray, np.ndarray]]] = {}
    # key → (vt, enc, cid, latest-seen chunk name); readers are ordered
    # oldest→newest, so the last write gives the newest on-disk name
    col_types: dict[object, tuple[ValueType, Encoding, int, str]] = {}
    offsets: list[int] = []
    total = 0
    for fm, r, tb in readers:
        cm = r.chunk(table, sid)
        if cm is None:
            continue
        ts = r.read_series_timestamps(table, sid)
        keep = tb.mask_for(table, sid, ts)
        for col in cm.columns:
            pm0 = col.pages[0]
            vt = ValueType(pm0.value_type)
            vals, valid = r.read_series_column(table, sid, col.name)
            if keep is not None:
                vals, valid = vals[keep], valid[keep]
            key = col.column_id if col.column_id else ("name", col.name)
            col_parts.setdefault(key, []).append((total, vals, valid))
            if key not in col_types:
                col_types[key] = (vt, Encoding(pm0.encoding),
                                  col.column_id, col.name)
            else:
                t = col_types[key]
                col_types[key] = (t[0], t[1], t[2], col.name)
        if keep is not None:
            ts = ts[keep]
        ts_parts.append(ts)
        offsets.append(total)
        total += len(ts)
    if total == 0:
        return None
    ts_all = np.concatenate(ts_parts)
    # fast path: time-disjoint inputs in ascending order (the promotion
    # chain's steady state — each flush covers a later window) need no
    # sort and can hold no cross-part duplicates
    presorted = len(ts_parts) == 1 or bool((ts_all[1:] > ts_all[:-1]).all())
    if not presorted:
        order = np.argsort(ts_all, kind="stable")
        ts_sorted = ts_all[order]
        group_starts = _group_starts(ts_sorted)
        uts = ts_sorted[group_starts]
        idx = np.arange(total, dtype=np.int64)
    else:
        uts = ts_all
    out_cols = {}
    for key, parts in col_parts.items():
        vt, enc, cid, name = col_types[key]
        if cid and schema is not None:
            sc = schema.column_by_id(cid)
            if sc is not None:
                name = sc.name
        np_dtype = vt.numpy_dtype()
        is_str = np_dtype is object
        if is_str:
            # dictionary columns merge on int32 codes under a union dict;
            # re-encode writes the union straight back out
            das = [_as_dict_part(vals) for _, vals, _ in parts]
            union = unify_dictionaries(das)
            vals_all = np.zeros(total, dtype=np.int32)
        else:
            vals_all = np.empty(total, dtype=np_dtype)
        valid_all = np.zeros(total, dtype=bool)
        for i, (off, vals, valid) in enumerate(parts):
            vals_all[off:off + len(vals)] = (das[i].remap_to(union)
                                             if is_str else vals)
            valid_all[off:off + len(valid)] = valid
        if presorted:
            vals_out, valid_out = vals_all, valid_all
        else:
            vals_s = vals_all[order]
            valid_s = valid_all[order]
            score = np.where(valid_s, idx, -1)
            last_valid = np.maximum.reduceat(score, group_starts)
            valid_out = last_valid >= 0
            vals_out = vals_s[np.clip(last_valid, 0, None)]
        if is_str:
            vals_out = DictArray(vals_out, union)
        null_mask = None if valid_out.all() else ~valid_out
        if name in out_cols:
            # two ids converged on one name (a dropped column whose last
            # on-disk name a live column now holds): ids stay the scan
            # identity, the name only needs chunk-uniqueness
            name = f"{name}#{cid}"
        out_cols[name] = (cid, vt, enc, vals_out, null_mask)
    return uts, out_cols


def gc_compacted_files(version: Version, edit: VersionEdit):
    """Delete merged-away files + their tombstones (after Summary.apply)."""
    for fid in edit.del_files:
        for sub in ("delta", "tsm"):
            p = os.path.join(version.dir, sub, f"_{fid:06d}.tsm")
            if os.path.exists(p):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            tp = tombstone_path(p)
            if os.path.exists(tp):
                try:
                    os.unlink(tp)
                except OSError:
                    pass
