"""Leveled compaction: picker + merge executor.

Role-parity with the reference's compaction subsystem
(tskv/src/compaction/: picker.rs LevelCompactionPicker/DeltaCompactionPicker,
compact.rs merge, job.rs): L0 holds overlapping delta files from flushes;
when enough accumulate they merge (plus overlapping L1 files) into L1;
levels 1..4 are size-bounded and spill upward. Merging is per-series with
per-field latest-file-wins on duplicate timestamps (same rule as memcache),
vectorized with numpy — no row-at-a-time k-way heap.
"""
from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from ..models.schema import ValueType
from ..models.codec import Encoding
from .memcache import _group_starts, _typed_array
from .summary import FileMeta, Version, VersionEdit, MAX_LEVEL
from .tombstone import tombstone_path
from .tsm import TsmWriter


@dataclass
class CompactReq:
    """One unit of compaction work."""

    files: list[FileMeta]
    target_level: int


class Picker:
    """Decides what to compact (reference picker.rs:17-300)."""

    def __init__(self, l0_trigger: int = 4,
                 level_base_size: int = 256 * 1024 * 1024,
                 level_size_multiplier: int = 4,
                 max_compact_files: int = 8):
        self.l0_trigger = l0_trigger
        self.level_base_size = level_base_size
        self.level_size_multiplier = level_size_multiplier
        self.max_compact_files = max_compact_files

    def level_max_size(self, level: int) -> int:
        return self.level_base_size * (self.level_size_multiplier ** max(0, level - 1))

    def pick(self, version: Version) -> CompactReq | None:
        # delta compaction first: L0 count trigger
        l0 = sorted(version.levels[0].values(), key=lambda f: f.file_id)
        if len(l0) >= self.l0_trigger:
            picked = l0[:self.max_compact_files]
            lo = min(f.min_ts for f in picked)
            hi = max(f.max_ts for f in picked)
            overlapped = [f for f in version.levels[1].values() if f.overlaps(lo, hi)]
            return CompactReq(picked + overlapped[: self.max_compact_files], 1)
        # level compaction: size overflow spills oldest files upward
        for level in range(1, MAX_LEVEL):
            if version.level_size(level) > self.level_max_size(level):
                files = sorted(version.levels[level].values(), key=lambda f: f.file_id)
                picked = files[: self.max_compact_files]
                lo = min(f.min_ts for f in picked)
                hi = max(f.max_ts for f in picked)
                overlapped = [f for f in version.levels[level + 1].values()
                              if f.overlaps(lo, hi)][: self.max_compact_files]
                return CompactReq(picked + overlapped, level + 1)
        return None


# ---------------------------------------------------------------------------
# merge executor
# ---------------------------------------------------------------------------
def run_compaction(version: Version, req: CompactReq, out_file_id: int) -> VersionEdit | None:
    """Merge req.files → one file at req.target_level; returns the edit
    (caller applies it via Summary). Tombstoned rows are dropped for good."""
    # priority must match scan._series_parts: higher level = older data =
    # lower priority (L4..L1 then L0), ascending file_id within a level.
    # Readers/tombstones come from the Version caches; Version._apply evicts
    # and closes them when the edit lands.
    readers = [(fm, version.reader(fm), version.tombstone(fm))
               for fm in req.files]
    readers.sort(key=lambda t: (-t[0].level, t[0].file_id))

    out_path_dir = "tsm" if req.target_level > 0 else "delta"
    out_path = os.path.join(version.dir, out_path_dir, f"_{out_file_id:06d}.tsm")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    w = TsmWriter(out_path)
    wrote = False

    tables: list[str] = sorted({t for _, r, _ in readers for t in r.tables()})
    for table in tables:
        sids = sorted({int(s) for _, r, _ in readers for s in r.series_ids(table)})
        for sid in sids:
            merged = _merge_series(table, sid, readers)
            if merged is None:
                continue
            ts, cols = merged
            if len(ts) == 0:
                continue
            w.write_series(table, sid, ts, cols)
            wrote = True

    edit_del = [fm.file_id for fm, _, _ in readers]
    if not wrote:
        w.abort()
        edit = VersionEdit(del_files=edit_del)
    else:
        footer = w.finish()
        fm_out = FileMeta(out_file_id, req.target_level, footer.min_ts,
                          footer.max_ts, os.path.getsize(out_path),
                          footer.series_count)
        edit = VersionEdit(add_files=[fm_out], del_files=edit_del)
    # old tombstones die with their files (caller deletes files after apply)
    return edit


def _merge_series(table: str, sid: int, readers) -> tuple[np.ndarray, dict] | None:
    """Vectorized k-file merge of one series.

    Concatenate rows from all files (priority = position in `readers`,
    ascending file_id), stable-sort by ts, then per field pick the last
    valid value within each timestamp group — identical semantics to
    memcache.materialize.
    """
    ts_parts: list[np.ndarray] = []
    col_parts: dict[str, list[tuple[int, np.ndarray, np.ndarray]]] = {}
    col_types: dict[str, tuple[ValueType, Encoding, int]] = {}
    offsets: list[int] = []
    total = 0
    for fm, r, tb in readers:
        cm = r.chunk(table, sid)
        if cm is None:
            continue
        ts = r.read_series_timestamps(table, sid)
        keep = tb.mask_for(table, sid, ts)
        for col in cm.columns:
            pm0 = col.pages[0]
            vt = ValueType(pm0.value_type)
            vals, valid = r.read_series_column(table, sid, col.name)
            if keep is not None:
                vals, valid = vals[keep], valid[keep]
            col_parts.setdefault(col.name, []).append((total, vals, valid))
            if col.name not in col_types:
                col_types[col.name] = (vt, Encoding(pm0.encoding), col.column_id)
        if keep is not None:
            ts = ts[keep]
        ts_parts.append(ts)
        offsets.append(total)
        total += len(ts)
    if total == 0:
        return None
    ts_all = np.concatenate(ts_parts)
    order = np.argsort(ts_all, kind="stable")
    ts_sorted = ts_all[order]
    group_starts = _group_starts(ts_sorted)
    uts = ts_sorted[group_starts]
    idx = np.arange(total, dtype=np.int64)
    out_cols = {}
    for name, parts in col_parts.items():
        vt, enc, cid = col_types[name]
        np_dtype = vt.numpy_dtype()
        vals_all = np.empty(total, dtype=np_dtype if np_dtype is not object else object)
        valid_all = np.zeros(total, dtype=bool)
        for off, vals, valid in parts:
            vals_all[off:off + len(vals)] = vals
            valid_all[off:off + len(valid)] = valid
        vals_s = vals_all[order]
        valid_s = valid_all[order]
        score = np.where(valid_s, idx, -1)
        last_valid = np.maximum.reduceat(score, group_starts)
        valid_out = last_valid >= 0
        vals_out = vals_s[np.clip(last_valid, 0, None)]
        null_mask = None if valid_out.all() else ~valid_out
        out_cols[name] = (cid, vt, enc, vals_out, null_mask)
    return uts, out_cols


def gc_compacted_files(version: Version, edit: VersionEdit):
    """Delete merged-away files + their tombstones (after Summary.apply)."""
    for fid in edit.del_files:
        for sub in ("delta", "tsm"):
            p = os.path.join(version.dir, sub, f"_{fid:06d}.tsm")
            if os.path.exists(p):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            tp = tombstone_path(p)
            if os.path.exists(tp):
                try:
                    os.unlink(tp)
                except OSError:
                    pass
