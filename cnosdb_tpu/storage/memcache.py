"""In-memory write cache (one active + N immutable per vnode).

Role-parity with the reference's MemCache (tskv/src/mem_cache/
memcache.rs:30-295, series_data.rs): per-series row storage that absorbs
writes and converts to columnar pages at flush. Kept deliberately simple —
per-series Python lists of appended row chunks; sorting, last-write-wins
dedup and null-mask construction happen once, vectorized, at
`series_batches()` (flush or read) time, not per write.
"""
from __future__ import annotations

import numpy as np

from ..models.points import SeriesRows
from ..models.schema import ValueType

# Per-row bookkeeping overhead charged on top of the payload bytes:
# timestamp (8) + WAL seq share + python list/chunk slots. The old flat
# _APPROX_ROW_BYTES = 48 heuristic ignored dtypes entirely, so a
# string-heavy workload blew far past the configured cap before
# should_flush() noticed while a sparse float workload flushed early;
# sizing is now dtype-aware (see _series_rows_bytes).
_ROW_OVERHEAD_BYTES = 16


def _series_rows_bytes(sr: SeriesRows) -> int:
    """Dtype-aware payload estimate for one appended chunk: actual
    ndarray nbytes where the chunk is typed, element sizes otherwise
    (strings cost their encoded length + an object-header share), plus
    8 bytes per row of timestamps and the per-row overhead."""
    n = len(sr.timestamps)
    total = n * (8 + _ROW_OVERHEAD_BYTES)
    for _name, (vt, vals) in sr.fields.items():
        nb = getattr(vals, "nbytes", None)
        if nb is not None:                      # typed ndarray chunk
            total += int(nb)
            continue
        if vt == int(ValueType.STRING):
            for v in vals:
                total += (len(v) if isinstance(v, (str, bytes)) else 0) + 49
        elif vt == int(ValueType.BOOLEAN):
            total += len(vals)
        else:                                   # numeric python lists
            total += 8 * len(vals)
    return total


class SeriesData:
    """Accumulated rows of one series inside a memcache."""

    __slots__ = ("sid", "table", "ts_chunks", "field_chunks", "n_rows",
                 "seq_chunks")

    def __init__(self, sid: int, table: str):
        self.sid = sid
        self.table = table
        self.ts_chunks: list[list[int]] = []
        # field → list[(row_offset, value_type, values)]; offset aligns the
        # chunk with its rows in the concatenated timestamp stream
        self.field_chunks: dict[str, list[tuple[int, int, list]]] = {}
        self.n_rows = 0
        # WAL seq per ts chunk (non-decreasing — appends follow log order);
        # lets a delta scan take only the chunk suffix newer than a token
        self.seq_chunks: list[int] = []

    def append(self, sr: SeriesRows, seq: int = 0):
        off = self.n_rows
        self.ts_chunks.append(sr.timestamps)
        self.seq_chunks.append(seq)
        self.n_rows += len(sr.timestamps)
        for name, (vt, vals) in sr.fields.items():
            self.field_chunks.setdefault(name, []).append((off, vt, vals))

    def suffix(self, after_seq: int) -> "SeriesData | None":
        """→ a SeriesData holding only the chunks with seq > after_seq
        (None when there are none). Shares the chunk lists' objects —
        callers must treat the result as read-only."""
        import bisect

        i = bisect.bisect_right(self.seq_chunks, after_seq)
        if i >= len(self.ts_chunks):
            return None
        nd = SeriesData(self.sid, self.table)
        nd.ts_chunks = self.ts_chunks[i:]
        nd.seq_chunks = self.seq_chunks[i:]
        nd.n_rows = sum(len(c) for c in nd.ts_chunks)
        if nd.n_rows == 0:
            return None
        base = sum(len(c) for c in self.ts_chunks[:i])
        for name, chunks in self.field_chunks.items():
            kept = [(off - base, vt, vals) for (off, vt, vals) in chunks
                    if off >= base]
            if kept:
                nd.field_chunks[name] = kept
        return nd

    def materialize(self) -> tuple[np.ndarray, dict[str, tuple[ValueType, np.ndarray, np.ndarray]], np.ndarray]:
        """→ (sorted unique ts, {field: (vt, values, valid_mask)}, order)

        Sorts by time. Duplicate timestamps merge PER FIELD: each field
        takes its latest non-missing value across the duplicate rows
        (reference memcache RowData::extend — a later partial row overrides
        only the fields it carries). Typed-array and None-free list chunks
        materialize fully vectorized; only chunks actually carrying Nones
        pay a per-element pass.
        """
        if len(self.ts_chunks) == 1:
            ts = np.asarray(self.ts_chunks[0], dtype=np.int64)
        else:
            ts = np.concatenate(
                [np.asarray(c, dtype=np.int64) for c in self.ts_chunks]) \
                if self.ts_chunks else np.empty(0, dtype=np.int64)
        n = len(ts)
        order = np.argsort(ts, kind="stable")  # stable: append order within ties
        ts_sorted = ts[order]
        group_starts = _group_starts(ts_sorted)
        uts = ts_sorted[group_starts]
        out_fields: dict[str, tuple[ValueType, np.ndarray, np.ndarray]] = {}
        idx = np.arange(n, dtype=np.int64)
        for name, chunks in self.field_chunks.items():
            vt = ValueType(chunks[0][1])
            np_dtype = vt.numpy_dtype()
            typed = np_dtype is not object
            vals_full = (np.zeros(n, dtype=np_dtype) if typed
                         else np.empty(n, dtype=object))
            valid_full = np.zeros(n, dtype=bool)
            for off, _vt, vals in chunks:
                m = len(vals)
                if typed and isinstance(vals, np.ndarray):
                    vals_full[off:off + m] = vals
                    valid_full[off:off + m] = True
                elif typed and None not in vals:
                    vals_full[off:off + m] = np.asarray(vals, dtype=np_dtype)
                    valid_full[off:off + m] = True
                else:
                    for i, v in enumerate(vals):
                        if v is not None:
                            vals_full[off + i] = v
                            valid_full[off + i] = True
            vals_s = vals_full[order]
            valid_s = valid_full[order]
            # per-group index of last valid row (-1 if none), vectorized
            score = np.where(valid_s, idx, -1)
            last_valid = np.maximum.reduceat(score, group_starts) if n else score
            valid_out = last_valid >= 0
            gather = np.clip(last_valid, 0, None)
            vals_out = vals_s[gather]
            if not typed:
                vals_out = _typed_array(vals_out, valid_out, vt)
            out_fields[name] = (vt, vals_out, valid_out)
        return uts, out_fields, order

    def time_range(self) -> tuple[int, int]:
        lo, hi = 2**63 - 1, -(2**63)
        for c in self.ts_chunks:
            a = np.asarray(c, dtype=np.int64)
            if len(a):
                lo = min(lo, int(a.min()))
                hi = max(hi, int(a.max()))
        return lo, hi


def _group_starts(sorted_arr: np.ndarray) -> np.ndarray:
    """Indices where a new run of equal values begins in a sorted array."""
    n = len(sorted_arr)
    if n == 0:
        return np.empty(0, dtype=np.int64)
    starts = np.empty(n, dtype=bool)
    starts[0] = True
    starts[1:] = sorted_arr[1:] != sorted_arr[:-1]
    return np.nonzero(starts)[0]


def _typed_array(obj_vals: np.ndarray, valid: np.ndarray, vt: ValueType) -> np.ndarray:
    np_dtype = vt.numpy_dtype()
    if np_dtype is object:
        out = np.empty(len(obj_vals), dtype=object)
        out[:] = [v if m else "" for v, m in zip(obj_vals, valid)]
        return out
    out = np.zeros(len(obj_vals), dtype=np_dtype)
    if valid.any():
        idx = np.nonzero(valid)[0]
        out[idx] = np.array([obj_vals[i] for i in idx], dtype=np_dtype)
    return out


class MemCache:
    """Active or immutable write cache for one vnode."""

    def __init__(self, vnode_id: int, max_bytes: int = 128 * 1024 * 1024):
        self.vnode_id = vnode_id
        self.max_bytes = max_bytes
        self.series: dict[tuple[str, int], SeriesData] = {}
        self.approx_bytes = 0
        # row-column count (rows × (1 + fields)) kept separately so the
        # reference's usage gauge stays exact while approx_bytes carries
        # the real dtype-aware payload size
        self.rowcols = 0
        self.min_seq: int | None = None
        self.max_seq: int = 0
        self.min_ts = 2**63 - 1
        self.max_ts = -(2**63)
        self.immutable = False

    def write_series(self, table: str, sid: int, sr: SeriesRows, seq: int):
        assert not self.immutable, "write to immutable memcache"
        key = (table, sid)
        sd = self.series.get(key)
        if sd is None:
            sd = self.series[key] = SeriesData(sid, table)
        sd.append(sr, seq)
        nb = len(sr.timestamps)
        self.approx_bytes += _series_rows_bytes(sr)
        self.rowcols += nb * (1 + len(sr.fields))
        if self.min_seq is None:
            self.min_seq = seq
        self.max_seq = max(self.max_seq, seq)
        if len(sr.timestamps):
            from ..models.points import ts_bounds

            lo, hi = ts_bounds(sr.timestamps)
            self.min_ts = min(self.min_ts, lo)
            self.max_ts = max(self.max_ts, hi)

    @property
    def is_empty(self) -> bool:
        return not self.series

    def should_flush(self) -> bool:
        return self.approx_bytes >= self.max_bytes

    @property
    def usage_size(self) -> int:
        """The reference's cache-memory estimate (80 bytes per
        row-column: a 1-row single-field write reads 160 —
        vnode_cache_size.slt), decoupled from the flush-threshold
        accounting so dtype-aware sizing can't change gauge parity."""
        return self.rowcols * 80

    def mark_immutable(self):
        self.immutable = True

    def series_batches(self):
        """Yield (table, sid, ts, fields) in sorted (table, sid) order —
        flush consumes this to write a delta TSM file."""
        for (table, sid) in sorted(self.series.keys()):
            sd = self.series[(table, sid)]
            ts, fields, _ = sd.materialize()
            yield table, sid, ts, fields

    def delete_series(self, table: str, sid: int):
        self.series.pop((table, sid), None)

    def delete_table(self, table: str):
        for key in [k for k in self.series if k[0] == table]:
            del self.series[key]

    def delete_time_range(self, table: str, sids, min_ts: int, max_ts: int):
        """Row-level delete inside cache (reference memcache delete):
        rebuild affected series without rows in [min_ts, max_ts]."""
        sidset = set(int(s) for s in sids) if sids is not None else None
        for (tbl, sid), sd in list(self.series.items()):
            if tbl != table or (sidset is not None and sid not in sidset):
                continue
            ts, fields, _ = sd.materialize()
            keep = (ts < min_ts) | (ts > max_ts)
            if keep.all():
                continue
            nd = SeriesData(sid, tbl)
            if keep.any():
                kts = ts[keep].tolist()
                nf = {}
                for name, (vt, vals, valid) in fields.items():
                    v = [vals[i] if valid[i] else None for i in np.nonzero(keep)[0]]
                    nf[name] = (int(vt), v)
                from ..models.series import SeriesKey
                # the rebuilt chunk carries the cache's max seq: it holds
                # survivors of older writes, so a delta suffix taken at an
                # older token must include it (the delete itself also bumps
                # destructive_version, which forces a full rescan anyway)
                nd.append(SeriesRows(SeriesKey(tbl, []), kts, nf),
                          self.max_seq)
                self.series[(tbl, sid)] = nd
            else:
                del self.series[(tbl, sid)]

    def suffix_view(self, after_seq: int) -> "MemCache | None":
        """→ a read-only MemCache exposing only rows appended with WAL
        seq > after_seq, or None when this cache has nothing newer. Used
        by the delta scan (storage/scan.DeltaVnodeView) so an incremental
        rescan decodes only post-token memcache chunks."""
        if self.max_seq <= after_seq:
            return None
        out = MemCache(self.vnode_id, self.max_bytes)
        out.immutable = True
        out.min_seq = self.min_seq
        out.max_seq = self.max_seq
        # list(): scans run without the vnode lock, so a concurrent write
        # may grow the dict mid-iteration (same discipline as _series_parts)
        for key, sd in list(self.series.items()):
            suf = sd.suffix(after_seq)
            if suf is not None:
                out.series[key] = suf
        return out if out.series else None
