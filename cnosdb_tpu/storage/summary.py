"""Version manifest: the durable record of which TSM files form a vnode.

Role-parity with the reference's Summary (tskv/src/tsfamily/
summary.rs:28-240) + Version/LevelInfo (version.rs, level_info.rs:16-65):
every flush/compaction appends a VersionEdit (files added/removed, flushed
WAL seq) to a CRC'd record file; on open the edits replay into a Version —
the immutable picture of 5 levels of column files (L0 = delta, overlapping;
L1-L4 non-overlapping, time-descending levels).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

import msgpack

from ..errors import StorageError
from .record_file import RecordReader, RecordWriter
from .tsm import TsmReader

MAX_LEVEL = 4  # levels 0..4 (reference kv_option.rs:56-59)


@dataclass
class FileMeta:
    file_id: int
    level: int
    min_ts: int
    max_ts: int
    size: int
    series_count: int

    def to_list(self):
        return [self.file_id, self.level, self.min_ts, self.max_ts,
                self.size, self.series_count]

    @classmethod
    def from_list(cls, l):
        return cls(*l)

    def overlaps(self, min_ts: int, max_ts: int) -> bool:
        return self.min_ts <= max_ts and min_ts <= self.max_ts


@dataclass
class VersionEdit:
    """One atomic manifest mutation (reference summary.rs VersionEdit)."""

    add_files: list[FileMeta] = field(default_factory=list)
    del_files: list[int] = field(default_factory=list)
    flushed_seq: int | None = None

    def encode(self) -> bytes:
        return msgpack.packb([
            [f.to_list() for f in self.add_files],
            self.del_files,
            self.flushed_seq,
        ])

    @classmethod
    def decode(cls, data: bytes) -> "VersionEdit":
        add, rm, seq = msgpack.unpackb(data, raw=False)
        return cls([FileMeta.from_list(f) for f in add], list(rm), seq)


class Version:
    """Immutable-ish view: levels of files + flushed seq + open readers.

    Readers are opened lazily and cached per file (reference version.rs
    TsmReader LRU cache).
    """

    def __init__(self, dir_path: str):
        self.dir = dir_path
        self.levels: list[dict[int, FileMeta]] = [dict() for _ in range(MAX_LEVEL + 1)]
        self.flushed_seq = 0
        self.max_file_id = 0
        self._readers: dict[int, TsmReader] = {}
        self._tombstones: dict[int, "TsmTombstone"] = {}

    # -- mutation (only via Summary.apply) -------------------------------
    def _apply(self, edit: VersionEdit):
        for fid in edit.del_files:
            for lvl in self.levels:
                lvl.pop(fid, None)
            r = self._readers.pop(fid, None)
            if r:
                r.close()
            self._tombstones.pop(fid, None)
        for fm in edit.add_files:
            self.levels[fm.level][fm.file_id] = fm
            self.max_file_id = max(self.max_file_id, fm.file_id)
        if edit.flushed_seq is not None:
            self.flushed_seq = max(self.flushed_seq, edit.flushed_seq)

    # -- queries ---------------------------------------------------------
    def file_path(self, fm: FileMeta) -> str:
        sub = "delta" if fm.level == 0 else "tsm"
        return os.path.join(self.dir, sub, f"_{fm.file_id:06d}.tsm")

    def all_files(self) -> list[FileMeta]:
        out = []
        for lvl in self.levels:
            out.extend(lvl.values())
        return out

    def reader(self, fm: FileMeta) -> TsmReader:
        r = self._readers.get(fm.file_id)
        if r is None:
            # the single reader chokepoint: files recorded in the vnode's
            # cold registry (storage/tiering.py cold.json) open as cold
            # readers — sidecar metadata locally, page bytes via ranged
            # object-store GETs — so every scan/decode lane above stays
            # tier-transparent
            from . import tiering

            entry = tiering.cold_entry(self.dir, fm.file_id)
            if entry is not None:
                r = tiering.open_cold_reader(self.file_path(fm), entry)
            else:
                r = TsmReader(self.file_path(fm))
            self._readers[fm.file_id] = r
        return r

    def drop_reader(self, fid: int) -> None:
        """Close and forget one cached reader (tier/rehydrate flips the
        backing store; the next `reader()` call reopens the right kind)."""
        r = self._readers.pop(fid, None)
        if r:
            r.close()

    def tombstone(self, fm: FileMeta):
        """Cached per-file tombstone; all tombstone writes must go through
        this accessor so readers observe them without re-parsing disk."""
        from .tombstone import TsmTombstone

        tb = self._tombstones.get(fm.file_id)
        if tb is None:
            tb = self._tombstones[fm.file_id] = TsmTombstone(self.file_path(fm))
        return tb

    def level_size(self, level: int) -> int:
        return sum(f.size for f in self.levels[level].values())

    def close(self):
        for r in self._readers.values():
            r.close()
        self._readers.clear()


class Summary:
    """The manifest writer/recoverer for one vnode."""

    def __init__(self, dir_path: str):
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        os.makedirs(os.path.join(dir_path, "delta"), exist_ok=True)
        os.makedirs(os.path.join(dir_path, "tsm"), exist_ok=True)
        self.path = os.path.join(dir_path, "summary")
        self.version = Version(dir_path)
        if os.path.exists(self.path):
            for payload in RecordReader(self.path):
                self.version._apply(VersionEdit.decode(payload))
        self._writer = RecordWriter(self.path)
        self._edit_count = 0

    def apply(self, edit: VersionEdit, sync: bool = True):
        """Durably record an edit, then mutate the live version
        (reference summary.rs:134 apply_version_edit)."""
        self._writer.append(edit.encode())
        if sync:
            self._writer.sync()
        self.version._apply(edit)
        self._edit_count += 1
        if self._edit_count >= 512:
            self._rewrite()

    def _rewrite(self):
        """Compact the manifest to a single snapshot edit (reference
        rewrite-on-open summary.rs)."""
        self._writer.close()
        snapshot = VersionEdit(add_files=self.version.all_files(),
                               flushed_seq=self.version.flushed_seq)
        tmp = self.path + ".tmp"
        w = RecordWriter(tmp)
        w.append(snapshot.encode())
        w.close()
        os.replace(tmp, self.path)
        self._writer = RecordWriter(self.path)
        self._edit_count = 0

    def next_file_id(self) -> int:
        self.version.max_file_id += 1
        return self.version.max_file_id

    def close(self):
        self._writer.close()
        self.version.close()


def delete_unreferenced_files(version: Version):
    """GC: remove tsm files on disk not referenced by the version."""
    live = {version.file_path(f) for f in version.all_files()}
    for sub in ("delta", "tsm"):
        d = os.path.join(version.dir, sub)
        if not os.path.isdir(d):
            continue
        for name in os.listdir(d):
            p = os.path.join(d, name)
            if p not in live and name.endswith(".tsm"):
                try:
                    os.unlink(p)
                except OSError:
                    pass
