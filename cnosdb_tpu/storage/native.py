"""ctypes bindings for the native codec library (native/codecs.cpp).

Loads cnosdb_tpu/_native/libcnosdb_codecs.so when present (built via
`make -C native`; auto-built on first import when a compiler is around) and
exposes fused decode kernels; storage.codecs falls back to the vectorized
numpy pipeline when unavailable, so the package works without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

import threading
from ..utils import lockwatch

_LIB = None
_TRIED = False
_LOAD_LOCK = lockwatch.Lock("native.load")
_tls = threading.local()


def _lib_path() -> str:
    override = os.environ.get("CNOSDB_NATIVE_LIB")
    if override:
        return override   # e.g. the ASAN build in tests
    return os.path.join(os.path.dirname(os.path.dirname(__file__)),
                        "_native", "libcnosdb_codecs.so")


def _try_build() -> bool:
    native_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "native")
    if not os.path.isdir(native_dir):
        return False
    try:
        subprocess.run(["make", "-C", native_dir], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_lib_path())
    except Exception:
        return False


def get_lib():
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOAD_LOCK:
        return _get_lib_locked()


def _get_lib_locked():
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("CNOSDB_NO_NATIVE"):
        return None
    path = _lib_path()
    if not os.path.exists(path):
        if not _try_build():
            return None
    try:
        lib = ctypes.CDLL(path)
        lib.decode_delta_i64.restype = ctypes.c_int
        lib.decode_delta_i64.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t]
        lib.decode_xor_f64.restype = ctypes.c_int
        lib.decode_xor_f64.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t]
        lib.version.restype = ctypes.c_int
        if lib.version() != 1:
            return None
        if hasattr(lib, "encode_delta_i64"):
            lib.encode_delta_i64.restype = ctypes.c_int
            lib.encode_delta_i64.argtypes = [
                ctypes.POINTER(ctypes.c_int64), ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t]
        if hasattr(lib, "encode_xor_transpose_f64"):
            lib.encode_xor_transpose_f64.restype = None
            lib.encode_xor_transpose_f64.argtypes = [
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint8)]
        if hasattr(lib, "decode_pages"):
            lib.decode_pages.restype = ctypes.c_int
            lib.decode_pages.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t,   # base, base_len
                ctypes.POINTER(ctypes.c_int64),     # desc (n_pages × 6)
                ctypes.c_int64,                     # n_pages
                ctypes.c_void_p, ctypes.c_void_p,   # out_vals, out_valid
                ctypes.c_int64,                     # out_rows capacity
                ctypes.c_int, ctypes.c_int,         # check_crc, n_threads
                ctypes.POINTER(ctypes.c_int32)]     # out_status
        if hasattr(lib, "fused_seg_agg_f64"):
            lib.fused_seg_agg_f64.restype = ctypes.c_int
            lib.fused_seg_agg_f64.argtypes = [
                ctypes.POINTER(ctypes.c_int64),    # ts
                ctypes.POINTER(ctypes.c_int32),    # sid_ord
                ctypes.POINTER(ctypes.c_int64),    # group_lut
                ctypes.c_int64,                    # n_rows
                ctypes.c_int64, ctypes.c_int64,    # origin, interval
                ctypes.c_int64, ctypes.c_int64,    # bmin, n_buckets
                ctypes.c_void_p,                   # vals (f64 or null)
                ctypes.c_void_p,                   # valid (u8 or null)
                ctypes.c_void_p,                   # row_mask
                ctypes.c_int64,                    # num_segments
                ctypes.c_void_p, ctypes.c_void_p,  # presence, count
                ctypes.c_void_p, ctypes.c_void_p,  # sum, min
                ctypes.c_void_p, ctypes.c_void_p,  # max, out_seg
                ctypes.c_void_p, ctypes.c_void_p,  # first, first_ts
                ctypes.c_void_p, ctypes.c_void_p,  # last, last_ts
                ctypes.c_int]                      # n_threads
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def available() -> bool:
    return get_lib() is not None


def _get_scratch(size: int) -> np.ndarray:
    """Per-thread scratch: decodes run concurrently (query pool + the
    background compaction worker), a shared buffer would corrupt both."""
    buf = getattr(_tls, "scratch", None)
    if buf is None or len(buf) < size:
        buf = _tls.scratch = np.empty(max(size, 1 << 20), dtype=np.uint8)
    return buf


def decode_delta_i64(comp: bytes, width: int, first: int, n: int) -> np.ndarray | None:
    lib = get_lib()
    if lib is None:
        return None
    out = np.empty(n, dtype=np.int64)
    scratch = _get_scratch((n - 1) * width if n > 1 else 1)
    rc = lib.decode_delta_i64(
        comp, len(comp), width, first,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
        scratch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(scratch))
    return out if rc == 0 else None


def encode_delta_i64(values: np.ndarray) -> tuple[int, np.ndarray] | None:
    """Fused width-scan + zigzag-delta encode; returns (width, raw bytes of
    (n-1)*width) or None (unavailable / n<2 handled by caller)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "encode_delta_i64"):
        return None
    n = len(values)
    v = np.ascontiguousarray(values, dtype=np.int64)
    out = np.empty(max((n - 1) * 8, 1), dtype=np.uint8)
    width = lib.encode_delta_i64(
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(out))
    if width <= 0:
        return None
    return width, out[: (n - 1) * width]


def encode_xor_transpose_f64(values: np.ndarray) -> np.ndarray | None:
    """XOR-with-previous + byte-plane transpose in one native pass; returns
    the n*8 transposed bytes ready for zstd, or None when unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "encode_xor_transpose_f64"):
        return None
    v = np.ascontiguousarray(values).view(np.uint64)
    out = np.empty(len(v) * 8, dtype=np.uint8)
    lib.encode_xor_transpose_f64(
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(v),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out


def fused_seg_agg_f64(ts, sid_ord, group_lut, origin, interval, bmin,
                      n_buckets, vals, valid, row_mask, num_segments,
                      wants: dict, out_seg: bool = False,
                      n_threads: int = 8):
    """One-pass segment partials (native/segagg.cpp) — presence always;
    count/sum/min/max of `vals` per `wants`. → dict of arrays (plus
    'seg' when out_seg) or None when the library / shape is unavailable
    or a segment falls out of range."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "fused_seg_agg_f64"):
        return None
    n = len(ts)

    def p64(a):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))

    def voidp(a):
        return a.ctypes.data if a is not None else None

    presence = np.zeros(num_segments, dtype=np.int64)
    count = np.zeros(num_segments, dtype=np.int64) \
        if (wants.get("want_count") or wants.get("want_sum")) else None
    sum_ = np.zeros(num_segments, dtype=np.float64) \
        if wants.get("want_sum") else None
    mn = np.zeros(num_segments, dtype=np.float64) \
        if wants.get("want_min") else None
    mx = np.zeros(num_segments, dtype=np.float64) \
        if wants.get("want_max") else None
    first = np.zeros(num_segments, dtype=np.float64) \
        if wants.get("want_first") else None
    first_ts = np.zeros(num_segments, dtype=np.int64) \
        if first is not None else None
    last = np.zeros(num_segments, dtype=np.float64) \
        if wants.get("want_last") else None
    last_ts = np.zeros(num_segments, dtype=np.int64) \
        if last is not None else None
    seg = np.empty(n, dtype=np.int64) if out_seg else None
    rc = lib.fused_seg_agg_f64(
        p64(ts), sid_ord.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        p64(group_lut), n, origin, interval, bmin, n_buckets,
        voidp(vals), voidp(valid), voidp(row_mask), num_segments,
        voidp(presence), voidp(count), voidp(sum_), voidp(mn), voidp(mx),
        voidp(seg), voidp(first), voidp(first_ts), voidp(last),
        voidp(last_ts), n_threads)
    if rc != 0:
        return None
    out = {"presence": presence}
    if count is not None:
        out["count"] = count
    if sum_ is not None:
        out["sum"] = sum_
    if mn is not None:
        out["min"] = mn
    if mx is not None:
        out["max"] = mx
    if first is not None:
        out["first"] = first
        out["first_ts"] = first_ts
    if last is not None:
        out["last"] = last
        out["last_ts"] = last_ts
    if seg is not None:
        out["seg"] = seg
    return out


def pagedec_available() -> bool:
    lib = get_lib()
    return lib is not None and hasattr(lib, "decode_pages")


def decode_pages(base: np.ndarray, desc: np.ndarray,
                 out_vals: np.ndarray, out_valid: np.ndarray | None,
                 check_crc: bool = True,
                 n_threads: int = 1) -> np.ndarray | None:
    """Batch-decode TSM pages from one mmap'd file (native/pagedec.cpp).

    base: u8 view over the whole file; desc: (n_pages, 6) i64 page
    descriptors [src_off, src_size, out_off, n_rows, kind, n_values];
    out_vals/out_valid: preallocated columns the pages decode into.
    → per-page status array (0 = decoded; nonzero = caller must decode
    that page via the Python path), or None when unavailable.
    """
    lib = get_lib()
    if lib is None or not hasattr(lib, "decode_pages"):
        return None
    desc = np.ascontiguousarray(desc, dtype=np.int64)
    n_pages = len(desc)
    status = np.empty(n_pages, dtype=np.int32)
    lib.decode_pages(
        base.ctypes.data, len(base),
        desc.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n_pages,
        out_vals.ctypes.data,
        out_valid.ctypes.data if out_valid is not None else None,
        len(out_vals), 1 if check_crc else 0, n_threads,
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return status


def decode_xor_f64(comp: bytes, n: int) -> np.ndarray | None:
    lib = get_lib()
    if lib is None:
        return None
    out = np.empty(n, dtype=np.uint64)
    scratch = _get_scratch(n * 8)
    rc = lib.decode_xor_f64(
        comp, len(comp),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n,
        scratch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(scratch))
    return out.view(np.float64) if rc == 0 else None
