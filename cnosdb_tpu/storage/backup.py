"""Disaster-recovery plane: WAL archiving, snapshots, point-in-time restore.

Closes the last fail-stop scenario class ("Should I Hide My Duck in the
Lake?" / Taurus, PAPERS.md: the object store IS the database): every
robustness plane so far assumed one healthy replica survives, while this
module makes the PR 12 object store (utils/objstore.py) a durability
root, so total node loss and operator-error DROP/DELETE both recover.

Three lanes, one store, laid out under the ``wal_archive_uri`` prefix:

* **continuous WAL archiving** — every sealed segment streams to
  ``wal/{owner}/{vnode_id}/wal_XXXXXXXXXX.log`` from the Wal's
  seal listener (storage/wal.py). ``Wal.archive_fence`` keeps local GC
  behind the archived watermark, so an upload hiccup can never let
  ``purge_to`` delete the only copy of an acked write. RPO is bounded by
  the ``archive_lag_seconds`` gauge (age of the oldest sealed-but-
  unarchived segment; the active segment is bounded by segment size).
* **incremental consistent snapshots** — ``create_backup`` cuts every
  placement via ``vnode.file_snapshot()`` (flush + file capture) and
  records the per-vnode ScanToken as the cut witness; content-addressed
  objects land at ``objects/{owner}/{sha256}`` so an INCREMENTAL backup
  uploads only blobs absent from the previous manifest. Cold-tiered
  bytes are NOT re-uploaded — the snapshot carries cold.json + the
  ``.tsmc`` sidecars, which keep referencing the tiering store's
  objects. Manifests are self-contained (full file list each time: no
  chain walk at restore) at ``manifests/{owner}/{id}.json``; the catalog
  entry is meta-replicated (MetaStore.record_backup).
* **point-in-time restore** — ``restore_backup`` picks the newest
  backup at-or-before T, recreates the database/table schemas from the
  manifest (``AS new_name`` re-owns them), maps each manifest vnode onto
  a placement (same vnode id when it still exists, else a fresh bucket
  placement by recorded bucket_start/shard), wipes + installs via
  ``install_file_snapshot``, then replays archived WAL entries with
  seq > flushed_seq and append-ts ≤ T.

Every exit out of the archive/backup/restore lanes books an
``cnosdb_backup_total{op,outcome}`` reason (``backup-accounting`` lint);
fault points ``backup.archive`` / ``backup.manifest`` /
``restore.install`` ride the chaos sweep like every other node point.
"""
from __future__ import annotations

import json
import os
import time

from .. import faults
from ..errors import DatabaseNotFound, StorageError, TsmError
from ..utils import lockwatch, objstore, stages
from . import tiering
from .record_file import iter_records
from .wal import SEGMENT_PATTERN, WalEntry

faults.register_point("backup.archive", __name__,
                      desc="sealed WAL segment upload, before the put")
faults.register_point("backup.manifest", __name__,
                      desc="backup manifest write, after objects uploaded")
faults.register_point("restore.install", __name__,
                      desc="per-vnode restore, before wipe+install")


# ---------------------------------------------------------------------------
# archive-store configuration (process-global, mirrors tiering's _cfg:
# set from config/server wiring; credentials never persist in manifests)
# ---------------------------------------------------------------------------
_cfg_lock = lockwatch.Lock("backup.config")
_cfg: dict = {"uri": "", "options": {}, "store": None, "prefix": ""}


def configure_archive(uri: str | None, options: dict | None = None) -> None:
    """Point the DR plane at `uri` (s3://…, gcs://…, azblob://…, or a
    local directory path); empty/None unconfigures and detaches every
    archiver."""
    with _cfg_lock:
        _cfg["uri"] = (uri or "").strip()
        _cfg["options"] = dict(options or {})
        _cfg["store"] = None
        _cfg["prefix"] = ""
    if not (uri or "").strip():
        with _archivers_lock:
            _archivers.clear()


def archive_enabled() -> bool:
    with _cfg_lock:
        return bool(_cfg["uri"])


def _store_and_prefix():
    with _cfg_lock:
        if not _cfg["uri"]:
            raise StorageError(
                "WAL archive not configured (storage.wal_archive_uri)")
        if _cfg["store"] is None:
            store, prefix = objstore.store_for(_cfg["uri"], _cfg["options"])
            _cfg["store"] = store
            _cfg["prefix"] = prefix.rstrip("/")
        return _cfg["store"], _cfg["prefix"]


def _key(prefix: str, rel: str) -> str:
    return f"{prefix}/{rel}" if prefix else rel


def _wal_prefix(prefix: str, owner: str, vnode_id: int) -> str:
    return _key(prefix, f"wal/{owner}/{vnode_id}")


def _object_key(prefix: str, owner: str, sha: str) -> str:
    # content objects are scoped per owner: manifest GC walks this prefix
    # and must never see (or delete) another database's blobs
    return _key(prefix, f"objects/{owner}/{sha}")


def _manifest_key(prefix: str, owner: str, backup_id: str) -> str:
    return _key(prefix, f"manifests/{owner}/{backup_id}.json")


# ---------------------------------------------------------------------------
# accounting — cnosdb_backup_total{op,outcome}
# ---------------------------------------------------------------------------
_counts_lock = lockwatch.Lock("backup.counters")
_counts: dict[tuple[str, str], int] = {}


def _count_backup(op: str, outcome: str, n: int = 1) -> None:
    with _counts_lock:
        _counts[(op, outcome)] = _counts.get((op, outcome), 0) + n


def backup_snapshot() -> dict[tuple[str, str], int]:
    with _counts_lock:
        return dict(_counts)


def counters_reset() -> None:
    with _counts_lock:
        _counts.clear()


# ---------------------------------------------------------------------------
# continuous WAL archiving
# ---------------------------------------------------------------------------
class WalArchiver:
    """Per-WAL archive pump: fires from the seal listener, uploads the
    sealed segment, maintains the per-vnode watermark object, and fences
    local GC (`may_purge`). Idempotent by construction — a crash between
    seal and upload (backup.archive:crash) is healed by `catch_up()` on
    the next attach re-uploading the same bytes to the same key."""

    def __init__(self, owner: str, vnode_id: int, wal):
        self.owner = owner
        self.vnode_id = vnode_id
        self.wal = wal
        self.archived: dict[int, dict] = {}   # seg → {max_seq, max_ts}
        self._loaded = False

    def _prefix(self):
        store, prefix = _store_and_prefix()
        return store, _wal_prefix(prefix, self.owner, self.vnode_id)

    def _load_watermark(self) -> None:
        """Seed the archived-set from the durable watermark object, so a
        restarted process neither re-uploads everything nor un-fences
        segments the previous incarnation already archived."""
        try:
            store, pfx = self._prefix()
            wm = json.loads(store.get(f"{pfx}/watermark.json"))
        except (OSError, ValueError, objstore.ObjectStoreError,
                StorageError):
            # first contact (no watermark yet) or a flaky store: start
            # empty — catch_up re-uploads, which is idempotent
            stages.count_error("backup.watermark_load")
            wm = {}
        self.archived = {int(k): dict(v)
                         for k, v in (wm.get("segments") or {}).items()}

    def _put_watermark(self, store, pfx: str) -> None:
        wm = dict(self.watermark())
        wm["segments"] = {str(k): v for k, v in sorted(self.archived.items())}
        store.put(f"{pfx}/watermark.json", json.dumps(wm).encode())

    def watermark(self) -> dict:
        """{max_seq, max_ts} over every archived segment — the durable
        point up to which this vnode's log survives total node loss."""
        if not self.archived:
            return {"max_seq": 0, "max_ts": 0}
        return {
            "max_seq": max(v["max_seq"] for v in self.archived.values()),
            "max_ts": max(v["max_ts"] for v in self.archived.values()),
        }

    def on_seal(self, seg_id: int) -> None:
        # seal-listener entry: Wal._roll swallows exceptions (an archive
        # outage must not fail the write path; catch_up heals later)
        self.archive_segment(seg_id)

    def archive_segment(self, seg_id: int) -> bool:
        """Upload one sealed segment; → True when newly archived."""
        if not self._loaded:
            self._load_watermark()
            self._loaded = True
        if seg_id in self.archived:
            _count_backup("archive", "already_archived")
            return False
        path = self.wal._seg_path(seg_id)
        if faults.ENABLED:
            # before the put: a crash here is the sealed-not-archived
            # window the catch_up/replay regression tests pin down
            faults.fire("backup.archive", dir=self.wal.dir, seg=seg_id)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            _count_backup("archive", "segment_unreadable")
            raise StorageError(f"archive: cannot read sealed segment "
                               f"{path}: {e}")
        max_seq = max_ts = 0
        for payload in iter_records(raw):
            e = WalEntry.decode(payload)
            max_seq = max(max_seq, e.seq)
            max_ts = max(max_ts, e.ts)
        store, pfx = self._prefix()
        store.put(f"{pfx}/{os.path.basename(path)}", raw)
        self.archived[seg_id] = {"max_seq": max_seq, "max_ts": max_ts}
        self._put_watermark(store, pfx)
        _count_backup("archive", "segments_archived")
        _count_backup("archive", "bytes_uploaded", len(raw))
        return True

    def catch_up(self) -> int:
        """Archive every sealed-but-unarchived local segment (attach-time
        crash healing + the BACKUP barrier). → segments uploaded."""
        n = 0
        for seg in self.wal._list_segments()[:-1]:
            if self.archive_segment(seg):
                n += 1
        return n

    def may_purge(self, seg_id: int) -> bool:
        """Wal.archive_fence: local GC may drop a segment only once its
        bytes are durably archived."""
        if not self._loaded:
            self._load_watermark()
            self._loaded = True
        return seg_id in self.archived

    def lag_seconds(self) -> float:
        """Age of the oldest sealed-but-unarchived segment (0.0 when
        fully caught up) — the RPO bound for everything already sealed."""
        oldest = None
        for seg in self.wal._list_segments()[:-1]:
            if seg in self.archived:
                continue
            try:
                m = os.path.getmtime(self.wal._seg_path(seg))
            except OSError:
                stages.count_error("swallow.backup.lag_mtime")
                continue
            oldest = m if oldest is None else min(oldest, m)
        if oldest is None:
            return 0.0
        return max(0.0, time.time() - oldest)  # lint: disable=wallclock-duration (segment mtimes are wall clock; the gauge measures real-world RPO, not a code interval)


_archivers_lock = lockwatch.Lock("backup.archivers")
_archivers: dict[str, WalArchiver] = {}     # wal dir → archiver


def attach_wal(owner: str, vnode_id: int, wal) -> WalArchiver:
    """Idempotently wire one Wal into the archive plane: registry entry,
    seal listener, purge fence, then a catch_up pass (heals the crash-
    between-seal-and-upload window on every boot)."""
    with _archivers_lock:
        arch = _archivers.get(wal.dir)
        if arch is None or arch.wal is not wal:
            arch = WalArchiver(owner, vnode_id, wal)
            _archivers[wal.dir] = arch
    wal.archive_fence = arch.may_purge
    if arch.on_seal not in wal.seal_listeners:
        wal.seal_listeners.append(arch.on_seal)
    try:
        arch.catch_up()
    except (OSError, StorageError, objstore.ObjectStoreError):
        # boot must not fail on an archive outage: the fence keeps the
        # unarchived segments local, so nothing is lost — only lagging
        stages.count_error("swallow.backup.attach_catch_up")
    return arch


def attach_vnode(vnode) -> WalArchiver | None:
    """VnodeStorage boot hook (vnode.py): owner is the vnode directory's
    parent name (engine layout data/<owner>/<id>)."""
    if not archive_enabled():
        return None
    owner = os.path.basename(os.path.dirname(vnode.dir))
    return attach_wal(owner, vnode.vnode_id, vnode.wal)


def archivers() -> list[WalArchiver]:
    with _archivers_lock:
        return list(_archivers.values())


def archive_lag_seconds() -> float:
    """The /metrics RPO gauge: worst lag over every attached WAL."""
    lags = [a.lag_seconds() for a in archivers()]
    return max(lags) if lags else 0.0


def cluster_watermark(owner: str) -> dict:
    """min over this owner's attached WALs of the archived watermark —
    the conservative "no acked write at-or-before this is lost" bound
    the client-history checker verifies after total node loss."""
    marks = [a.watermark() for a in archivers() if a.owner == owner]
    if not marks:
        return {"max_seq": 0, "max_ts": 0}
    return {"max_seq": min(m["max_seq"] for m in marks),
            "max_ts": min(m["max_ts"] for m in marks)}


# ---------------------------------------------------------------------------
# incremental consistent snapshots
# ---------------------------------------------------------------------------
def _local_cut(vnode) -> dict:
    """One vnode's consistency cut: flush + file capture, the ScanToken
    as the witness, and a forced seal + catch_up so the archived log
    covers everything up to the cut."""
    snap = vnode.file_snapshot()          # flushes first
    token = vnode.scan_token()
    arch = attach_vnode(vnode)
    if arch is not None:
        vnode.wal.seal_active()
        arch.catch_up()
    try:
        cold_refs = tiering.cold_objects(vnode.dir)
    except TsmError:
        # torn registry rides the snapshot as-is; the restored vnode's
        # own recover path rebuilds it from the shipped sidecars
        stages.count_error("backup.cold_refs")
        cold_refs = []
    return {"files": snap["files"], "digests": snap["digests"],
            "flushed_seq": vnode.summary.version.flushed_seq,
            "cold_refs": cold_refs,
            "token": {"file_ids": sorted(token.file_ids),
                      "mem_seq": token.mem_seq}}


def create_backup(meta, engine, tenant: str, db: str,
                  incremental: bool = False, fetch_cut=None) -> dict:
    """Cut + upload one database backup; → the meta-recorded catalog
    entry. `fetch_cut(vnode_id, node_id)` lets the coordinator supply
    cuts for non-local placements."""
    owner = f"{tenant}.{db}"
    if not archive_enabled():
        _count_backup("backup", "unconfigured")
        raise StorageError("BACKUP: no archive store configured — set "
                           "[storage] wal_archive_uri")
    schema = meta.database(tenant, db)     # raises DatabaseNotFound
    store, prefix = _store_and_prefix()
    catalog = meta.list_backups(owner)
    prev_shas: set[str] = set()
    base_id = None
    if incremental and catalog:
        base_id = catalog[-1]["id"]
        try:
            prev = json.loads(
                store.get(_manifest_key(prefix, owner, base_id)))
        except (OSError, ValueError, objstore.ObjectStoreError):
            # base manifest unreadable: fall back to a full upload — the
            # new manifest is self-contained either way
            _count_backup("backup", "base_manifest_unreadable")
            prev, base_id = {"vnodes": []}, None
        for vn in prev.get("vnodes", []):
            for info in vn["files"].values():
                prev_shas.add(info["sha256"])
    uploaded = reused = nbytes = 0
    seen = set(prev_shas)
    vnodes_meta = []
    for bucket in meta.buckets_for(tenant, db):
        for shard, rs in enumerate(bucket.shard_group):
            vid = rs.leader_vnode_id
            v = engine.vnode(owner, vid)
            if v is not None:
                cut = _local_cut(v)
            elif fetch_cut is not None:
                cut = fetch_cut(vid, rs.leader_node_id)
            else:
                cut = None
            entry = {"vnode_id": vid, "shard": shard,
                     "bucket_start": bucket.start_time,
                     "bucket_end": bucket.end_time,
                     "flushed_seq": 0, "files": {}, "token": None,
                     "cold_refs": []}
            if cut is None:
                # placement never materialized locally: nothing to cut,
                # but the slot is still recorded so restore re-creates it
                _count_backup("backup", "vnode_empty")
                vnodes_meta.append(entry)
                continue
            for rel, raw in cut["files"].items():
                sha = cut["digests"][rel]
                if sha not in seen:
                    store.put(_object_key(prefix, owner, sha), raw)
                    uploaded += 1
                    nbytes += len(raw)
                else:
                    reused += 1
                seen.add(sha)
                entry["files"][rel] = {"sha256": sha, "size": len(raw)}
            entry["flushed_seq"] = cut["flushed_seq"]
            entry["token"] = cut["token"]
            entry["cold_refs"] = cut.get("cold_refs", [])
            vnodes_meta.append(entry)
    backup_id = f"{db}-{len(catalog):06d}"
    manifest = {
        "backup_id": backup_id, "tenant": tenant, "db": db, "owner": owner,
        "incremental": bool(incremental and base_id is not None),
        "base": base_id, "created_ts": time.time(),
        "db_options": schema.options.to_dict(),
        "tables": {t: s.to_dict()
                   for t, s in meta.tables.get(owner, {}).items()},
        "vnodes": vnodes_meta,
    }
    if faults.ENABLED:
        # between object uploads and the manifest write: a crash here
        # leaves orphaned (content-addressed, re-usable) objects and NO
        # manifest — the catalog never references a torn backup
        faults.fire("backup.manifest", owner=owner, backup_id=backup_id)
    store.put(_manifest_key(prefix, owner, backup_id),
              json.dumps(manifest).encode())
    entry = {"id": backup_id, "owner": owner,
             "incremental": manifest["incremental"], "base": base_id,
             "created_ts": manifest["created_ts"],
             "vnodes": len(vnodes_meta), "objects_uploaded": uploaded,
             "objects_reused": reused, "bytes": nbytes,
             "manifest_key": _manifest_key(prefix, owner, backup_id)}
    meta.record_backup(owner, entry)
    _count_backup("backup", "ok")
    return entry


# ---------------------------------------------------------------------------
# point-in-time restore
# ---------------------------------------------------------------------------
def _pick(catalog: list[dict], backup_id: str | None,
          to_ts: int | None) -> dict | None:
    if backup_id is not None:
        for e in catalog:
            if e["id"] == backup_id:
                return e
        return None
    if to_ts is not None:
        ok = [e for e in catalog if e["created_ts"] * 1e9 <= to_ts]
        return ok[-1] if ok else None
    return catalog[-1] if catalog else None


def _archived_entries(store, prefix: str, owner: str, vnode_id: int,
                      from_seq: int, to_ts: int | None = None) -> list:
    """Replay-set from the archived log: later-dup-wins dedup (same rule
    as Wal.replay), then filter to seq ≥ from_seq and ts ≤ to_ts.
    → [(seq, entry_type, data, term, ts)] in seq order."""
    pfx = _wal_prefix(prefix, owner, vnode_id)
    segs = sorted(k for k in store.list_prefix(pfx + "/")
                  if SEGMENT_PATTERN.match(os.path.basename(k)))
    entries: dict[int, WalEntry] = {}
    tail_seq = 0
    for seg_key in segs:
        for payload in iter_records(store.get(seg_key)):
            e = WalEntry.decode(payload)
            if e.seq <= tail_seq:
                entries = {k: v for k, v in entries.items() if k < e.seq}
            entries[e.seq] = e
            tail_seq = e.seq
    out = []
    for seq in sorted(entries):
        e = entries[seq]
        if seq < from_seq:
            continue
        if to_ts is not None and e.ts > to_ts:
            continue
        out.append((e.seq, e.entry_type, e.data, e.term, e.ts))
    return out


def _ensure_target_schema(meta, tenant: str, target_db: str,
                          manifest: dict) -> None:
    """Recreate database + table schemas from the manifest (RESTORE AS
    re-owns them); existing objects are left untouched."""
    from ..models.schema import (DatabaseOptions, DatabaseSchema,
                                 TskvTableSchema)

    try:
        meta.database(tenant, target_db)
    except DatabaseNotFound:
        meta.create_database(
            DatabaseSchema(tenant, target_db,
                           DatabaseOptions.from_dict(
                               manifest["db_options"])),
            if_not_exists=True)
    for tdict in manifest.get("tables", {}).values():
        ts = TskvTableSchema.from_dict(tdict)
        ts.db = target_db
        meta.create_table(ts, if_not_exists=True)


def _target_vnode(meta, tenant: str, target_db: str, vn: dict) -> int:
    """Map one manifest vnode onto a live placement: the original vnode
    id when it still belongs to the target db (in-place / total-loss
    restore), else a fresh placement in the bucket covering the recorded
    bucket_start (RESTORE AS / restore after DROP)."""
    owner = f"{tenant}.{target_db}"
    hit = meta.find_vnode(vn["vnode_id"])
    if hit is not None and hit[0] == owner:
        return vn["vnode_id"]
    bucket = meta.locate_bucket_for_write(tenant, target_db,
                                          vn["bucket_start"])
    rs = bucket.shard_group[vn["shard"] % len(bucket.shard_group)]
    return rs.leader_vnode_id


def install_vnode(engine, owner: str, vnode_id: int, snap: dict,
                  entries: list) -> None:
    """Local per-vnode restore: wipe (stale WAL included — its higher
    seqs would otherwise replay over the restored summary), reopen,
    install the snapshot, replay the archived entries, make durable."""
    engine.drop_vnode(owner, vnode_id)
    v = engine.open_vnode(owner, vnode_id)
    if snap["files"]:
        v.install_file_snapshot(snap)
    for (seq, entry_type, data, term, _ts) in entries:
        v.wal.append(entry_type, data, seq=seq, term=term)
        v.apply_entry(entry_type, data, seq)
    v.wal.sync()
    v.flush(sync=True)
    _count_backup("restore", "vnodes_installed")


def restore_backup(meta, engine, tenant: str, db: str,
                   backup_id: str | None = None, to_ts: int | None = None,
                   new_name: str | None = None, install=None) -> dict:
    """Restore `db` (optionally AS `new_name`, optionally to timestamp
    `to_ts` ns): manifest closure download → schema recreation → per-
    vnode install + archived-WAL replay. `install(owner, vnode_id, vn,
    snap, entries)` lets the coordinator route non-local placements."""
    owner = f"{tenant}.{db}"
    if not archive_enabled():
        _count_backup("restore", "unconfigured")
        raise StorageError("RESTORE: no archive store configured — set "
                           "[storage] wal_archive_uri")
    store, prefix = _store_and_prefix()
    entry = _pick(meta.list_backups(owner), backup_id, to_ts)
    if entry is None:
        _count_backup("restore", "no_backup")
        raise StorageError(
            f"RESTORE: no backup of {owner}"
            + (f" with id {backup_id!r}" if backup_id else "")
            + (f" created at or before ts {to_ts}" if to_ts else ""))
    manifest = json.loads(
        store.get(_manifest_key(prefix, owner, entry["id"])))
    target_db = new_name or db
    target_owner = f"{tenant}.{target_db}"
    _ensure_target_schema(meta, tenant, target_db, manifest)
    restored = []
    for vn in manifest["vnodes"]:
        tvid = _target_vnode(meta, tenant, target_db, vn)
        snap = {"files": {}, "digests": {}}
        for rel, info in vn["files"].items():
            snap["files"][rel] = store.get(
                _object_key(prefix, owner, info["sha256"]))
            snap["digests"][rel] = info["sha256"]
        entries = _archived_entries(store, prefix, owner, vn["vnode_id"],
                                    from_seq=vn["flushed_seq"] + 1,
                                    to_ts=to_ts)
        if faults.ENABLED:
            # before the wipe: a crash at nth=1 must leave the SOURCE
            # database untouched (the sweep's recovery oracle)
            faults.fire("restore.install", owner=target_owner,
                        vnode_id=tvid, source_vnode=vn["vnode_id"])
        if install is not None:
            install(target_owner, tvid, vn, snap, entries)
        else:
            install_vnode(engine, target_owner, tvid, snap, entries)
        restored.append(tvid)
    out = {"backup_id": entry["id"], "database": target_db,
           "owner": target_owner, "vnodes": restored, "to_ts": to_ts,
           "tables": sorted(manifest.get("tables", {}))}
    _count_backup("restore", "ok")
    return out


# ---------------------------------------------------------------------------
# manifest GC
# ---------------------------------------------------------------------------
def gc_backups(meta, tenant: str, db: str, keep: int = 2) -> dict:
    """Retire catalog entries beyond the newest `keep`: delete their
    manifests, then every content object no kept manifest references
    (the list_prefix walk — objects are owner-scoped, so other databases'
    blobs are out of reach). keep=0 wipes the owner's whole backup area
    (delete_prefix), archived WAL included."""
    owner = f"{tenant}.{db}"
    store, prefix = _store_and_prefix()
    catalog = meta.list_backups(owner)
    if keep <= 0:
        n = store.delete_prefix(_key(prefix, f"manifests/{owner}/"))
        n += store.delete_prefix(_key(prefix, f"objects/{owner}/"))
        n += store.delete_prefix(_key(prefix, f"wal/{owner}/"))
        meta.prune_backups(owner, 0)
        _count_backup("gc", "wiped")
        return {"removed": len(catalog), "objects_deleted": n}
    if len(catalog) <= keep:
        _count_backup("gc", "nothing_to_do")
        return {"removed": 0, "objects_deleted": 0}
    drop, kept = catalog[:-keep], catalog[-keep:]
    live: set[str] = set()
    for entry in kept:
        man = json.loads(
            store.get(_manifest_key(prefix, owner, entry["id"])))
        for vn in man["vnodes"]:
            for info in vn["files"].values():
                live.add(info["sha256"])
    deleted = 0
    opfx = _key(prefix, f"objects/{owner}/")
    for key in store.list_prefix(opfx):
        if os.path.basename(key) not in live:
            store.delete(key)
            deleted += 1
    for entry in drop:
        store.delete(_manifest_key(prefix, owner, entry["id"]))
    meta.prune_backups(owner, keep)
    _count_backup("gc", "manifests_removed", len(drop))
    _count_backup("gc", "objects_deleted", deleted)
    return {"removed": len(drop), "objects_deleted": deleted}
