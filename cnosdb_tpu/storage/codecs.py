"""Column block codecs.

Role mirrors the reference's tsm codec suite (tskv/src/tsm/codec/:
timestamp.rs delta+simple8b, integer.rs zigzag+simple8b, float.rs Gorilla
XOR, string.rs snappy/zstd/gzip/bzip/zlib, boolean.rs bitpack, dispatch
instance.rs:358-420) with the same Encoding ids, but the bit layouts are a
new design optimized for a TPU host: every transform is numpy-vectorized
(no per-value Python or bit-granular loops) so pages decode at memory
bandwidth into arrays ready for PCIe staging.

- DELTA / DELTA_TS (i64/u64/ts): zigzag(delta) → narrowest uint cast →
  zstd-1. DELTA_TS adds a constant-stride fast path (regular time series
  encode to 18 bytes). Decode = zstd → widen → unzigzag → cumsum.
- GORILLA (f64): XOR with previous (u64 view) → byte-plane transpose →
  zstd-1 (XOR zero-bytes compress like Gorilla's leading/trailing zero
  windows). Decode = zstd → untranspose → log-step prefix-XOR scan.
- QUANTILE: raw-LE → byte-plane transpose → zstd-3 (stands in for the
  reference's pco; keeps the enum id).
- BITPACK (bool): np.packbits.
- Strings: DICTIONARY pages — sorted unique values + narrow-cast int32
  codes → container codec (zstd/gzip/zlib/bzip; SNAPPY rides zlib-1 — no
  snappy lib in env, id preserved). Decode materializes the dictionary
  with one whole-blob UTF-8 decode + offset slicing (_materialize_dict)
  and the codes in one frombuffer; v1 length-prefixed pages remain
  readable. Code order == string order (models.strcol).

`split_for_device` is the host half of the device-decode lane
(ops/device_decode): it parses a block and runs ONLY the byte-container
stage, returning a kernel plan for the per-value transforms — the
device runs widen/unzigzag/cumsum/untranspose/XOR-scan/unpackbits.

Each encoded block: [1B encoding id][payload]; `encode`/`decode` dispatch
on column value type + id, matching the reference's one-byte code header
(tsm/codec block layout).
"""
from __future__ import annotations

import bz2
import gzip
import threading
import zlib

import numpy as np

from ..utils.zstd_compat import zstandard
from ..errors import CodecError
from ..models.codec import Encoding
from ..models.schema import ValueType
from ..models.strcol import DictArray

# zstd (de)compression CONTEXTS are not thread-safe for concurrent use;
# encodes run from parallel ingest writers + the compaction pool and
# decodes from the query pool concurrently, so each thread gets its own.
_tls = threading.local()


class _TlsZstd:
    def __init__(self, level: int | None):
        self._level = level
        self._attr = f"zstd_{level}"

    def _ctx(self):
        c = getattr(_tls, self._attr, None)
        if c is None:
            c = (zstandard.ZstdDecompressor() if self._level is None
                 else zstandard.ZstdCompressor(level=self._level))
            setattr(_tls, self._attr, c)
        return c

    def compress(self, data):
        return self._ctx().compress(data)

    def decompress(self, data):
        return self._ctx().decompress(data)


_ZSTD_C = _TlsZstd(1)
_ZSTD_C3 = _TlsZstd(3)
_ZSTD_D = _TlsZstd(None)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------
def zigzag(v: np.ndarray) -> np.ndarray:
    v = v.astype(np.int64, copy=False)
    return ((v << 1) ^ (v >> 63)).view(np.uint64)


def unzigzag(u: np.ndarray) -> np.ndarray:
    u = u.astype(np.uint64, copy=False)
    half = (u >> np.uint64(1)).view(np.int64)
    sign = (u & np.uint64(1)).view(np.int64)
    np.negative(sign, out=sign)
    half ^= sign
    return half


def _narrow_cast(u: np.ndarray) -> tuple[int, bytes]:
    """Cast u64 array to the narrowest of u8/u16/u32/u64; returns (width, bytes)."""
    if len(u) == 0:
        return 1, b""
    mx = int(u.max())
    if mx < 1 << 8:
        return 1, u.astype(np.uint8).tobytes()
    if mx < 1 << 16:
        return 2, u.astype(np.uint16).tobytes()
    if mx < 1 << 32:
        return 4, u.astype(np.uint32).tobytes()
    return 8, u.tobytes()


_WIDTH_DTYPE = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def _widen(width: int, raw: bytes) -> np.ndarray:
    return np.frombuffer(raw, dtype=_WIDTH_DTYPE[width]).astype(np.uint64)


def _byte_transpose(raw: np.ndarray, itemsize: int) -> bytes:
    return raw.view(np.uint8).reshape(-1, itemsize).T.tobytes()


def _byte_untranspose(raw: bytes, itemsize: int, dtype) -> np.ndarray:
    a = np.frombuffer(raw, dtype=np.uint8).reshape(itemsize, -1).T
    return np.ascontiguousarray(a).view(dtype).ravel()


def prefix_xor_scan(x: np.ndarray) -> np.ndarray:
    """Inclusive XOR scan (vectorized Gorilla 'undo'): single C pass."""
    return np.bitwise_xor.accumulate(x)


# ---------------------------------------------------------------------------
# integer / timestamp
# ---------------------------------------------------------------------------
def _encode_delta(values: np.ndarray, is_ts: bool) -> bytes:
    v = values.view(np.int64) if values.dtype == np.uint64 else values.astype(np.int64, copy=False)
    n = len(v)
    if n == 0:
        return b"\x00"
    deltas = np.diff(v)
    if is_ts and n > 1 and bool(np.all(deltas == deltas[0])):
        # constant stride: [1][n u32][first i64][stride i64]
        return (b"\x01" + np.uint32(n).tobytes() + np.int64(v[0]).tobytes()
                + np.int64(deltas[0]).tobytes())
    from . import native

    nat = native.encode_delta_i64(v) if n > 1 else None
    if nat is not None:
        width, raw_arr = nat
        comp = _ZSTD_C.compress(raw_arr.tobytes())
        return (b"\x02" + np.uint32(n).tobytes() + np.int64(v[0]).tobytes()
                + bytes([width]) + comp)
    zz = zigzag(deltas) if n > 1 else np.empty(0, dtype=np.uint64)
    width, raw = _narrow_cast(zz)
    comp = _ZSTD_C.compress(raw)
    return (b"\x02" + np.uint32(n).tobytes() + np.int64(v[0]).tobytes()
            + bytes([width]) + comp)


def _decode_delta(data: bytes, unsigned: bool) -> np.ndarray:
    tag = data[0]
    dtype = np.uint64 if unsigned else np.int64
    if tag == 0:
        return np.empty(0, dtype=dtype)
    n = int(np.frombuffer(data[1:5], dtype=np.uint32)[0])
    first = int(np.frombuffer(data[5:13], dtype=np.int64)[0])
    if tag == 1:
        stride = int(np.frombuffer(data[13:21], dtype=np.int64)[0])
        out = first + stride * np.arange(n, dtype=np.int64)
        return out.view(dtype)
    width = data[13]
    from . import native

    nat = native.decode_delta_i64(data[14:], width, first, n)
    if nat is not None:
        return nat.view(dtype)
    zz = _widen(width, _ZSTD_D.decompress(data[14:]))
    deltas = unzigzag(zz)
    out = np.empty(n, dtype=np.int64)
    out[0] = first
    if n > 1:
        np.cumsum(deltas, out=out[1:])
        out[1:] += first
    return out.view(dtype)


# ---------------------------------------------------------------------------
# float (Gorilla family)
# ---------------------------------------------------------------------------
def _encode_gorilla(values: np.ndarray) -> bytes:
    v = np.ascontiguousarray(values, dtype=np.float64).view(np.uint64)
    n = len(v)
    if n == 0:
        return b"\x00"
    from . import native

    nat = native.encode_xor_transpose_f64(v)
    if nat is not None:
        comp = _ZSTD_C.compress(nat.tobytes())
        return b"\x02" + np.uint32(n).tobytes() + comp
    x = v.copy()
    x[1:] ^= v[:-1]
    comp = _ZSTD_C.compress(_byte_transpose(x, 8))
    return b"\x02" + np.uint32(n).tobytes() + comp


def _decode_gorilla(data: bytes) -> np.ndarray:
    if data[0] == 0:
        return np.empty(0, dtype=np.float64)
    n = int(np.frombuffer(data[1:5], dtype=np.uint32)[0])
    from . import native

    nat = native.decode_xor_f64(data[5:], n)
    if nat is not None:
        return nat
    x = _byte_untranspose(_ZSTD_D.decompress(data[5:]), 8, np.uint64)
    assert len(x) == n, (len(x), n)
    return prefix_xor_scan(x).view(np.float64)


# ---------------------------------------------------------------------------
# raw / quantile-style
# ---------------------------------------------------------------------------
def _encode_raw_transposed(values: np.ndarray, level3: bool = False) -> bytes:
    a = np.ascontiguousarray(values)
    comp = (_ZSTD_C3 if level3 else _ZSTD_C).compress(_byte_transpose(a, a.itemsize))
    return np.uint32(len(a)).tobytes() + comp


def _decode_raw_transposed(data: bytes, dtype) -> np.ndarray:
    n = int(np.frombuffer(data[:4], dtype=np.uint32)[0])
    if n == 0:
        return np.empty(0, dtype=dtype)
    out = _byte_untranspose(_ZSTD_D.decompress(data[4:]), np.dtype(dtype).itemsize, dtype)
    assert len(out) == n
    return out


# ---------------------------------------------------------------------------
# boolean
# ---------------------------------------------------------------------------
def _encode_bool(values: np.ndarray) -> bytes:
    b = np.ascontiguousarray(values, dtype=np.bool_)
    return np.uint32(len(b)).tobytes() + np.packbits(b).tobytes()


def _decode_bool(data: bytes) -> np.ndarray:
    n = int(np.frombuffer(data[:4], dtype=np.uint32)[0])
    bits = np.unpackbits(np.frombuffer(data[4:], dtype=np.uint8), count=n)
    return bits.astype(np.bool_)


# ---------------------------------------------------------------------------
# strings — dictionary-encoded (codes + sorted unique dictionary)
# ---------------------------------------------------------------------------
# Page layout v2: [0xFFFFFFFF u32][n u32][u u32][dict lens u32 xU]
#                 [dict utf8 concat][codes width u8][codes narrow raw]
# Python-object decode cost is O(U) (the dictionary); the N row codes are
# one frombuffer. v1 ([n][lens][concat], per-row decode) remains readable.
_DICT_MARKER = 0xFFFFFFFF


def _pack_strings(values) -> bytes:
    da = values if isinstance(values, DictArray) else DictArray.from_objects(values)
    bs = [v.encode() if isinstance(v, str) else bytes(v) for v in da.values]
    lens = np.array([len(b) for b in bs], dtype=np.uint32)
    width, codes_raw = _narrow_cast(da.codes.astype(np.uint64))
    return (np.uint32(_DICT_MARKER).tobytes() + np.uint32(len(da.codes)).tobytes()
            + np.uint32(len(bs)).tobytes() + lens.tobytes() + b"".join(bs)
            + bytes([width]) + codes_raw)


def _materialize_dict(blob: bytes, lens: np.ndarray) -> np.ndarray:
    """Length-prefixed UTF-8 blob → object array of str, vectorized:
    ONE whole-blob decode + offset slicing instead of a per-entry
    bytes.decode() call (the former O(unique) loop dominated string-page
    cold decodes). Byte offsets equal char offsets only for ASCII, so a
    multibyte blob maps byte→char offsets via a cumsum over UTF-8
    start bytes (continuation bytes match 0b10xxxxxx)."""
    u = len(lens)
    values = np.empty(u, dtype=object)
    if u == 0:
        return values
    ends = np.cumsum(lens)
    starts = ends - lens
    text = blob.decode()
    if len(text) != len(blob):
        bs = np.frombuffer(blob, dtype=np.uint8)
        chars = np.concatenate(
            ([0], np.cumsum((bs & 0xC0) != 0x80)))   # chars in blob[:i]
        starts = chars[starts]
        ends = chars[ends]
    values[:] = [text[s:e] for s, e in zip(starts.tolist(), ends.tolist())]
    return values


def _unpack_strings(raw: bytes) -> DictArray:
    head = int(np.frombuffer(raw[:4], dtype=np.uint32)[0])
    if head != _DICT_MARKER:  # v1 page
        return DictArray.from_objects(_unpack_strings_v1(raw))
    n = int(np.frombuffer(raw[4:8], dtype=np.uint32)[0])
    u = int(np.frombuffer(raw[8:12], dtype=np.uint32)[0])
    lens = np.frombuffer(raw[12:12 + 4 * u], dtype=np.uint32)
    off = 12 + 4 * u
    blob_len = int(lens.sum())
    values = _materialize_dict(raw[off:off + blob_len], lens)
    off += blob_len
    width = raw[off]
    codes = _widen(width, raw[off + 1:])[:n].astype(np.int32)
    if u == 0:
        values = np.array([""], dtype=object)
    return DictArray(codes, values)


def _unpack_strings_v1(raw: bytes) -> np.ndarray:
    n = int(np.frombuffer(raw[:4], dtype=np.uint32)[0])
    lens = np.frombuffer(raw[4:4 + 4 * n], dtype=np.uint32)
    off = 4 + 4 * n
    return _materialize_dict(raw[off:off + int(lens.sum())], lens)


_STR_CONTAINERS = {
    Encoding.ZSTD: (lambda b: _ZSTD_C3.compress(b), lambda b: _ZSTD_D.decompress(b)),
    Encoding.GZIP: (lambda b: gzip.compress(b, 6), gzip.decompress),
    Encoding.ZLIB: (lambda b: zlib.compress(b, 6), zlib.decompress),
    Encoding.BZIP: (lambda b: bz2.compress(b, 9), bz2.decompress),
    Encoding.SNAPPY: (lambda b: zlib.compress(b, 1), zlib.decompress),
    Encoding.DEFAULT: (lambda b: _ZSTD_C3.compress(b), lambda b: _ZSTD_D.decompress(b)),
    Encoding.NULL: (lambda b: b, lambda b: b),
}


# ---------------------------------------------------------------------------
# dispatch — one codec table, keyed (value type, encoding id)
# ---------------------------------------------------------------------------
def _resolve_default(vt: ValueType, is_time: bool) -> Encoding:
    if is_time:
        return Encoding.DELTA_TS
    return {
        ValueType.FLOAT: Encoding.GORILLA,
        ValueType.INTEGER: Encoding.DELTA,
        ValueType.UNSIGNED: Encoding.DELTA,
        ValueType.BOOLEAN: Encoding.BITPACK,
        ValueType.STRING: Encoding.ZSTD,
        ValueType.GEOMETRY: Encoding.ZSTD,
    }[vt]


# device-decode lane: the host half -----------------------------------------
def _rejected(reason: str):
    """No device lane for this block; the CALLER books `reason` (scan's
    _count_fallback + device_decode.count_outcome — storage stays
    jax-free, so the counters live across the hook boundary)."""
    return None, reason


def _split_delta(payload: bytes):
    tag = payload[0]
    if tag == 0:
        return _rejected("empty")
    n = int(np.frombuffer(payload[1:5], dtype=np.uint32)[0])
    first = int(np.frombuffer(payload[5:13], dtype=np.int64)[0])
    if tag == 1:
        stride = int(np.frombuffer(payload[13:21], dtype=np.int64)[0])
        return {"kind": "delta_const", "n": n, "first": first,
                "stride": stride}, None
    width = payload[13]
    raw = _ZSTD_D.decompress(payload[14:])
    return {"kind": "delta", "n": n, "first": first, "width": width,
            "raw": raw}, None


def _split_gorilla(payload: bytes):
    if payload[0] == 0:
        return _rejected("empty")
    n = int(np.frombuffer(payload[1:5], dtype=np.uint32)[0])
    return {"kind": "gorilla", "n": n,
            "raw": _ZSTD_D.decompress(payload[5:])}, None


def _split_bitpack(payload: bytes):
    n = int(np.frombuffer(payload[:4], dtype=np.uint32)[0])
    if n == 0:
        return _rejected("empty")
    return {"kind": "bitpack", "n": n, "raw": payload[4:]}, None


def _split_dict(raw: bytes):
    """Container-stripped string page → dict plan (codes stay narrow,
    dictionary materialized host-side once per page)."""
    head = int(np.frombuffer(raw[:4], dtype=np.uint32)[0])
    if head != _DICT_MARKER:
        return _rejected("string_v1")
    n = int(np.frombuffer(raw[4:8], dtype=np.uint32)[0])
    if n == 0:
        return _rejected("empty")
    u = int(np.frombuffer(raw[8:12], dtype=np.uint32)[0])
    lens = np.frombuffer(raw[12:12 + 4 * u], dtype=np.uint32)
    off = 12 + 4 * u
    blob_len = int(lens.sum())
    values = _materialize_dict(raw[off:off + blob_len], lens)
    if u == 0:
        values = np.array([""], dtype=object)
    off += blob_len
    width = raw[off]
    return {"kind": "dict", "n": n, "width": width,
            "raw": raw[off + 1:off + 1 + n * width],
            "values": values}, None


class _Codec:
    """One (value type, encoding) dispatch row.

    ``enc(values, is_time) -> payload`` and ``dec(payload) -> array``
    implement the byte codec; ``split(payload) -> (plan, reason)`` is the
    host half of the device-decode lane (None ⇒ the device lane rejects
    with "encoding"). encode/decode/split_for_device all dispatch through
    this one table, and downstream lanes (device decode, the
    compressed-domain lane) register per-``kind`` handlers against the
    split plans instead of growing their own if/elif ladders.
    """
    __slots__ = ("enc", "dec", "split")

    def __init__(self, enc, dec, split=None):
        self.enc = enc
        self.dec = dec
        self.split = split


def _int_rows(unsigned: bool) -> dict:
    dtype = np.uint64 if unsigned else np.int64

    def dec_delta(payload):
        return _decode_delta(payload, unsigned)

    def dec_raw(payload):
        return _decode_raw_transposed(payload, dtype)

    def enc_raw(values, is_time):
        return _encode_raw_transposed(np.asarray(values), level3=True)

    raw_codec = _Codec(enc_raw, dec_raw)
    return {
        Encoding.DELTA: _Codec(
            lambda values, is_time: _encode_delta(np.asarray(values), is_ts=is_time),
            dec_delta, _split_delta),
        Encoding.DELTA_TS: _Codec(
            lambda values, is_time: _encode_delta(np.asarray(values), is_ts=True),
            dec_delta, _split_delta),
        Encoding.QUANTILE: raw_codec,
        Encoding.NULL: raw_codec,
    }


def _float_rows() -> dict:
    def enc_raw(values, is_time):
        return _encode_raw_transposed(np.asarray(values, dtype=np.float64), level3=True)

    def dec_raw(payload):
        return _decode_raw_transposed(payload, np.float64)

    raw_codec = _Codec(enc_raw, dec_raw)
    return {
        Encoding.GORILLA: _Codec(
            lambda values, is_time: _encode_gorilla(np.asarray(values)),
            _decode_gorilla, _split_gorilla),
        Encoding.QUANTILE: raw_codec,
        Encoding.NULL: raw_codec,
    }


def _bool_rows() -> dict:
    codec = _Codec(lambda values, is_time: _encode_bool(np.asarray(values)),
                   _decode_bool, _split_bitpack)
    return {Encoding.BITPACK: codec, Encoding.NULL: codec}


def _str_row(container: Encoding) -> _Codec:
    comp, decomp = _STR_CONTAINERS[container]
    return _Codec(lambda values, is_time: comp(_pack_strings(values)),
                  lambda payload: _unpack_strings(decomp(payload)),
                  lambda payload: _split_dict(decomp(payload)))


_CODEC_TABLE: dict[tuple[ValueType, Encoding], _Codec] = {}
for _vt, _rows in ((ValueType.INTEGER, _int_rows(False)),
                   (ValueType.UNSIGNED, _int_rows(True)),
                   (ValueType.FLOAT, _float_rows()),
                   (ValueType.BOOLEAN, _bool_rows())):
    for _e, _codec in _rows.items():
        _CODEC_TABLE[(_vt, _e)] = _codec
for _vt in (ValueType.STRING, ValueType.GEOMETRY):
    for _e in _STR_CONTAINERS:
        _CODEC_TABLE[(_vt, _e)] = _str_row(_e)
_VTS_WITH_ROWS = {vt for vt, _ in _CODEC_TABLE}


def _codec_for(vt: ValueType, encoding: Encoding) -> _Codec | None:
    codec = _CODEC_TABLE.get((vt, encoding))
    if codec is None and vt in (ValueType.STRING, ValueType.GEOMETRY):
        # string pages round-trip under any container id: unknown ids ride
        # the DEFAULT container (historic `_STR_CONTAINERS.get` fallback)
        codec = _CODEC_TABLE.get((vt, Encoding.DEFAULT))
    return codec


def encode(values: np.ndarray, vt: ValueType, encoding: Encoding = Encoding.DEFAULT,
           is_time: bool = False) -> bytes:
    """Encode a column block → [1B encoding id][payload]."""
    if encoding == Encoding.DEFAULT:
        encoding = _resolve_default(vt, is_time)
    codec = _codec_for(vt, encoding)
    if codec is None:
        raise CodecError("illegal encoding for type", vt=vt.name, encoding=encoding.name)
    try:
        return bytes([int(encoding)]) + codec.enc(values, is_time)
    except CodecError:
        raise
    except Exception as e:  # pragma: no cover - defensive
        raise CodecError(f"encode failed: {e}", vt=vt.name, encoding=encoding.name)


def decode(data: bytes, vt: ValueType) -> np.ndarray:
    """Decode a column block produced by `encode`."""
    if len(data) == 0:
        raise CodecError("empty block")
    encoding = Encoding(data[0])
    codec = _codec_for(vt, encoding)
    if codec is None:
        raise CodecError("illegal encoding for type", vt=vt.name, encoding=encoding.name)
    try:
        return codec.dec(data[1:])
    except CodecError:
        raise
    except Exception as e:
        raise CodecError(f"decode failed: {e}", vt=vt.name, encoding=encoding.name)


def split_for_device(data: bytes, vt: ValueType):
    """Host half of a device decode → (plan, None) or (None, reason).

    Parses one encoded block ([1B id][payload]) and runs only the byte
    container (zstd et al). The plan dict describes the remaining
    per-value work for ops/device_decode's batched kernels:
      {"kind": "delta", "n", "first", "width", "raw"}    zigzag deltas
      {"kind": "delta_const", "n", "first", "stride"}    18-byte pages
      {"kind": "gorilla", "n", "raw"}                    u8 byte planes
      {"kind": "bitpack", "n", "raw"}                    packed bits
      {"kind": "dict", "n", "width", "raw", "values"}    narrow codes +
                                                         host dictionary
    Rejections are total: every early return passes through _rejected()
    (enforced by the device-decode-accounting lint rule). The same plans
    feed the compressed-domain lane's closed-form handlers
    (storage/compressed_domain.py), which key off plan["kind"].
    """
    if len(data) == 0:
        return _rejected("empty")
    encoding = Encoding(data[0])
    codec = _codec_for(vt, encoding)
    if codec is None:
        return _rejected("encoding" if vt in _VTS_WITH_ROWS else "value_type")
    if codec.split is None:
        return _rejected("encoding")
    return codec.split(data[1:])


def encode_timestamps(ts: np.ndarray, encoding: Encoding = Encoding.DEFAULT) -> bytes:
    return encode(ts, ValueType.INTEGER, encoding, is_time=True)


def decode_timestamps(data: bytes) -> np.ndarray:
    return decode(data, ValueType.INTEGER)
