"""TSM file format: the immutable columnar store.

Role-parity with the reference's TSM v2 (tskv/src/tsm/writer.rs:40-540,
reader.rs, page.rs, chunk.rs, chunk_group.rs, footer.rs): a file holds, per
table (chunk group), per series (chunk), per column, encoded pages; the
footer carries a series-id bloom filter and the meta tree offset; pages
carry null bitsets and min/max/sum/count statistics used for pruning and
for metadata-only aggregates (reference pushdown_agg_reader.rs answers
COUNT from page meta without decoding).

The byte layout is a fresh design (not the reference's): meta sections are
msgpack (fast C codec), pages are [null bitset][codec block] with crc32,
and chunks keep whole-series column runs contiguous so a scan materializes
large numpy arrays per column — the shape the TPU staging path wants.

Layout:
    [magic u32 | version u8]
    page data ...                         (sequential, crc'd)
    meta: msgpack chunk tree              (zstd)
    bloom: series-id bloom bits
    footer (fixed 64B): meta_off u64 | meta_len u64 | bloom_off u64 |
        bloom_len u64 | min_ts i64 | max_ts i64 | series_count u32 |
        crc u32 | magic u32 | version u8 | pad
"""
from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass, field

import msgpack
import numpy as np

from .. import faults
from ..errors import TsmError, ChecksumMismatch
from ..utils.zstd_compat import zstandard
from ..models.codec import Encoding
from ..models.schema import ValueType
from ..models.strcol import DictArray
from ..utils.bloom import BloomFilter
from . import codecs

MAGIC = 0x7C05DB01
VERSION = 1
FOOTER_SIZE = 64

faults.register_point("tsm.write", __name__,
                      desc="sealed TSM file finalize (corrupt-at-rest site)")

# thread-local contexts (parallel flush/compaction writers + query-pool
# readers; zstd contexts are not safe for concurrent use)
_ZC = codecs._TlsZstd(1)
_ZD = codecs._TlsZstd(None)


def _string_signature(dense) -> bytes | None:
    """Trigram page-skip signature for one string page (flush and
    compaction both land here via TsmWriter.write_series). Advisory:
    any failure yields None (page always admits), never a failed seal.
    Lazy import — strkernels lives in ops/, whose package init pulls jax;
    host-only storage paths must not pay that unless a string page is
    actually sealed."""
    try:
        from ..ops import strkernels

        if isinstance(dense, DictArray):
            uniques = dense.values[np.unique(dense.codes)]
        else:
            uniques = {v for v in dense if isinstance(v, str)}
        return strkernels.build_page_signature(uniques)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# metadata model
# ---------------------------------------------------------------------------
@dataclass
class PageMeta:
    offset: int
    size: int
    n_rows: int           # logical rows in the page (incl. nulls)
    n_values: int         # non-null values
    value_type: int       # ValueType
    encoding: int         # Encoding id actually used
    min_ts: int
    max_ts: int
    stat_min: float | int | None = None
    stat_max: float | int | None = None
    stat_sum: float | int | None = None
    # stats format era. 0 = legacy writers whose float stats excluded ±inf
    # (a page holding inf rows could carry a finite-only interval); 1 =
    # ±inf-inclusive stats. Predicate pruning (scan._page_admits) must not
    # prune float pages below version 1 — their interval may lie.
    stats_version: int = 0
    # string pages: trigram bloom signature over the page's distinct
    # values (ops/strkernels.build_page_signature). None = pre-signature
    # file (never prunes); b"" = page provably holds no 3-byte substring.
    ngram: bytes | None = None

    def to_list(self):
        return [self.offset, self.size, self.n_rows, self.n_values,
                self.value_type, self.encoding, self.min_ts, self.max_ts,
                self.stat_min, self.stat_max, self.stat_sum,
                self.stats_version, self.ngram]

    @classmethod
    def from_list(cls, l):
        # length-tolerant: files sealed before stats_version existed carry
        # 11-element page lists and decode with the legacy default of 0
        return cls(*l)


@dataclass
class ColumnMeta:
    column_id: int
    name: str
    pages: list[PageMeta] = field(default_factory=list)

    def to_list(self):
        return [self.column_id, self.name, [p.to_list() for p in self.pages]]

    @classmethod
    def from_list(cls, l):
        return cls(l[0], l[1], [PageMeta.from_list(p) for p in l[2]])


@dataclass
class ChunkMeta:
    """All pages of one series (reference chunk.rs)."""

    series_id: int
    n_rows: int
    min_ts: int
    max_ts: int
    time_pages: list[PageMeta] = field(default_factory=list)
    columns: list[ColumnMeta] = field(default_factory=list)

    def column(self, name: str) -> ColumnMeta | None:
        for c in self.columns:
            if c.name == name:
                return c
        return None

    def to_list(self):
        return [self.series_id, self.n_rows, self.min_ts, self.max_ts,
                [p.to_list() for p in self.time_pages],
                [c.to_list() for c in self.columns]]

    @classmethod
    def from_list(cls, l):
        return cls(l[0], l[1], l[2], l[3],
                   [PageMeta.from_list(p) for p in l[4]],
                   [ColumnMeta.from_list(c) for c in l[5]])


@dataclass
class ChunkGroupMeta:
    """All chunks of one table (reference chunk_group.rs)."""

    table: str
    chunks: dict[int, ChunkMeta] = field(default_factory=dict)

    def to_list(self):
        return [self.table, [c.to_list() for c in self.chunks.values()]]

    @classmethod
    def from_list(cls, l):
        cm = {c[0]: ChunkMeta.from_list(c) for c in l[1]}
        return cls(l[0], cm)


# ---------------------------------------------------------------------------
# stats
# ---------------------------------------------------------------------------
def _compute_stats(values: np.ndarray, vt: ValueType):
    if len(values) == 0:
        return None, None, None
    if vt == ValueType.FLOAT:
        # NaNs are excluded (they satisfy no comparison, and would poison
        # the interval) but ±inf MUST be included: predicate page-pruning
        # (scan._admit_pages) drops pages whose [min, max] cannot match,
        # and an inf row outside a finite-only interval does match
        nonnan = values[~np.isnan(values)]
        if len(nonnan) == 0:
            return None, None, None
        return float(nonnan.min()), float(nonnan.max()), float(nonnan.sum())
    if vt in (ValueType.INTEGER, ValueType.UNSIGNED):
        return int(values.min()), int(values.max()), int(values.sum())
    if vt == ValueType.BOOLEAN:
        return bool(values.min()), bool(values.max()), int(values.sum())
    return None, None, None  # strings: no numeric stats


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------
class TsmWriter:
    """Streams series chunks into a TSM file; finish() seals meta+footer.

    Mirrors reference TsmWriter::write_record_batch/finish
    (tsm/writer.rs:249,503).
    """

    def __init__(self, path: str, max_page_rows: int = 256 * 1024):
        self.path = path
        self.max_page_rows = max_page_rows
        self._f = open(path + ".tmp", "wb")
        self._f.write(struct.pack("<IB", MAGIC, VERSION))
        self._off = self._f.tell()
        self._groups: dict[str, ChunkGroupMeta] = {}
        self._bloom = BloomFilter()
        self._min_ts = 2**63 - 1
        self._max_ts = -(2**63)
        self._finished = False

    # -- core append -----------------------------------------------------
    def _write_page(self, payload: bytes) -> tuple[int, int]:
        crc = zlib.crc32(payload)
        data = struct.pack("<II", len(payload), crc) + payload
        off = self._off
        self._f.write(data)
        self._off += len(data)
        return off, len(data)

    def write_series(self, table: str, series_id: int,
                     timestamps: np.ndarray,
                     columns: dict[str, tuple[int, ValueType, Encoding, np.ndarray, np.ndarray | None]]):
        """Write one series chunk.

        columns: name → (column_id, value_type, encoding, values, null_mask)
        `values` has one entry per row; rows where null_mask is True are
        nulls (their value slot is ignored; dense packing happens here).
        Timestamps must be sorted ascending and deduplicated.
        """
        if self._finished:
            raise TsmError("writer already finished")
        n = len(timestamps)
        if n == 0:
            return
        ts = np.ascontiguousarray(timestamps, dtype=np.int64)
        if n > 1 and bool(np.any(np.diff(ts) < 0)):
            raise TsmError("timestamps not sorted", series=series_id)
        group = self._groups.setdefault(table, ChunkGroupMeta(table))
        if series_id in group.chunks:
            raise TsmError("duplicate series chunk", series=series_id)
        chunk = ChunkMeta(series_id, n, int(ts[0]), int(ts[-1]))
        self._min_ts = min(self._min_ts, int(ts[0]))
        self._max_ts = max(self._max_ts, int(ts[-1]))
        self._bloom.insert_u64(series_id)

        # time pages
        for s in range(0, n, self.max_page_rows):
            seg = ts[s:s + self.max_page_rows]
            blk = codecs.encode_timestamps(seg)
            off, size = self._write_page(blk)
            chunk.time_pages.append(PageMeta(
                off, size, len(seg), len(seg), int(ValueType.INTEGER),
                int(Encoding.DELTA_TS), int(seg[0]), int(seg[-1]),
                int(seg[0]), int(seg[-1]), None, stats_version=1))

        # field pages
        for name, (cid, vt, enc, values, null_mask) in columns.items():
            cm = ColumnMeta(cid, name)
            for s in range(0, n, self.max_page_rows):
                e = min(s + self.max_page_rows, n)
                seg_ts = ts[s:e]
                vals = values[s:e]
                if null_mask is not None:
                    nm = np.ascontiguousarray(null_mask[s:e], dtype=bool)
                    dense = vals[~nm] if isinstance(vals, (np.ndarray, DictArray)) \
                        else [v for v, m in zip(vals, nm) if not m]
                    bitset = np.packbits(nm).tobytes()
                    has_nulls = bool(nm.any())
                else:
                    nm = None
                    dense = vals
                    bitset = b""
                    has_nulls = False
                ngram = None
                if vt in (ValueType.STRING, ValueType.GEOMETRY):
                    smin = smax = ssum = None
                    if vt == ValueType.STRING:
                        ngram = _string_signature(dense)
                else:
                    dense = np.ascontiguousarray(dense)
                    smin, smax, ssum = _compute_stats(dense, vt)
                blk = codecs.encode(dense, vt, enc)
                payload = (struct.pack("<BI", 1 if has_nulls else 0, len(bitset))
                           + (bitset if has_nulls else b"") + blk)
                off, size = self._write_page(payload)
                nvals = len(dense)
                cm.pages.append(PageMeta(
                    off, size, e - s, nvals, int(vt), blk[0],
                    int(seg_ts[0]), int(seg_ts[-1]), smin, smax, ssum,
                    stats_version=1, ngram=ngram))
            chunk.columns.append(cm)
        group.chunks[series_id] = chunk

    # -- finish ----------------------------------------------------------
    def finish(self) -> "TsmFooter":
        if self._finished:
            raise TsmError("writer already finished")
        meta_raw = msgpack.packb([g.to_list() for g in self._groups.values()])
        meta = _ZC.compress(meta_raw)
        meta_off = self._off
        self._f.write(meta)
        bloom = self._bloom.to_bytes()
        bloom_off = meta_off + len(meta)
        self._f.write(bloom)
        series_count = sum(len(g.chunks) for g in self._groups.values())
        body = struct.pack("<QQQQqqI", meta_off, len(meta), bloom_off,
                           len(bloom), self._min_ts, self._max_ts, series_count)
        crc = zlib.crc32(body)
        footer = body + struct.pack("<II B", crc, MAGIC, VERSION)
        footer += b"\x00" * (FOOTER_SIZE - len(footer))
        self._f.write(footer)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        os.replace(self.path + ".tmp", self.path)
        self._finished = True
        if faults.ENABLED:
            # silent-corruption model: flip bytes INSIDE the already-durable
            # page region (header/meta/footer stay intact, so the file opens
            # fine and the flip is only caught by a page-crc check)
            hit = faults.fire("tsm.write", path=self.path)
            if hit and hit[0] == "corrupt":
                faults.corrupt_file(self.path, int(hit[1] or 1),
                                    lo=5, hi=meta_off)
        return TsmFooter(meta_off, len(meta), bloom_off, len(bloom),
                         self._min_ts, self._max_ts, series_count)

    def abort(self):
        if not self._finished:
            self._f.close()
            try:
                os.unlink(self.path + ".tmp")
            except FileNotFoundError:
                pass


@dataclass
class TsmFooter:
    meta_off: int
    meta_len: int
    bloom_off: int
    bloom_len: int
    min_ts: int
    max_ts: int
    series_count: int


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------
def parse_tail(tail, path: str, tail_off: int = 0):
    """Parse a TSM file's trailing metadata section (zstd-msgpack chunk
    meta + bloom + fixed footer) → (groups, bloom, footer).

    `tail` holds the file bytes from absolute offset `tail_off` to EOF —
    the whole mmap for the hot reader (tail_off=0), or just the sidecar
    tail for the cold tier (tail_off = footer.meta_off). Footer offsets
    are absolute file offsets, rebased here."""
    if len(tail) < FOOTER_SIZE:
        raise TsmError("file too small", path=path)
    footer_raw = tail[-FOOTER_SIZE:]
    body = footer_raw[:52]
    crc, fmagic, fver = struct.unpack_from("<IIB", footer_raw, 52)
    if fmagic != MAGIC:
        raise TsmError("bad footer magic", path=path)
    if zlib.crc32(body) != crc:
        raise ChecksumMismatch("footer crc", path=path)
    (meta_off, meta_len, bloom_off, bloom_len,
     min_ts, max_ts, series_count) = struct.unpack("<QQQQqqI", body)
    footer = TsmFooter(meta_off, meta_len, bloom_off, bloom_len,
                       min_ts, max_ts, series_count)
    lo = meta_off - tail_off
    if lo < 0 or bloom_off - tail_off < 0:
        raise TsmError("tail section does not cover meta", path=path)
    meta_raw = _ZD.decompress(tail[lo:lo + meta_len])
    groups: dict[str, ChunkGroupMeta] = {}
    for g in msgpack.unpackb(meta_raw, strict_map_key=False):
        cg = ChunkGroupMeta.from_list(g)
        groups[cg.table] = cg
    blo = bloom_off - tail_off
    bloom = BloomFilter.from_bytes(tail[blo:blo + bloom_len])
    return groups, bloom, footer


class TsmReader:
    """Random-access TSM reader (reference tsm/reader.rs:825).

    Loads footer + meta eagerly (small), pages lazily via one mmap'd file.
    """

    # storage/tiering.py's ColdTsmReader overrides this: scan routing uses
    # it to keep cold pages off the mmap-dependent native batch lane
    is_cold = False

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        import mmap as _mmap

        self._buf = _mmap.mmap(self._f.fileno(), 0, access=_mmap.ACCESS_READ)
        if len(self._buf) < FOOTER_SIZE + 5:
            raise TsmError("file too small", path=path)
        magic, version = struct.unpack_from("<IB", self._buf, 0)
        if magic != MAGIC:
            raise TsmError("bad magic", path=path)
        self.groups, self.bloom, self.footer = parse_tail(self._buf, path)
        self.min_ts = self.footer.min_ts
        self.max_ts = self.footer.max_ts
        self.series_count = self.footer.series_count

    def close(self):
        self._buf_arr = None
        if not isinstance(self._buf, bytes):
            try:
                self._buf.close()
            except BufferError:
                # a lock-free concurrent scan still holds a buffer_array()
                # view; the mmap stays alive until that array drops and GC
                # reclaims it — never crash the closer (compaction's
                # VersionEdit apply closes readers of deleted files)
                pass
        self._f.close()
        self._buf = b""

    def buffer_array(self) -> np.ndarray:
        """Whole-file u8 view over the mmap (zero-copy) — the base pointer
        the native batch page decoder reads from."""
        arr = getattr(self, "_buf_arr", None)
        if arr is None:
            arr = self._buf_arr = np.frombuffer(self._buf, dtype=np.uint8)
        return arr

    # -- meta queries ----------------------------------------------------
    def tables(self) -> list[str]:
        return list(self.groups)

    def chunk(self, table: str, series_id: int) -> ChunkMeta | None:
        g = self.groups.get(table)
        return g.chunks.get(series_id) if g else None

    def series_ids(self, table: str) -> np.ndarray:
        g = self.groups.get(table)
        if not g:
            return np.empty(0, dtype=np.uint64)
        return np.fromiter(g.chunks.keys(), dtype=np.uint64, count=len(g.chunks))

    def maybe_contains_series(self, series_id: int) -> bool:
        return self.bloom.maybe_contains_u64(series_id)

    # -- page reads ------------------------------------------------------
    def _read_page(self, pm: PageMeta) -> bytes:
        raw = self._buf[pm.offset:pm.offset + pm.size]
        plen, crc = struct.unpack_from("<II", raw, 0)
        payload = raw[8:8 + plen]
        if zlib.crc32(payload) != crc:
            raise ChecksumMismatch("page crc", path=self.path, offset=pm.offset)
        return payload

    def read_time_page(self, pm: PageMeta) -> np.ndarray:
        return codecs.decode_timestamps(self._read_page(pm))

    def read_field_page(self, pm: PageMeta) -> tuple[np.ndarray, np.ndarray | None]:
        """→ (dense_values, null_mask|None). null_mask[i] True → row i null."""
        payload = self._read_page(pm)
        has_nulls, blen = struct.unpack_from("<BI", payload, 0)
        off = 5
        nm = None
        if has_nulls:
            bits = np.frombuffer(payload[off:off + blen], dtype=np.uint8)
            nm = np.unpackbits(bits, count=pm.n_rows).astype(bool)
            off += blen
        vals = codecs.decode(payload[off:], ValueType(pm.value_type))
        return vals, nm

    def read_field_page_split(self, pm: PageMeta) -> tuple[bytes, np.ndarray | None]:
        """→ (encoded_block, null_mask|None) WITHOUT decoding values —
        the device-decode lane's entry point: the null bitset expands
        host-side (cheap), the codec block goes to
        codecs.split_for_device so its value transforms run on device."""
        payload = self._read_page(pm)
        has_nulls, blen = struct.unpack_from("<BI", payload, 0)
        off = 5
        nm = None
        if has_nulls:
            bits = np.frombuffer(payload[off:off + blen], dtype=np.uint8)
            nm = np.unpackbits(bits, count=pm.n_rows).astype(bool)
            off += blen
        return payload[off:], nm

    def read_series_timestamps(self, table: str, series_id: int) -> np.ndarray:
        cm = self.chunk(table, series_id)
        if cm is None:
            return np.empty(0, dtype=np.int64)
        return np.concatenate([self.read_time_page(p) for p in cm.time_pages]) \
            if len(cm.time_pages) != 1 else self.read_time_page(cm.time_pages[0])

    def read_series_column(self, table: str, series_id: int, name: str,
                           fill=None) -> tuple[np.ndarray, np.ndarray]:
        """→ (values_full, valid_mask) aligned to the series' timestamps.

        Nulls are expanded in place (fill value, default type-zero), with
        valid_mask False at null rows — the padded/masked shape the device
        kernels consume.
        """
        cm = self.chunk(table, series_id)
        if cm is None:
            return np.empty(0), np.empty(0, dtype=bool)
        col = cm.column(name)
        if col is None:
            # column absent in this chunk (schema evolution): all-null
            n = cm.n_rows
            return np.zeros(n), np.zeros(n, dtype=bool)
        outs, masks = [], []
        for pm in col.pages:
            dense, nm = self.read_field_page(pm)
            vt = ValueType(pm.value_type)
            if nm is None:
                outs.append(dense)
                masks.append(np.ones(pm.n_rows, dtype=bool))
            elif isinstance(dense, DictArray):
                # null expansion on codes: invalid rows carry code 0
                full_codes = np.zeros(pm.n_rows, dtype=np.int32)
                full_codes[~nm] = dense.codes
                outs.append(DictArray(full_codes, dense.values))
                masks.append(~nm)
            else:
                full = np.zeros(pm.n_rows, dtype=dense.dtype if isinstance(dense, np.ndarray) else object)
                if fill is not None:
                    full[:] = fill
                full[~nm] = dense
                outs.append(full)
                masks.append(~nm)
        if len(outs) == 1:
            return outs[0], masks[0]
        if any(isinstance(o, DictArray) for o in outs):
            return DictArray.concat(outs), np.concatenate(masks)
        return np.concatenate(outs), np.concatenate(masks)
