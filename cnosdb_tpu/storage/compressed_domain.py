"""Compressed-domain execution: answer filters and aggregates from the
encoded page representation, decode only surviving rows.

The lane slots in AHEAD of the three decode lanes (device → native →
py_jobs) in storage/scan: after `_plan_series` proves a series' chunks are
row-aligned and merge-free ("n" entries), every admitted page is classified
per (page, query) against a `CompressedSpec` the executor derived from the
aggregate plan:

  skip    a conjunct is provably false for every row (or the conjunct
          column is absent ⇒ all-NULL ⇒ fails) — the page leaves the plan:
          never fetched, never decoded, zero rows.
  answer  every projected aggregate is computable without materializing
          the page's value rows:
            meta    pure PageMeta algebra — count from n_rows/n_values,
                    int sum/min/max from the exact page stats, the page's
                    time-bucket from min_ts/max_ts (page inside one
                    bucket).
            closed  a deferred job reads the page BYTES (block cache /
                    ranged GET) and applies a per-codec closed form on the
                    still-encoded stream: DELTA last = first + Σdeltas
                    (int64 wrap is associative ⇒ bit-identical to the
                    cumsum decode), constant-stride DELTA_TS answers
                    bucket boundaries arithmetically (no cumsum
                    materialization), GORILLA first/last via byte-plane
                    XOR algebra, BITPACK via the packed bits. Handlers
                    register per split-plan "kind" (codecs._CODEC_TABLE's
                    plans) — no fourth dispatch ladder.
          The page leaves the plan; its contribution rides the batch as a
          pre-aggregated partial sql/executor merges like matview
          partials.
  mask    a string/bool conjunct is mixed on the page but decidable in
          code space: the predicate is mapped onto the page DICTIONARY
          (once per page, PR 10 per-unique style) or the packed BOOLEAN
          bits (unpackbits fused into the mask AND — never widened to an
          int64 column), producing a row mask. The page still decodes,
          but only rows surviving every mask are gathered into the batch
          (late materialization) — assembly ANDs the mask into the trim
          gather.
  mat     anything unprovable materializes normally. Fallback is
          PER-PAGE, never per-query, and total: every bail books a
          (lane, reason) outcome (cnosdb_compressed_domain_total on
          /metrics; compressed.* stage counters carry per-query byte
          books). Enforced by the compressed-domain-accounting lint rule.

Answerability rules (why the table looks the way it does):
  count(*)            n_rows; count(col) = n_values — exact from meta.
  int/uint sum        page stat_sum is int(values.sum()) — same wrapping
                      int64/uint64 arithmetic as the kernel's np.add.at,
                      and integer addition is associative ⇒ bit-identical.
  int/uint min/max    exact page stats.
  float sum           DECLINED (float_assoc): fp addition is not
                      associative; a closed form cannot reproduce the
                      decode lane's reduction order bit-for-bit.
  float min/max       DECLINED (float_nan): the kernel propagates NaN,
                      page stats exclude it, and NaN presence is not
                      provable from metadata.
  bool/string aggs    DECLINED (bool_agg/string_agg): kernel dtype
                      semantics aren't reproducible from stats.
  first/last          closed forms per codec; need the companion
                      timestamp, answered from the time page (constant
                      stride arithmetically, else from the delta stream).
  predicates          interval tri-state on exact int stats (TRUE needs
                      no-NULLs: NULL fails every conjunct, matching the
                      kernel's 3VL mask); floats only ever prove
                      "!=" TRUE / everything-else FALSE (hidden NaN);
                      strings/bools go to the mask path.

`CNOSDB_COMPRESSED_DOMAIN=0` disables the lane (parity/oracle switch):
every query then takes the decode lanes, which this lane must match
bit-for-bit (tests/test_compressed_domain.py property suite).
"""
from __future__ import annotations

import os

import numpy as np

from ..models.codec import Encoding
from ..models.schema import ValueType
from ..utils import lockwatch, stages

__all__ = [
    "enabled", "count_outcome", "outcomes_snapshot", "build_spec",
    "CompressedSpec", "ScanLane", "register_closed",
]


def enabled() -> bool:
    return os.environ.get("CNOSDB_COMPRESSED_DOMAIN", "1").lower() \
        not in ("0", "off", "false")


# ---------------------------------------------------------------------------
# accounting — every lane outcome is booked (lint-enforced totality)
# ---------------------------------------------------------------------------
_OUTCOME_LOCK = lockwatch.Lock("compressed_domain.outcomes")
_OUTCOMES: dict[tuple[str, str], int] = {}


def count_outcome(lane: str, reason: str, n: int = 1) -> None:
    """Book one (lane, reason) outcome: lane ∈ {spec, skip, meta, closed,
    closed_decode, mask, mat}. Surfaced as
    cnosdb_compressed_domain_total{lane,reason} on /metrics."""
    with _OUTCOME_LOCK:
        _OUTCOMES[(lane, reason)] = _OUTCOMES.get((lane, reason), 0) + n


def outcomes_snapshot() -> dict[tuple[str, str], int]:
    with _OUTCOME_LOCK:
        return dict(sorted(_OUTCOMES.items()))


def _declined(reason: str):
    """Query-level decline: the whole query takes the decode lanes. The
    booked reason keeps 'why is the lane idle' answerable from /metrics."""
    count_outcome("spec", reason)
    return None


# ---------------------------------------------------------------------------
# query-level spec
# ---------------------------------------------------------------------------
_AGG_FUNCS = frozenset({"count", "sum", "min", "max", "first", "last"})
_NUM_OPS = frozenset({"=", "!=", "<", "<=", ">", ">=", "between", "in"})
_STR_OPS = frozenset({"str_eq", "str_ne", "str_in"})
_BOOL_OPS = frozenset({"bool_eq", "bool_ne"})
_INT_VTS = (ValueType.INTEGER, ValueType.UNSIGNED)


class CompressedSpec:
    """What one aggregate query asks of the lane: physical aggs, bucket
    geometry, and the FULL conjunction of its filter (build_spec declines
    unless the filter is exhaustively decomposable — an answered page must
    be provably all-true, which a partially-understood filter can't be)."""

    __slots__ = ("aggs", "bucket", "conjuncts", "col_types", "key")

    def __init__(self, aggs, bucket, conjuncts, col_types):
        self.aggs = aggs                # ((func, column|None, alias), ...)
        self.bucket = bucket            # (origin_ns, interval_ns) | None
        self.conjuncts = conjuncts      # {col: [(op, value), ...]}
        self.col_types = col_types      # {col: ValueType}
        self.key = repr((aggs, bucket,
                         sorted((c, [(op, repr(v)) for op, v in cons])
                                for c, cons in conjuncts.items())))


def _extract_conjuncts(filt, schema):
    """Decompose an AND-only filter tree into per-column conjuncts, or a
    decline reason string. Every reachable leaf must convert — unlike
    scan._page_constraints (where ignoring a conjunct is sound for
    pruning), answering a page requires understanding the WHOLE filter."""
    from ..sql.expr import Between, BinOp, Column, InList, Literal

    out: dict[str, list] = {}
    fields = set(schema.field_names())

    def numeric(v):
        return isinstance(v, (int, float, np.integer, np.floating)) \
            and not isinstance(v, bool)

    def colname(e):
        if not isinstance(e, Column):
            return None
        if e.name == "time":
            return "time"
        return e.name if e.name in fields else None

    def walk(e):
        if isinstance(e, BinOp) and e.op == "and":
            return walk(e.left) or walk(e.right)
        if isinstance(e, BinOp) and e.op in ("=", "!=", "<", "<=", ">", ">="):
            col = lit = op = None
            if isinstance(e.left, Column) and isinstance(e.right, Literal):
                col, lit, op = colname(e.left), e.right.value, e.op
            elif isinstance(e.right, Column) and isinstance(e.left, Literal):
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                        "=": "=", "!=": "!="}
                col, lit, op = colname(e.right), e.left.value, flip[e.op]
            if col == "time":
                return "filter_time"
            if col is None:
                return "filter_col"
            if isinstance(lit, bool):
                if op not in ("=", "!="):
                    return "filter_shape"
                out.setdefault(col, []).append(
                    ("bool_eq" if op == "=" else "bool_ne", bool(lit)))
                return None
            if numeric(lit):
                out.setdefault(col, []).append((op, lit))
                return None
            if isinstance(lit, str):
                if op not in ("=", "!="):
                    return "filter_shape"
                out.setdefault(col, []).append(
                    ("str_eq" if op == "=" else "str_ne", lit))
                return None
            return "filter_shape"
        if isinstance(e, Between) and not e.negated \
                and isinstance(e.low, Literal) and isinstance(e.high, Literal):
            col = colname(e.expr)
            if col in (None, "time"):
                return "filter_time" if col == "time" else "filter_col"
            if not (numeric(e.low.value) and numeric(e.high.value)):
                return "filter_shape"
            out.setdefault(col, []).append(
                ("between", (e.low.value, e.high.value)))
            return None
        if isinstance(e, InList) and not e.negated and e.values:
            col = colname(e.expr)
            if col in (None, "time"):
                return "filter_time" if col == "time" else "filter_col"
            if all(numeric(v) for v in e.values):
                out.setdefault(col, []).append(("in", list(e.values)))
                return None
            if all(isinstance(v, str) for v in e.values):
                out.setdefault(col, []).append(("str_in", tuple(e.values)))
                return None
            return "filter_shape"
        return "filter_shape"

    why = walk(filt)
    return (None, why) if why else (out, None)


def build_spec(plan, phys_aggs):
    """AggregatePlan + decomposed physical aggs → CompressedSpec, or None
    (reason booked) when the query can't engage the lane at all. The
    gates here are QUERY-level; pages still fall back individually."""
    if not enabled():
        return _declined("disabled")
    if plan.group_fields:
        # field group keys need per-row values — nothing to answer
        return _declined("group_fields")
    funcs = {a.func for a in phys_aggs}
    if not funcs <= _AGG_FUNCS:
        return _declined("agg_func")
    if any(a.column == "time" for a in phys_aggs):
        # min(time)/max(time) aggregate the time axis, not a field page;
        # the decode lane owns that path
        return _declined("time_agg")
    schema = plan.schema
    conjuncts: dict[str, list] = {}
    if plan.filter is not None:
        conjuncts, why = _extract_conjuncts(plan.filter, schema)
        if conjuncts is None:
            return _declined(why)
    col_types: dict[str, ValueType] = {}
    for name in ({a.column for a in phys_aggs if a.column}
                 | set(conjuncts)):
        try:
            col_types[name] = schema.column(name).column_type.value_type
        except Exception:
            return _declined("schema")
    aggs = tuple((a.func, a.column, a.alias) for a in phys_aggs)
    return CompressedSpec(aggs, plan.bucket, conjuncts, col_types)


# ---------------------------------------------------------------------------
# per-codec closed forms, registered against codecs.split_for_device plans
# ---------------------------------------------------------------------------
def _widen(width, raw):
    from . import codecs

    return codecs._widen(width, raw)


def _delta_stream(plan):
    from . import codecs

    return codecs.unzigzag(_widen(plan["width"], plan["raw"]))[:plan["n"] - 1]


def _delta_first(plan):
    return np.int64(plan["first"])


def _delta_last(plan):
    # int64 addition wraps associatively: first + Σdeltas is bit-identical
    # to the decode lane's cumsum final element
    if plan["n"] == 1:
        return np.int64(plan["first"])
    return np.int64(plan["first"]) + _delta_stream(plan).sum()


def _delta_const_first(plan):
    return np.int64(plan["first"])


def _delta_const_last(plan):
    return np.int64(plan["first"] + plan["stride"] * (plan["n"] - 1))


def _gorilla_planes(plan):
    return np.frombuffer(plan["raw"], dtype=np.uint8).reshape(8, plan["n"])


def _gorilla_first(plan):
    b = np.ascontiguousarray(_gorilla_planes(plan)[:, 0])
    return np.frombuffer(b.tobytes(), dtype="<f8")[0]


def _gorilla_last(plan):
    # value k is the XOR-prefix of each byte plane; the last value is the
    # whole-plane XOR reduction — no scan materialized
    b = np.bitwise_xor.reduce(_gorilla_planes(plan), axis=1)
    return np.frombuffer(np.ascontiguousarray(b).tobytes(), dtype="<f8")[0]


def _bitpack_bits(plan):
    return np.unpackbits(np.frombuffer(plan["raw"], dtype=np.uint8),
                         count=plan["n"])


def _bitpack_first(plan):
    return np.bool_(_bitpack_bits(plan)[0])


def _bitpack_last(plan):
    return np.bool_(_bitpack_bits(plan)[-1])


_CLOSED: dict[str, tuple] = {}


def register_closed(kind: str, first_fn, last_fn) -> None:
    """Register first/last closed forms for one split-plan kind — new
    codecs extend the lane here, not with another if/elif chain."""
    _CLOSED[kind] = (first_fn, last_fn)


register_closed("delta", _delta_first, _delta_last)
register_closed("delta_const", _delta_const_first, _delta_const_last)
register_closed("gorilla", _gorilla_first, _gorilla_last)
register_closed("bitpack", _bitpack_first, _bitpack_last)


def _time_value_at(tplan, k: int) -> int:
    """Timestamp at row k from a still-encoded time plan (prefix-sum
    algebra — Σ of a delta slice, never a cumsum array)."""
    if tplan["kind"] == "delta_const":
        return int(tplan["first"] + tplan["stride"] * k)
    if k == 0:
        return int(tplan["first"])
    return int(np.int64(tplan["first"]) + _delta_stream(tplan)[:k].sum())


# ---------------------------------------------------------------------------
# page-level predicate tri-state
# ---------------------------------------------------------------------------
_TRUE, _FALSE, _MIXED = 1, 0, -1


def _interval_verdict(op, val, lo, hi, is_float: bool) -> int:
    """Tri-state over the page's exact non-null interval [lo, hi]. For
    floats a hidden NaN row fails every comparison except '!=', so TRUE
    is only provable for '!=' and FALSE never is for '!='."""
    if op == ">":
        if not is_float and lo > val:
            return _TRUE
        return _FALSE if hi <= val else _MIXED
    if op == ">=":
        if not is_float and lo >= val:
            return _TRUE
        return _FALSE if hi < val else _MIXED
    if op == "<":
        if not is_float and hi < val:
            return _TRUE
        return _FALSE if lo >= val else _MIXED
    if op == "<=":
        if not is_float and hi <= val:
            return _TRUE
        return _FALSE if lo > val else _MIXED
    if op == "=":
        if val < lo or val > hi:
            return _FALSE
        if not is_float and lo == hi == val:
            return _TRUE
        return _MIXED
    if op == "!=":
        if val < lo or val > hi:
            return _TRUE
        if not is_float and lo == hi == val:
            return _FALSE
        return _MIXED
    if op == "between":
        blo, bhi = val
        if bhi < lo or blo > hi:
            return _FALSE
        if not is_float and lo >= blo and hi <= bhi:
            return _TRUE
        return _MIXED
    if op == "in":
        if all(v < lo or v > hi for v in val):
            return _FALSE
        if not is_float and lo == hi and any(v == lo for v in val):
            return _TRUE
        return _MIXED
    return _MIXED


def _fold_partial(parts: dict, func: str, alias: str, value,
                  ts: int | None = None) -> None:
    """Merge one page's contribution — same semantics as the executor's
    _merge_partial, so lane partials and kernel partials interleave
    bit-identically in any order."""
    cur = parts.get(alias)
    if func == "count":
        parts[alias] = (cur or 0) + int(value)
    elif func == "sum":
        parts[alias] = value if cur is None else cur + value
    elif func == "min":
        parts[alias] = value if cur is None else min(cur, value)
    elif func == "max":
        parts[alias] = value if cur is None else max(cur, value)
    else:   # first / last
        cur_ts = parts.get(alias + "__ts")
        better = (cur is None or cur_ts is None
                  or (func == "first" and ts < cur_ts)
                  or (func == "last" and ts > cur_ts))
        if better:
            parts[alias] = value
            parts[alias + "__ts"] = ts


_NP_STAT = {ValueType.INTEGER: np.int64, ValueType.UNSIGNED: np.uint64}


def _stat_value(vt: ValueType, v):
    # numpy-typed so executor-side merges (cur + v, min/max) run the same
    # wrapping int64/uint64 arithmetic as the kernel partials
    return _NP_STAT[vt](v)


_DELTA_ENCODINGS = (int(Encoding.DELTA), int(Encoding.DELTA_TS))


class ScanLane:
    """Per-(vnode scan, query) lane state: classify pages out of the
    native plan, collect meta partials, run deferred closed-form jobs
    after the cold prefetch, and build survivor row masks."""

    def __init__(self, spec: CompressedSpec, trs, index):
        self.spec = spec
        self.trs = trs
        self.index = index
        self.partials: dict[tuple, dict] = {}   # (sid, bts|None) → parts
        self.series_keys: dict[int, object] = {}
        self.jobs: list[tuple] = []             # (sid, r, tp, [(pm, aggs)], bts, straddle)
        self.mask_pages: dict[tuple, list] = {}  # (id(cm), i) → builders
        self._mask_keep: dict[int, object] = {}  # keep cm refs alive for id()
        self.row_mask: np.ndarray | None = None
        self.pages_answered = 0
        self.pages_skipped = 0
        self.pages_masked = 0
        self.bytes_avoided = 0
        self.bytes_materialized = 0   # job page bytes the lane DID read

    # -- plan filtering ---------------------------------------------------
    @property
    def engaged(self) -> bool:
        return bool(self.pages_answered or self.pages_skipped
                    or self.pages_masked)

    @property
    def has_masks(self) -> bool:
        return bool(self.mask_pages)

    def filter_plan(self, plan: list) -> list:
        out = []
        for entry in plan:
            if entry[0] != "n":
                out.append(entry)
                continue
            _tag, sid, admitted, n_rows, trim, pruned = entry
            new_chunks = []
            removed = 0
            for (r, cm, cols, idx) in admitted:
                keep_idx = []
                for i in idx:
                    removed += self._classify(sid, r, cm, cols, i, keep_idx)
                if keep_idx:
                    new_chunks.append((r, cm, cols, keep_idx))
            n2 = n_rows - removed
            if n2 > 0:
                out.append(("n", sid, new_chunks, n2, trim, pruned))
        return out

    def _page_bytes(self, cols, tp, i) -> int:
        total = tp.size
        for col in cols.values():
            total += col.pages[i].size
        return total

    def _classify(self, sid, r, cm, cols, i, keep_idx) -> int:
        """Classify page i; append to keep_idx when it must materialize.
        → rows removed from the series plan (0 when kept)."""
        spec = self.spec
        tp = cm.time_pages[i]

        def _mat(reason):
            count_outcome("mat", reason)
            keep_idx.append(i)
            return 0

        # rows outside the query's time ranges can't be answered away:
        # the page must materialize so assembly's trim drops them
        if not self.trs.is_all and not any(
                tr.min_ts <= tp.min_ts and tp.max_ts <= tr.max_ts
                for tr in self.trs.ranges):
            return _mat("trim")

        # ---- predicate tri-state over the full conjunction
        verdict = _TRUE
        mask_builders = []
        for colname, cons in spec.conjuncts.items():
            colmeta = cols.get(colname)
            if colmeta is None:
                # column absent from the chunk ⇒ all rows NULL ⇒ every
                # conjunct on it fails ⇒ no row of the page survives
                count_outcome("skip", "null_column")
                self.pages_skipped += 1
                self.bytes_avoided += self._page_bytes(cols, tp, i)
                return tp.n_rows
            pm = colmeta.pages[i]
            evt = spec.col_types[colname]
            if pm.value_type != int(evt):
                return _mat("schema_change")
            v = self._conjunct_verdict(r, pm, evt, cons, mask_builders)
            if v == _FALSE:
                count_outcome("skip", "pred_false")
                self.pages_skipped += 1
                self.bytes_avoided += self._page_bytes(cols, tp, i)
                return tp.n_rows
            if v == _MIXED:
                verdict = _MIXED

        if verdict == _MIXED:
            if mask_builders and len(mask_builders) == sum(
                    1 for colname, cons in spec.conjuncts.items()
                    if self._col_mixed(r, cols, i, colname, cons)):
                # every mixed conjunct is maskable in code space: the page
                # materializes but only surviving rows are gathered
                count_outcome("mask", "code_space")
                self.pages_masked += 1
                self._mask_keep[id(cm)] = cm
                self.mask_pages.setdefault((id(cm), i), []).extend(
                    mask_builders)
                keep_idx.append(i)
                return 0
            return _mat("pred_mixed")

        # ---- all conjuncts TRUE: try to answer every aggregate
        return self._answer(sid, r, cm, cols, i, tp, keep_idx, _mat)

    def _col_mixed(self, r, cols, i, colname, cons) -> bool:
        pm = cols[colname].pages[i]
        evt = self.spec.col_types[colname]
        return self._conjunct_verdict(r, pm, evt, cons, []) == _MIXED

    def _conjunct_verdict(self, r, pm, evt: ValueType, cons,
                          mask_builders: list) -> int:
        """Tri-state for ALL of one column's conjuncts on one page; mixed
        string/bool conjuncts append a deferred mask builder."""
        verdict = _TRUE
        no_nulls = pm.n_values == pm.n_rows
        is_float = evt == ValueType.FLOAT
        legacy_float = is_float and getattr(pm, "stats_version", 0) < 1
        maskable_ops = []
        for op, val in cons:
            if op in _NUM_OPS:
                if pm.stat_min is None or pm.stat_max is None:
                    if pm.n_values == 0:
                        # all-NULL page: every comparison fails
                        return _FALSE
                    verdict = _MIXED
                    continue
                if legacy_float:
                    # finite-only stats may omit ±inf rows: no verdict
                    verdict = _MIXED
                    continue
                if evt == ValueType.BOOLEAN:
                    verdict = _MIXED
                    continue
                v = _interval_verdict(op, val, pm.stat_min, pm.stat_max,
                                      is_float)
                if v == _FALSE:
                    return _FALSE
                if v == _TRUE and not no_nulls:
                    v = _MIXED   # NULL rows fail the conjunct
                if v == _MIXED:
                    verdict = _MIXED
            elif op in _BOOL_OPS:
                if evt != ValueType.BOOLEAN:
                    verdict = _MIXED   # planner type confusion: no verdict
                    continue
                if pm.n_values == 0:
                    return _FALSE
                maskable = pm.encoding == int(Encoding.BITPACK)
                if pm.stat_min is None:
                    verdict = _MIXED
                    if maskable:
                        maskable_ops.append((op, val))
                    continue
                want = val if op == "bool_eq" else (not val)
                if bool(pm.stat_min) == bool(pm.stat_max):
                    if bool(pm.stat_min) != want:
                        return _FALSE
                    if no_nulls:
                        continue   # TRUE for this conjunct
                verdict = _MIXED
                if maskable:
                    maskable_ops.append((op, val))
            elif op in _STR_OPS:
                if evt not in (ValueType.STRING, ValueType.GEOMETRY):
                    verdict = _MIXED
                    continue
                if pm.n_values == 0:
                    return _FALSE
                # decided in code space after the cold prefetch: the
                # dictionary lives in the page bytes
                verdict = _MIXED
                maskable_ops.append((op, val))
            else:
                verdict = _MIXED
        if verdict == _MIXED and maskable_ops:
            mask_builders.append((r, pm, evt, tuple(maskable_ops)))
        return verdict

    # -- aggregate answering ---------------------------------------------
    def _answer(self, sid, r, cm, cols, i, tp, keep_idx, _mat) -> int:
        spec = self.spec
        straddle = False
        bts = None
        if spec.bucket is not None:
            origin, interval = spec.bucket
            blo = (tp.min_ts - origin) // interval
            bhi = (tp.max_ts - origin) // interval
            straddle = blo != bhi
            bts = int(origin + blo * interval)

        meta_parts: list[tuple] = []    # (func, alias, value)
        job_aggs: list[tuple] = []      # (func, col, alias, pm, evt)
        count_aliases: list[str] = []   # straddle counts (per-bucket job)
        for func, col, alias in spec.aggs:
            colmeta = cols.get(col) if col is not None else None
            pm = colmeta.pages[i] if colmeta is not None else None
            evt = spec.col_types.get(col) if col is not None else None
            if pm is not None and pm.value_type != int(evt):
                return _mat("schema_change")
            if func == "count":
                n = tp.n_rows if col is None else \
                    (pm.n_values if pm is not None else 0)
                if not straddle:
                    meta_parts.append((func, alias, n))
                elif col is None or (pm is not None
                                     and pm.n_values == pm.n_rows):
                    # no NULLs ⇒ per-bucket count(col) == per-bucket rows
                    count_aliases.append(alias)
                elif pm is None:
                    pass   # absent column: counts 0 into every bucket
                else:
                    return _mat("bucket_straddle")
                continue
            if straddle:
                return _mat("bucket_straddle")
            if colmeta is None or pm.n_values == 0:
                continue   # no values: no contribution (kernel: invalid)
            if func in ("sum", "min", "max"):
                if evt == ValueType.FLOAT:
                    return _mat("float_assoc" if func == "sum"
                                else "float_nan")
                if evt not in _INT_VTS:
                    return _mat("bool_agg" if evt == ValueType.BOOLEAN
                                else "string_agg")
                stat = {"sum": pm.stat_sum, "min": pm.stat_min,
                        "max": pm.stat_max}[func]
                if stat is None:
                    return _mat("no_stats")
                meta_parts.append((func, alias, _stat_value(evt, stat)))
                continue
            # first / last: per-codec closed form over the page bytes
            if evt in _INT_VTS:
                if pm.encoding not in _DELTA_ENCODINGS:
                    return _mat("encoding")
            elif evt == ValueType.FLOAT:
                if pm.encoding != int(Encoding.GORILLA):
                    return _mat("encoding")
            elif evt == ValueType.BOOLEAN:
                if pm.encoding != int(Encoding.BITPACK):
                    return _mat("encoding")
            else:
                return _mat("string_agg")
            if tp.encoding not in _DELTA_ENCODINGS:
                return _mat("encoding")
            job_aggs.append((func, col, alias, pm, evt))
        if count_aliases and tp.encoding not in _DELTA_ENCODINGS:
            return _mat("encoding")

        # answered: remove the page from the plan, book its contribution
        self.pages_answered += 1
        self.series_keys.setdefault(sid, self.index.get_series_key(sid))
        key = (sid, bts)
        parts = self.partials.setdefault(key, {})
        for func, alias, value in meta_parts:
            _fold_partial(parts, func, alias, value)
        if not job_aggs and not count_aliases:
            count_outcome("meta", "stats")
        if job_aggs or count_aliases:
            self.jobs.append((sid, r, tp,
                              tuple(job_aggs), tuple(count_aliases), bts))
        avoided = self._page_bytes(cols, tp, i)
        for _f, _c, _a, pm, _t in job_aggs:
            avoided -= pm.size
        if job_aggs or count_aliases:
            avoided -= tp.size
        self.bytes_avoided += max(0, avoided)
        return tp.n_rows

    # -- deferred jobs ----------------------------------------------------
    def extend_cold_wants(self, cold_wants: dict) -> None:
        """Add the page bytes the closed-form jobs will read to the cold
        prefetch, so they ride the same coalesced ranged GETs."""
        for _sid, r, tp, job_aggs, count_aliases, _bts in self.jobs:
            if not getattr(r, "is_cold", False):
                continue
            lst = cold_wants.setdefault(id(r), (r, []))[1]
            lst.append(tp)
            for _f, _c, _a, pm, _t in job_aggs:
                lst.append(pm)

    def run_jobs(self) -> None:
        from . import codecs

        tplan_cache: dict[tuple, dict | None] = {}
        for sid, r, tp, job_aggs, count_aliases, bts in self.jobs:
            tkey = (id(r), tp.offset)
            if tkey not in tplan_cache:
                self.bytes_materialized += tp.size
                tplan, why = codecs.split_for_device(
                    r._read_page(tp), ValueType.INTEGER)
                if tplan is None:
                    count_outcome("closed_decode", "time_" + why)
                tplan_cache[tkey] = tplan
            tplan = tplan_cache[tkey]
            if count_aliases:
                self._job_bucket_counts(r, tp, tplan, sid, count_aliases)
            for func, _col, alias, pm, evt in job_aggs:
                self._job_first_last(r, tp, tplan, pm, evt, func, alias,
                                     (sid, bts))

    def _bucket_counts(self, tplan, tp) -> tuple[np.ndarray, int] | None:
        """Per-bucket row counts for a straddling time page, straight
        from the encoded stream. → (counts, first_bucket) or None."""
        origin, interval = self.spec.bucket
        blo = (tp.min_ts - origin) // interval
        bhi = (tp.max_ts - origin) // interval
        n = tplan["n"]
        if tplan["kind"] == "delta_const" and tplan["stride"] > 0:
            first, stride = tplan["first"], tplan["stride"]
            # row k lands in bucket (first + k*stride - origin) // interval;
            # bucket boundaries are solved arithmetically — no cumsum
            edges = origin + np.arange(blo + 1, bhi + 1,
                                       dtype=np.int64) * interval
            ks = -((first - edges) // stride)    # ceil((edge-first)/stride)
            ks = np.clip(ks, 0, n)
            bounds = np.concatenate(([0], ks, [n]))
            return np.diff(bounds), int(blo)
        if tplan["kind"] == "delta":
            # non-constant stride: one cumsum of the already-decompressed
            # delta stream (the page bytes were read anyway)
            count_outcome("closed_decode", "delta_cumsum")
            ts = np.empty(n, dtype=np.int64)
            ts[0] = tplan["first"]
            if n > 1:
                np.cumsum(_delta_stream(tplan), out=ts[1:])
                ts[1:] += np.int64(tplan["first"])
            buckets = (ts - origin) // interval
            counts = np.bincount((buckets - blo).astype(np.int64),
                                 minlength=int(bhi - blo + 1))
            return counts, int(blo)
        return None

    def _job_bucket_counts(self, r, tp, tplan, sid, aliases) -> None:
        origin, interval = self.spec.bucket
        if tplan is not None:
            got = self._bucket_counts(tplan, tp)
        else:
            got = None
        if got is None:
            count_outcome("closed_decode", "time_decode")
            ts = r.read_time_page(tp)
            blo = (tp.min_ts - origin) // interval
            buckets = (ts - origin) // interval
            counts = np.bincount((buckets - blo).astype(np.int64))
            got = counts, int(blo)
        else:
            count_outcome("closed", "bucket_arith")
        counts, blo = got
        self.series_keys.setdefault(sid, self.index.get_series_key(sid))
        for j, c in enumerate(counts.tolist()):
            if c == 0:
                continue
            bts = int(origin + (blo + j) * interval)
            parts = self.partials.setdefault((sid, bts), {})
            for alias in aliases:
                _fold_partial(parts, "count", alias, c)

    def _job_first_last(self, r, tp, tplan, pm, evt, func, alias,
                        key) -> None:
        from . import codecs

        self.bytes_materialized += pm.size
        block, nm = r.read_field_page_split(pm)
        plan, why = codecs.split_for_device(block, evt)
        handlers = _CLOSED.get(plan["kind"]) if plan is not None else None
        if handlers is None:
            # exact decode-compute fallback (first/last are order
            # lookups — no float reduction, so still bit-identical)
            count_outcome("closed_decode", why or "kind")
            dense, nm2 = r.read_field_page(pm)
            if len(dense) == 0:
                return
            value = dense[0] if func == "first" else dense[-1]
            nm = nm2
        else:
            count_outcome("closed", plan["kind"])
            value = handlers[0 if func == "first" else 1](plan)
            if evt == ValueType.UNSIGNED:
                # delta closed forms run in wrapping int64 (like the
                # decode lane), which then VIEWS the result as uint64
                value = np.uint64(int(value) & 0xFFFFFFFFFFFFFFFF)
        if nm is None:
            row = 0 if func == "first" else pm.n_rows - 1
        else:
            nn = np.flatnonzero(~nm)
            if len(nn) == 0:
                return
            row = int(nn[0] if func == "first" else nn[-1])
        if tplan is not None and tplan["kind"] in ("delta", "delta_const"):
            ts = _time_value_at(tplan, row)
        else:
            ts = int(r.read_time_page(tp)[row])
        parts = self.partials.setdefault(key, {})
        _fold_partial(parts, func, alias, value, ts)

    # -- survivor row masks ----------------------------------------------
    def apply_page_masks(self, cm, i, off: int, total: int) -> None:
        builders = self.mask_pages.get((id(cm), i))
        if not builders:
            return
        if self.row_mask is None:
            self.row_mask = np.ones(total, dtype=bool)
        for (r, pm, evt, ops) in builders:
            m = self._page_row_mask(r, pm, evt, ops)
            if m is not None:
                self.row_mask[off:off + pm.n_rows] &= m

    def _page_row_mask(self, r, pm, evt, ops) -> np.ndarray | None:
        """Row survivor mask from the encoded page, or None (reason
        booked) — a None mask keeps every row, which is always sound
        because the executor re-applies the full filter."""
        from . import codecs

        try:
            block, nm = r.read_field_page_split(pm)
            plan, why = codecs.split_for_device(block, evt)
        except Exception:
            count_outcome("mask", "read_error")
            return None
        if plan is None:
            count_outcome("mat" if why == "string_v1" else "mask", why)
            return None
        if plan["kind"] == "bitpack":
            bits = _bitpack_bits(plan).astype(bool)
            dense = np.ones(plan["n"], dtype=bool)
            for op, val in ops:
                want = val if op == "bool_eq" else (not val)
                dense &= bits if want else ~bits
        elif plan["kind"] == "dict":
            uniq = plan["values"]
            lut = np.ones(len(uniq), dtype=bool)
            for op, val in ops:
                if op == "str_eq":
                    lut &= np.array([u == val for u in uniq], dtype=bool)
                elif op == "str_ne":
                    lut &= np.array([u != val for u in uniq], dtype=bool)
                else:   # str_in
                    vals = set(val)
                    lut &= np.array([u in vals for u in uniq], dtype=bool)
            codes = _widen(plan["width"], plan["raw"])[:plan["n"]]
            dense = lut[codes.astype(np.int64)]
        else:
            count_outcome("mask", "kind")
            return None
        if nm is None:
            return dense
        rows = np.zeros(pm.n_rows, dtype=bool)
        rows[~nm] = dense   # NULL rows fail the conjunct (kernel 3VL)
        return rows

    # -- batch attachment -------------------------------------------------
    def attach(self, batch) -> None:
        """Hang the lane's results + books on the finished ScanBatch."""
        if self.partials:
            batch.compressed_partials = {
                "rows": self.partials,
                "series_keys": self.series_keys,
                "aggs": self.spec.aggs,
            }
        batch._compressed_engaged = self.engaged
        if self.pages_answered:
            stages.count("compressed.pages_answered", self.pages_answered)
        if self.pages_skipped:
            stages.count("compressed.pages_skipped", self.pages_skipped)
        if self.pages_masked:
            stages.count("compressed.pages_masked", self.pages_masked)
        if self.bytes_avoided:
            stages.count("compressed.bytes_avoided", self.bytes_avoided)
