"""Flush: immutable memcache → delta (L0) TSM file.

Role-parity with reference FlushTask (tskv/src/compaction/flush.rs:21-215):
per-series pages are encoded from the materialized memcache and written as
one L0 file; the resulting VersionEdit carries the flushed WAL seq so the
WAL can be purged behind it.
"""
from __future__ import annotations

import os

from .. import faults
from ..models.schema import TskvTableSchema, ValueType
from ..models.codec import Encoding
from .memcache import MemCache
from .summary import FileMeta, VersionEdit
from .tsm import TsmWriter

faults.register_point("flush.run", __name__,
                      desc="memcache→TSM flush, before the version edit")


def flush_memcache(cache: MemCache, file_id: int, path: str,
                   schemas: dict[str, TskvTableSchema] | None = None) -> VersionEdit | None:
    """Write `cache` to a delta TSM file at `path`; → VersionEdit (None if
    the cache was empty)."""
    if cache.is_empty:
        return None
    if faults.ENABLED:
        faults.fire("flush.run", path=path, file_id=file_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    w = TsmWriter(path)
    n_series = 0
    for table, sid, ts, fields in cache.series_batches():
        if len(ts) == 0:
            continue
        schema = schemas.get(table) if schemas else None
        cols = {}
        for name, (vt, vals, valid) in fields.items():
            cid, enc = _column_meta(schema, name, vt)
            null_mask = None if valid.all() else ~valid
            cols[name] = (cid, vt, enc, vals, null_mask)
        w.write_series(table, sid, ts, cols)
        n_series += 1
    if n_series == 0:
        w.abort()
        return None
    footer = w.finish()
    fm = FileMeta(file_id, 0, footer.min_ts, footer.max_ts,
                  os.path.getsize(path), footer.series_count)
    return VersionEdit(add_files=[fm], flushed_seq=cache.max_seq)


def _column_meta(schema: TskvTableSchema | None, name: str, vt: ValueType):
    if schema is not None and schema.contains_column(name):
        col = schema.column(name)
        enc = col.encoding if col.encoding != Encoding.DEFAULT else col.default_encoding()
        return col.id, enc
    return 0, Encoding.DEFAULT
