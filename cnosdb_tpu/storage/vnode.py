"""Per-vnode storage state machine.

Role-parity with the reference's VnodeStorage (tskv/src/vnode_store.rs:
29-620): the unit that a replica set replicates. apply() consumes logged
commands (Write / DeleteTable / DeleteSeries / DeleteTimeRange / UpdateTags),
write() stages rows into the memcache after series-id assignment, flush()
rotates the active cache into an L0 TSM file recorded in the Summary, and
recovery replays WAL entries above the flushed watermark
(wal_store.rs:429 recover).

Directory layout: <vnode_dir>/{wal/, index/, delta/, tsm/, summary}
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass

import msgpack
import numpy as np

from ..errors import StorageError
from ..models.points import SeriesRows, WriteBatch
from ..models.schema import TskvTableSchema
from ..models.series import SeriesKey, Tag
from .compaction import Picker, gc_compacted_files, run_compaction
from .flush import flush_memcache
from .index import TSIndex
from .memcache import MemCache
from .summary import Summary, VersionEdit
from .tombstone import TombstoneEntry, TsmTombstone
from .wal import Wal, WalEntryType
from ..utils import lockwatch, stages


@dataclass(frozen=True)
class ScanToken:
    """What a cached ScanBatch was decoded from: the TSM file-id set plus
    the last memcache WAL seq at capture time. A later scan whose current
    token differs only by ADDED files / HIGHER seq can decode just the
    delta and merge it into the cached batch; `destructive_version`
    gates that — deletes/tag-renames mutate existing files (tombstones)
    or the index in place, which no file/seq diff can express, so any
    bump forces a full rescan. `data_version` is kept for the exact-match
    fast path (scan_hit)."""

    data_version: int
    destructive_version: int
    file_ids: frozenset
    mem_seq: int


class VnodeStorage:
    def __init__(self, vnode_id: int, dir_path: str,
                 schemas: dict[str, TskvTableSchema] | None = None,
                 memcache_bytes: int = 128 * 1024 * 1024,
                 wal_sync: bool = False,
                 picker: Picker | None = None):
        self.vnode_id = vnode_id
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        self.schemas = schemas if schemas is not None else {}
        self.memcache_bytes = memcache_bytes
        self.lock = lockwatch.RLock(f"vnode.{vnode_id}")
        self.summary = Summary(dir_path)
        self.index = TSIndex(os.path.join(dir_path, "index"))
        self.wal = Wal(os.path.join(dir_path, "wal"), sync_on_append=wal_sync)
        # DR plane: attach the WAL archiver BEFORE replay — replay can
        # flush, flush purges, and the purge fence must already be up
        from . import backup as _backup
        if _backup.archive_enabled():
            _backup.attach_vnode(self)
        self.active = MemCache(vnode_id, memcache_bytes)
        self.immutables: list[MemCache] = []
        self.picker = picker or Picker()
        # monotonically increasing snapshot id: bumps on any mutation so
        # scan caches (host ScanBatch + device twin) invalidate naturally
        self.data_version = 0
        # bumps only on mutations that CANNOT be expressed as a delta over
        # the (file set, memcache seq) token: tombstone-writing deletes,
        # tag re-keys, snapshot installs, in-place memcache field edits
        self.destructive_version = 0
        # post-flush callback set by the storage engine (materialized
        # rollup maintenance); fired OUTSIDE the vnode lock
        self.on_flush = None
        # highest WAL seq whose mutation is REFLECTED in files+memcache.
        # Distinct from wal.next_seq-1: under replication the WAL doubles
        # as the raft log, so entries are durable at replication time but
        # only visible at apply time — a scan token must describe what a
        # scan can see, not what the log stores (see scan_token()).
        self.applied_seq = self.summary.version.flushed_seq
        self._replay_wal()

    def scan_token(self) -> ScanToken:
        """Capture the snapshot token for a scan ABOUT to run. Taken under
        the vnode lock so the file set and seq are mutually consistent; a
        write racing the subsequent (unlocked) decode only makes the token
        conservative — its rows re-decode on the next delta and dedup."""
        with self.lock:
            return ScanToken(
                self.data_version,
                self.destructive_version,
                frozenset(fm.file_id
                          for fm in self.summary.version.all_files()),
                # applied_seq, NOT wal.next_seq-1: a raft-replicated entry
                # sits in the WAL before it commits/applies. A token taken
                # in that window must not claim the entry's seq — the
                # delta path (DeltaVnodeView, seq > token.mem_seq) would
                # then skip its rows forever once they apply.
                self.applied_seq)

    # ------------------------------------------------------------------ boot
    def _replay_wal(self):
        flushed = self.summary.version.flushed_seq
        for entry in self.wal.replay(from_seq=flushed + 1):
            self._apply_entry(entry.entry_type, entry.data, entry.seq, logged=True)

    # ------------------------------------------------------------------ write
    def write(self, batch: WriteBatch, sync: bool = False) -> int:
        """Log + apply one write batch; → assigned WAL seq."""
        with self.lock:
            # stamp schema version + column ids into the WAL payload so a
            # post-crash replay can re-key fields by id across RENAME/DROP
            batch.stamp_schema(self.schemas)
            data = batch.encode()
            seq = self.wal.append(WalEntryType.WRITE, data)
            if sync:
                self.wal.sync()
            self._apply_write(batch, seq)
            if seq > self.applied_seq:
                self.applied_seq = seq
            return seq

    def apply_entry(self, entry_type: int, data: bytes, seq: int):
        """Apply a replicated log entry (replication layer path): the entry
        is already durable in this vnode's WAL at `seq`."""
        with self.lock:
            self._apply_entry(entry_type, data, seq, logged=True)

    def _apply_entry(self, entry_type: int, data: bytes, seq: int, logged: bool):
        # advance even for no-op entries (blank/membership, empty deletes):
        # the entry's full effect is reflected once this call returns
        if seq > self.applied_seq:
            self.applied_seq = seq
        if entry_type == WalEntryType.WRITE:
            self._apply_write(WriteBatch.decode(data), seq)
        elif entry_type == WalEntryType.DELETE_TABLE:
            obj = msgpack.unpackb(data, raw=False)
            self._apply_drop_table(obj["table"])
        elif entry_type == WalEntryType.DELETE_SERIES:
            obj = msgpack.unpackb(data, raw=False)
            self._apply_delete_series(obj["table"], obj["sids"])
        elif entry_type == WalEntryType.UPDATE_TAGS:
            obj = msgpack.unpackb(data, raw=False)
            self._apply_update_tags(obj["table"], obj["old_keys"], obj["new_keys"])
        elif entry_type == WalEntryType.DELETE_TIME_RANGE:
            obj = msgpack.unpackb(data, raw=False)
            sids = obj.get("sids")
            if obj.get("doms") is not None:
                # replicated deletes carry the tag predicate and resolve
                # series ids at APPLY time on each replica — identical by
                # determinism, and robust to replica index skew
                from ..models.predicate import ColumnDomains

                doms = ColumnDomains.from_wire(obj["doms"])
                if not doms.is_all:
                    sids = self.index.get_series_ids_by_domains(
                        obj["table"], doms)
                    if len(sids) == 0:
                        return
            self._apply_delete_time_range(obj["table"], sids,
                                          obj["min_ts"], obj["max_ts"])
        # RAFT_BLANK/MEMBERSHIP: no storage effect

    def _apply_write(self, batch: WriteBatch, seq: int):
        self.data_version += 1
        for table, series_list in batch.tables.items():
            # the batch's schema stamp vs the live schema: replayed entries
            # written before a RENAME/DROP re-key their fields by column id
            # (live writes stamp and apply under one lock, so remap is None)
            remap = batch.replay_remap(table, self.schemas.get(table))
            for sr in series_list:
                sid = self.index.add_series_if_not_exists(sr.key)
                if remap is not None:
                    fields = {}
                    for name, v in sr.fields.items():
                        tgt = remap.get(name, name)
                        if tgt is not None:   # None → column dropped
                            fields[tgt] = v
                    sr = SeriesRows(sr.key, sr.timestamps, fields)
                self.active.write_series(table, sid, sr, seq)
        if self.active.should_flush():
            self.flush()

    # ------------------------------------------------------------------ flush
    def switch_to_immutable(self):
        with self.lock:
            if self.active.is_empty:
                return
            self.active.mark_immutable()
            self.immutables.append(self.active)
            self.active = MemCache(self.vnode_id, self.memcache_bytes)

    def flush(self, sync: bool = True):
        """Rotate active cache and persist ALL immutables to L0 files."""
        flushed = False
        with self.lock:
            self.switch_to_immutable()
            if self.immutables:
                self.data_version += 1
                flushed = True
            for cache in self.immutables:
                fid = self.summary.next_file_id()
                path = os.path.join(self.dir, "delta", f"_{fid:06d}.tsm")
                edit = flush_memcache(cache, fid, path, self.schemas)
                if edit is not None:
                    self.summary.apply(edit, sync=sync)
            self.immutables.clear()
            self.index.sync()
            self.wal.sync()
            self.wal.purge_to(self.summary.version.flushed_seq + 1)
        cb = self.on_flush
        if flushed and cb is not None:
            # outside the lock: listeners must never block the write path
            try:
                cb()
            except Exception:
                stages.count_error("flush.listener")

    def rename_mem_field(self, table: str, old: str, new: str):
        """ALTER ... RENAME COLUMN: re-key buffered (unflushed) rows so
        in-memory data follows the column the same way id-resolved TSM
        chunks do — without this, renaming a column to a previously-used
        name would conflate the two columns' unflushed values."""
        with self.lock:
            # in-place memcache edit: invisible to the (file set, seq)
            # token, so delta merges must not span it (the schema_version
            # cache key already isolates it; this is defense in depth)
            self.destructive_version += 1
            for cache in [self.active, *self.immutables]:
                for (t, _sid), sd in cache.series.items():
                    if t == table and old in sd.field_chunks:
                        sd.field_chunks[new] = sd.field_chunks.pop(old)

    def drop_mem_field(self, table: str, name: str):
        """ALTER ... DROP COLUMN: purge buffered rows of the dropped
        field. Leftover name-keyed memcache chunks would otherwise be
        resurrected by a later RENAME/ADD that reuses the name (flushed
        chunks are immune: their dropped column id is never requested)."""
        with self.lock:
            self.destructive_version += 1
            for cache in [self.active, *self.immutables]:
                for (t, _sid), sd in cache.series.items():
                    if t == table:
                        sd.field_chunks.pop(name, None)

    # ------------------------------------------------------------------ compact
    def _compaction_exclude(self) -> frozenset:
        """File ids compaction must leave alone: cold-tiered files (their
        bytes live in the object store — storage/tiering.py) plus any hot
        file overlapping a cold file's time range. The overlap closure
        prevents resurrection: a rewrite landing at a level that outranks
        a cold file carrying a newer row version would flip
        last-write-wins. Backfill writes into an already-tiered window
        therefore freeze until the tiering job moves them too (documented
        limitation)."""
        from . import tiering

        cold = tiering.cold_ids(self.dir)
        if not cold:
            return frozenset()
        version = self.summary.version
        all_fms = version.all_files()
        ranges = [(fm.min_ts, fm.max_ts) for fm in all_fms
                  if fm.file_id in cold]
        out = set(cold)
        for fm in all_fms:
            if fm.file_id not in out and any(
                    fm.overlaps(lo, hi) for lo, hi in ranges):
                out.add(fm.file_id)
        return frozenset(out)

    def compact(self, force_level: int | None = None) -> bool:
        """Run at most one compaction round; → True if work was done."""
        with self.lock:
            if self._promote_l0():
                return True
            req = self.picker.pick(self.summary.version,
                                   exclude=self._compaction_exclude())
            if req is None:
                return False
            fid = self.summary.next_file_id()
            edit = run_compaction(
                self.summary.version, req, fid,
                alloc_id=self.summary.next_file_id,
                max_out_bytes=self.picker.max_output_file_size,
                schemas=self.schemas)
            if edit is None:
                return False
            # bump only when the file set actually changes so no-op rounds
            # don't invalidate scan caches
            self.data_version += 1
            self.summary.apply(edit)
            gc_compacted_files(self.summary.version, edit)
            return True

    def _promote_l0(self) -> bool:
        """Rewrite-free level promotion (picker.pick_promotions): for
        L0→L1, link the physical file into tsm/ and drop the delta link
        (levels ≥1 share the tsm/ dir — a pure metadata flip). Crash-safe
        in every window: before the edit lands the meta still says the
        old level (its link intact, the new one is garbage for gc);
        after, the new level's link is the live one."""
        import dataclasses

        from .tombstone import tombstone_path as _tb

        version = self.summary.version
        promos = self.picker.pick_promotions(
            version, exclude=self._compaction_exclude())
        if not promos:
            return False
        adds = []
        for fm, target in promos:
            src = version.file_path(fm)
            new = dataclasses.replace(fm, level=target)
            dst = version.file_path(new)
            if dst != src:
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                if not os.path.exists(dst):
                    os.link(src, dst)
                if os.path.exists(_tb(src)) and not os.path.exists(_tb(dst)):
                    os.link(_tb(src), _tb(dst))
            adds.append(new)
        self.data_version += 1
        self.summary.apply(VersionEdit(
            add_files=adds, del_files=[fm.file_id for fm, _ in promos]))
        for fm, target in promos:
            src = version.file_path(fm)   # path at the OLD level
            new = dataclasses.replace(fm, level=target)
            if version.file_path(new) == src:
                continue
            for p in (src, _tb(src)):
                if os.path.exists(p):
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
        return True

    def quarantine_file(self, path: str | None = None,
                        file_id: int | None = None) -> int | None:
        """Contain a corrupt TSM file: durably drop it from the manifest
        (future scans never open it; the cached reader is closed by the
        VersionEdit apply) and rename it to `<path>.quarantine` — kept on
        disk as forensic evidence, invisible to the `.tsm`-suffix GC, and
        wiped by the next snapshot install (repair). Bumps both version
        counters so every ScanToken / scan-cache entry over this vnode
        invalidates. → the quarantined file_id, or None when the file is
        not (or no longer) referenced."""
        with self.lock:
            version = self.summary.version
            target = None
            for fm in version.all_files():
                if fm.file_id == file_id or (
                        path is not None
                        and os.path.abspath(version.file_path(fm))
                        == os.path.abspath(path)):
                    target = fm
                    break
            if target is None:
                return None
            fpath = version.file_path(target)
            self.summary.apply(VersionEdit(del_files=[target.file_id]))
            try:
                os.replace(fpath, fpath + ".quarantine")
            except OSError:
                pass   # already renamed / vanished: the manifest drop holds
            self.data_version += 1
            self.destructive_version += 1
            return target.file_id

    def quarantined_files(self) -> list[str]:
        """Paths of quarantined (renamed-aside) TSM files still on disk."""
        out = []
        for sub in ("delta", "tsm"):
            d = os.path.join(self.dir, sub)
            if os.path.isdir(d):
                out.extend(os.path.join(d, n) for n in sorted(os.listdir(d))
                           if n.endswith(".quarantine"))
        return out

    def compact_major(self) -> bool:
        """One-shot FULL compaction: merge every file of every level into
        time-partitioned, size-bounded files at one level (reference user
        COMPACT = full compaction). One pass over the data — unlike
        looping normal rounds, which against heavily-overlapping tiered
        levels would rewrite the tail repeatedly."""
        from .compaction import CompactReq

        with self.lock:
            version = self.summary.version
            exclude = self._compaction_exclude()
            files = [f for lvl in range(0, 5)
                     for f in version.levels[lvl].values()
                     if f.file_id not in exclude]
            if len(files) <= 1:
                return False
            total = sum(f.size for f in files)
            # land everything at the smallest level whose budget holds it
            target = 1
            while target < 4 and total > self.picker.level_max_size(target):
                target += 1
            req = CompactReq(files, target)
            fid = self.summary.next_file_id()
            edit = run_compaction(
                self.summary.version, req, fid,
                alloc_id=self.summary.next_file_id,
                max_out_bytes=self.picker.max_output_file_size,
                schemas=self.schemas)
            if edit is None:
                return False
            self.data_version += 1
            self.summary.apply(edit)
            gc_compacted_files(self.summary.version, edit)
            return True

    def file_snapshot(self) -> dict:
        """FILE-level snapshot (reference vnode_store.rs:129-213
        VnodeSnapshot = VersionEdit + file set shipped via DownloadFile):
        flush everything, then capture the physical files — TSM levels,
        summary manifest, index checkpoint/binlog — as relative-path blobs.
        The WAL is excluded: it IS the raft log being snapshotted around.

        Lock discipline: only the MANIFEST (file list + small mutable
        metadata) is captured under the vnode lock; TSM data files are
        immutable once written, so their bytes are read after release —
        a concurrent compaction that deletes one shows up as a missing
        file and triggers a retry, instead of stalling writes for the
        whole multi-GB read.

        A vnode holding quarantined files REFUSES to snapshot: its state
        machine no longer matches the applied log (the quarantined rows
        are gone), so serving the snapshot — to a raft follower or a
        repair fetch — would clone the data loss onto healthy replicas.
        Repair wipes the quarantine evidence on install, which is what
        re-enables snapshots afterwards."""
        if self.quarantined_files():
            raise StorageError(
                f"vnode {self.vnode_id} has quarantined files: snapshot "
                "refused (state diverged from the applied log; this "
                "replica must be repaired from a healthy peer first)")
        skip_top = {"wal", "hardstate"}
        for _attempt in range(5):
            with self.lock:
                self.flush(sync=True)
                files: dict[str, bytes] = {}
                big: list[str] = []
                for root, _dirs, names in os.walk(self.dir):
                    rel_root = os.path.relpath(root, self.dir)
                    if rel_root.split(os.sep)[0] in skip_top:
                        continue
                    for name in names:
                        if rel_root == "." and name == "hardstate":
                            continue
                        if name.endswith(".quarantine"):
                            continue   # forensic evidence, never shipped
                        rel = os.path.normpath(os.path.join(rel_root, name))
                        if name.endswith(".tsm"):
                            big.append(rel)   # immutable: read outside
                        else:
                            with open(os.path.join(root, name), "rb") as f:  # lint: disable=lock-blocking (small mutable files read under lock so the snapshot is a consistent cut)
                                files[rel] = f.read()
            try:
                for rel in big:
                    with open(os.path.join(self.dir, rel), "rb") as f:
                        files[rel] = f.read()
                return {"files": files, "digests": _digests(files)}
            except FileNotFoundError:
                continue   # compaction replaced the file set: re-capture
        # final attempt entirely under the lock (consistency over latency)
        with self.lock:
            self.flush(sync=True)
            files = {}
            for root, _dirs, names in os.walk(self.dir):
                rel_root = os.path.relpath(root, self.dir)
                if rel_root.split(os.sep)[0] in skip_top:
                    continue
                for name in names:
                    if rel_root == "." and name == "hardstate":
                        continue
                    if name.endswith(".quarantine"):
                        continue
                    rel = os.path.normpath(os.path.join(rel_root, name))
                    with open(os.path.join(root, name), "rb") as f:  # lint: disable=lock-blocking (final capture attempt deliberately under lock: consistency over latency)
                        files[rel] = f.read()
            return {"files": files, "digests": _digests(files)}

    def install_file_snapshot(self, snap: dict):
        """Replace this vnode's physical state with a snapshot, in place
        (the raft member and engine registry keep their object). Old
        readers stay valid on unlinked inodes; data_version invalidates
        every cache. Paths are CONFINED to the vnode dir — the snapshot
        arrives over the network and must never become a file-write
        primitive outside it."""
        import shutil

        base = os.path.realpath(self.dir)
        digests = snap.get("digests") or {}
        for rel in snap["files"]:
            if os.path.isabs(rel):
                raise StorageError(f"absolute path in snapshot: {rel!r}")
            dest = os.path.realpath(os.path.join(base, rel))
            if not (dest == base or dest.startswith(base + os.sep)):
                raise StorageError(f"path escapes vnode dir: {rel!r}")
            want = digests.get(rel)
            if want is not None and _sha256(snap["files"][rel]) != want:
                raise StorageError(
                    f"snapshot file {rel!r} corrupted in transit")
        with self.lock:
            self.summary.version.close()
            self.summary.close()
            self.index.close()
            for name in os.listdir(self.dir):
                if name in ("wal", "hardstate"):
                    continue
                path = os.path.join(self.dir, name)
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                else:
                    os.unlink(path)
            for rel, raw in snap["files"].items():
                path = os.path.join(self.dir, rel)
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "wb") as f:  # lint: disable=lock-blocking (snapshot install must be atomic vs readers; consistency over latency)
                    f.write(raw)
            self.summary = Summary(self.dir)
            self.index = TSIndex(os.path.join(self.dir, "index"))
            self.active = MemCache(self.vnode_id, self.memcache_bytes)
            self.immutables = []
            self.data_version += 1
            self.destructive_version += 1

    def checksum(self) -> str:
        """Content checksum of every live row, independent of physical
        layout (reference compaction/check.rs:99 ChecksumGroup): replicas
        of one raft group must agree regardless of flush/compaction state,
        so the hash runs over the logical merged scan in canonical
        (table, series key, time) order. Vectorized — whole-column buffers
        feed the hash, so multi-million-row vnodes answer within an RPC
        timeout instead of minutes of per-row python."""
        import hashlib

        import numpy as np

        from ..models.strcol import as_object_array
        from .scan import scan_vnode

        h = hashlib.sha256()
        with self.lock:
            # under the vnode lock: a concurrent snapshot install swaps
            # summary/index mid-scan otherwise (truncated-footer reads
            # while a lagging replica is being seeded)
            tables = set()
            for (table, _sid) in list(self.active.series.keys()):
                tables.add(table)
            for c in self.immutables:
                for (table, _sid) in c.series:
                    tables.add(table)
            for fm in self.summary.version.all_files():
                r = self.summary.version.reader(fm)
                tables.update(r.tables())
            batches = {t: scan_vnode(self, t) for t in sorted(tables)}  # lint: disable=lock-held-dispatch (checksum scan must see one version cut; consistency over latency)
        for table in sorted(tables):
            b = batches[table]
            if b.n_rows == 0:
                continue
            keys = [k.encode() if k is not None else b""
                    for k in b.series_keys]
            # canonical order: series key bytes, then time — via the rank
            # of each row's key so lexsort stays fully vectorized
            key_rank_of_series = np.argsort(
                np.argsort(np.array(keys, dtype=object)))
            key_rank = key_rank_of_series[b.sid_ordinal]
            order = np.lexsort((b.ts, key_rank))
            h.update(table.encode())
            for kb in sorted(keys):   # key SET in key order — layout-free
                h.update(kb)
            h.update(key_rank[order].astype(np.int64).tobytes())
            h.update(b.ts[order].astype(np.int64).tobytes())
            for name in sorted(b.fields):
                _vt, vals, valid = b.fields[name]
                h.update(name.encode())
                h.update(valid[order].astype(np.uint8).tobytes())
                v_ord = as_object_array(vals[order])
                if v_ord.dtype == object:
                    masked = np.where(valid[order], v_ord, "")
                    h.update("\x00".join(str(x) for x in masked).encode())
                else:
                    zero = np.zeros((), dtype=v_ord.dtype)
                    h.update(np.where(valid[order], v_ord, zero).tobytes())
        return h.hexdigest()

    def compact_full(self, max_rounds: int = 32):
        for _ in range(max_rounds):
            if not self.compact():
                break

    # ------------------------------------------------------------------ deletes
    def drop_table(self, table: str):
        with self.lock:
            data = msgpack.packb({"table": table})
            seq = self.wal.append(WalEntryType.DELETE_TABLE, data)
            self._apply_drop_table(table)
            self.applied_seq = max(self.applied_seq, seq)

    def _apply_drop_table(self, table: str):
        self.data_version += 1
        self.destructive_version += 1
        self.active.delete_table(table)
        for c in self.immutables:
            c.delete_table(table)
        for sid in self.index.table_series_ids(table):
            self.index.del_series(int(sid))
        for fm in self.summary.version.all_files():
            self.summary.version.tombstone(fm).add(
                [TombstoneEntry(table, None, -(2**63), 2**63 - 1)])

    def delete_series(self, table: str, sids: list[int]):
        with self.lock:
            data = msgpack.packb({"table": table, "sids": [int(s) for s in sids]})
            seq = self.wal.append(WalEntryType.DELETE_SERIES, data)
            self._apply_delete_series(table, sids)
            self.applied_seq = max(self.applied_seq, seq)

    def _apply_delete_series(self, table: str, sids):
        self.data_version += 1
        self.destructive_version += 1
        for c in [self.active, *self.immutables]:
            for sid in sids:
                c.delete_series(table, int(sid))
        for fm in self.summary.version.all_files():
            self.summary.version.tombstone(fm).add(
                [TombstoneEntry(table, int(s), -(2**63), 2**63 - 1) for s in sids])

    def delete_time_range(self, table: str, sids, min_ts: int, max_ts: int):
        """DELETE FROM t WHERE ... (reference vnode_store.rs:503)."""
        with self.lock:
            data = msgpack.packb({
                "table": table,
                "sids": [int(s) for s in sids] if sids is not None else None,
                "min_ts": int(min_ts), "max_ts": int(max_ts)})
            seq = self.wal.append(WalEntryType.DELETE_TIME_RANGE, data)
            self._apply_delete_time_range(table, sids, min_ts, max_ts)
            self.applied_seq = max(self.applied_seq, seq)

    def _apply_delete_time_range(self, table: str, sids, min_ts: int, max_ts: int):
        self.data_version += 1
        self.destructive_version += 1
        for c in [self.active, *self.immutables]:
            c.delete_time_range(table, sids, min_ts, max_ts)
        ents = ([TombstoneEntry(table, int(s), min_ts, max_ts) for s in sids]
                if sids is not None else [TombstoneEntry(table, None, min_ts, max_ts)])
        for fm in self.summary.version.all_files():
            if fm.overlaps(min_ts, max_ts):
                self.summary.version.tombstone(fm).add(ents)

    def _apply_update_tags(self, table: str, old_keys: list[bytes], new_keys: list[bytes]):
        """UPDATE tag values: re-key series (reference update_tags_value)."""
        self.data_version += 1
        self.destructive_version += 1
        for ob, nb in zip(old_keys, new_keys):
            old_key = SeriesKey.decode(ob)
            sid = self.index.get_series_id(old_key)
            if sid is None:
                continue
            self.index.rename_series(sid, SeriesKey.decode(nb))

    def update_tags(self, table: str, old_keys: list[SeriesKey], new_keys: list[SeriesKey]):
        with self.lock:
            data = msgpack.packb({
                "table": table,
                "old_keys": [k.encode() for k in old_keys],
                "new_keys": [k.encode() for k in new_keys]})
            seq = self.wal.append(WalEntryType.UPDATE_TAGS, data)
            self._apply_update_tags(table, [k.encode() for k in old_keys],
                                    [k.encode() for k in new_keys])
            self.applied_seq = max(self.applied_seq, seq)

    # ------------------------------------------------------------------ stats
    def series_count(self) -> int:
        return self.index.series_count()

    def disk_size(self) -> int:
        return sum(f.size for f in self.summary.version.all_files())

    def close(self):
        with self.lock:
            self.flush()
            self.wal.close()
            self.index.close()
            self.summary.close()


def _sha256(raw: bytes) -> str:
    import hashlib

    return hashlib.sha256(raw).hexdigest()


def _digests(files: dict[str, bytes]) -> dict[str, str]:
    """Per-file integrity digests shipped with a snapshot: install
    verifies them so transit corruption fails loudly instead of landing
    silently in the store."""
    return {rel: _sha256(raw) for rel, raw in files.items()}
