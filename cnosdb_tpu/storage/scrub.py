"""Background integrity scrubber: end-to-end CRC verification at rest.

Role-parity with the reference's file-level checksums plus the repair loop
the paper's integrity plane calls for: silent corruption (bit rot, torn
sectors, fs bugs) is found *before* a query trips over it, and found
corruption feeds the same quarantine path the read side uses — the file is
dropped from the live Version (manifest-durable), renamed aside, and the
vnode is left for anti-entropy repair to restore from a healthy replica.

What is verified, per vnode:
  - every live TSM file (delta + tsm levels): footer crc via TsmReader
    construction, then every page crc via ``_read_page`` over the full
    chunk tree — the same codepaths a scan exercises, so a clean scrub
    means clean reads. Known gap: the bloom region carries no crc in the
    TSM format, so a flipped bloom bit (possible silent false-negative
    series skip) is invisible to both scrub and reads;
  - the index checkpoint (``index.ckpt``): magic/version header (the body
    is msgpack + numpy sections decoded on open; a bad header is the
    corruption signature of a torn replace);
  - sealed WAL segments (every ``wal_*.log`` except the active tail):
    ``record_file._valid_prefix_len`` must cover the whole file.

Actively-appended record files (summary manifest, index binlog, active WAL
segment) are deliberately NOT scrubbed — a reader racing an in-flight
append sees a legitimately torn tail, which replay tolerates by design.

Scrubbing is rate-limited by a token bucket (``scrub_mb_per_sec``) so a
background sweep cannot starve foreground scans of disk bandwidth, and is
off by default (``scrub_interval = 0``) so tests and benchmarks see no
background I/O unless they opt in.

Counters (always on, folded into /metrics):
    scrub_bytes           bytes whose crcs were verified
    scrub_files           files fully verified
    corruptions_detected  mismatches found (scrub or read path)
    files_quarantined     TSM files renamed aside + dropped from Version
    repairs_ok            anti-entropy snapshot repairs that converged
    repairs_failed        repair attempts that did not converge
"""
from __future__ import annotations

import logging
import os
import threading
import time

from ..errors import ChecksumMismatch, CnosError
from .. import faults
from .index import CKPT_NAME, _CKPT_MAGIC
from .record_file import _valid_prefix_len
from .tsm import TsmReader
from .wal import SEGMENT_PATTERN
from ..utils import lockwatch

log = logging.getLogger(__name__)

faults.register_point("scrub.read", __name__,
                      desc="scrubber about to verify a file (corrupt-at-rest)")

# ---------------------------------------------------------------------------
# counters — always on (stages.count_error pattern); cheap enough to never
# gate, folded into /metrics gauges at render time
# ---------------------------------------------------------------------------
_COUNTER_NAMES = ("scrub_bytes", "scrub_files", "corruptions_detected",
                  "files_quarantined", "repairs_ok", "repairs_failed")
_counters = {k: 0 for k in _COUNTER_NAMES}
_counters_lock = lockwatch.Lock("scrub.counters")


def count(name: str, n: int = 1) -> None:
    with _counters_lock:
        _counters[name] = _counters.get(name, 0) + int(n)


def counters_snapshot() -> dict[str, int]:
    with _counters_lock:
        return dict(_counters)


def counters_reset() -> None:
    """Test helper: zero all counters."""
    with _counters_lock:
        for k in list(_counters):
            _counters[k] = 0


# ---------------------------------------------------------------------------
# rate limiting
# ---------------------------------------------------------------------------
class RateLimiter:
    """Token bucket in bytes/sec; ``take`` blocks until the debt drains.

    Capacity is one second's allowance, so a burst (one big TSM file read
    at once) borrows at most ~1s ahead and then pays it back — the sweep's
    long-run rate stays within ~2x of the configured target even though
    verification reads whole files."""

    def __init__(self, bytes_per_sec: int):
        self.rate = max(1, int(bytes_per_sec))
        self._avail = float(self.rate)
        self._last = time.monotonic()
        self._lock = lockwatch.Lock("scrub.throttle")

    def take(self, nbytes: int, stop: threading.Event | None = None) -> None:
        while True:
            with self._lock:
                now = time.monotonic()
                self._avail = min(float(self.rate),
                                  self._avail + (now - self._last) * self.rate)
                self._last = now
                if self._avail > 0:
                    self._avail -= nbytes  # may go negative: debt
                    return
                wait = max(-self._avail / self.rate, 0.001)
            if stop is not None and stop.wait(min(wait, 0.25)):
                return
            if stop is None:
                time.sleep(min(wait, 0.25))


# ---------------------------------------------------------------------------
# verification primitives — each returns bytes verified, raises
# ChecksumMismatch on corruption
# ---------------------------------------------------------------------------
def verify_tsm(path: str) -> int:
    """Footer crc + every page crc of one TSM file.

    Raises ChecksumMismatch for ANY damage — crc mismatch, bad magic, a
    meta tree that no longer decompresses — because a flip landing in the
    meta/footer region is the same bit rot as one landing in a page. Only
    a missing file propagates as OSError (compaction race, not damage)."""
    size = os.path.getsize(path)
    try:
        r = TsmReader(path)
    except ChecksumMismatch:
        raise
    except OSError:
        raise
    except Exception as e:
        raise ChecksumMismatch(f"tsm structure: {e}", path=path)
    try:
        for group in r.groups.values():
            for chunk in group.chunks.values():
                for pm in chunk.time_pages:
                    r._read_page(pm)
                for col in chunk.columns:
                    for pm in col.pages:
                        r._read_page(pm)
    except ChecksumMismatch:
        raise
    except Exception as e:
        raise ChecksumMismatch(f"tsm page walk: {e}", path=path)
    finally:
        r.close()
    return size


def verify_record_file(path: str) -> int:
    """A sealed record file must be valid crc'd records end to end."""
    size = os.path.getsize(path)
    ok = _valid_prefix_len(path)
    if ok < size:
        raise ChecksumMismatch("record crc", path=path, offset=ok)
    return size


def verify_index_checkpoint(path: str) -> int:
    """Header magic/version of an index checkpoint (atomic-replace
    artifact: a bad header means the file itself is damaged)."""
    import struct

    size = os.path.getsize(path)
    with open(path, "rb") as f:
        hdr = f.read(12)
    if len(hdr) < 12:
        raise ChecksumMismatch("index ckpt truncated", path=path, offset=0)
    magic, _version, hlen = struct.unpack("<III", hdr)
    if magic != _CKPT_MAGIC or 12 + hlen > size:
        raise ChecksumMismatch("index ckpt header", path=path, offset=0)
    return size


# ---------------------------------------------------------------------------
# per-vnode sweep
# ---------------------------------------------------------------------------
def _corrupt_window(path: str) -> tuple[int, int | None]:
    """Flip window for the `corrupt` fault action: for TSM files, the
    crc-covered page region [5, meta_off) — a flip in the (un-crc'd)
    bloom region would be undetectable by design and make the fault a
    no-op for tests; other files flip anywhere."""
    if path.endswith(".tsm"):
        import struct

        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(size - 64)
                meta_off = struct.unpack("<Q", f.read(8))[0]
            if 5 < meta_off <= size:
                return 5, meta_off
        except (OSError, struct.error):
            pass
    return 0, None


def _fire_read_fault(path: str) -> None:
    """`scrub.read` fault point: lets tests flip bytes of exactly the file
    the scrubber is about to verify (deterministic corruption-at-rest)."""
    if faults.ENABLED:
        hit = faults.fire("scrub.read", path=path)
        if hit and hit[0] == "corrupt":
            lo, hi = _corrupt_window(path)
            faults.corrupt_file(path, int(hit[1] or 1), lo=lo, hi=hi)


def scrub_vnode(vnode, limiter: RateLimiter | None = None,
                stop: threading.Event | None = None) -> dict:
    """Verify one vnode's at-rest artifacts; quarantine corrupt TSM files.

    Returns a summary dict: {"bytes", "files", "corrupt": [paths]}."""
    out = {"bytes": 0, "files": 0, "corrupt": []}

    def _budget(path: str) -> int:
        try:
            size = os.path.getsize(path)
        except OSError:
            return -1  # vanished (compaction / quarantine race): skip
        if limiter is not None:
            limiter.take(size, stop)
        return size

    # -- live TSM files (snapshot the list; compaction may mutate) -------
    from . import tiering
    from ..utils import objstore

    with vnode.lock:
        version = vnode.summary.version
        cold = tiering.cold_ids(vnode.dir)
        tsm_files = [(version.file_path(fm), fm.file_id)
                     for fm in version.all_files()]
    for path, fid in tsm_files:
        if stop is not None and stop.is_set():
            return out
        if fid in cold:
            # cold file: no local bytes. Verify the local sidecar still
            # parses and the remote object's footer matches it (a cheap
            # ranged GET); divergence is corruption evidence that feeds
            # the same anti-entropy repair path, but never quarantine —
            # the manifest entry is the only pointer to the remote bytes
            try:
                n = tiering.verify_cold_file(vnode, fid)
            except ChecksumMismatch as e:
                log.warning("scrub: cold-tier corruption in %s: %s", path, e)
                count("corruptions_detected")
                out["corrupt"].append(path)
            except (OSError, objstore.ObjectStoreError):
                continue  # store unreachable / races: not corruption
            else:
                out["bytes"] += n
                out["files"] += 1
                count("scrub_bytes", n)
                count("scrub_files")
            continue
        if _budget(path) < 0:
            continue
        _fire_read_fault(path)
        try:
            n = verify_tsm(path)
        except ChecksumMismatch as e:
            log.warning("scrub: corruption in %s: %s", path, e)
            count("corruptions_detected")
            out["corrupt"].append(path)
            if vnode.quarantine_file(path=path) is not None:
                count("files_quarantined")
            continue
        except OSError:
            continue  # racing delete/compaction — not corruption evidence
        out["bytes"] += n
        out["files"] += 1
        count("scrub_bytes", n)
        count("scrub_files")

    # -- index checkpoint ------------------------------------------------
    ckpt = os.path.join(vnode.dir, "index", CKPT_NAME)
    if os.path.exists(ckpt) and not (stop is not None and stop.is_set()):
        if _budget(ckpt) >= 0:
            _fire_read_fault(ckpt)
            try:
                n = verify_index_checkpoint(ckpt)
                out["bytes"] += n
                out["files"] += 1
                count("scrub_bytes", n)
                count("scrub_files")
            except ChecksumMismatch as e:
                log.warning("scrub: corruption in %s: %s", ckpt, e)
                count("corruptions_detected")
                out["corrupt"].append(ckpt)
            except OSError:
                pass

    # -- sealed WAL segments (all but the active tail) -------------------
    wal_dir = os.path.join(vnode.dir, "wal")
    try:
        segs = sorted(n for n in os.listdir(wal_dir)
                      if SEGMENT_PATTERN.match(n))
    except OSError:
        segs = []
    for name in segs[:-1]:
        if stop is not None and stop.is_set():
            return out
        path = os.path.join(wal_dir, name)
        if _budget(path) < 0:
            continue
        _fire_read_fault(path)
        try:
            n = verify_record_file(path)
            out["bytes"] += n
            out["files"] += 1
            count("scrub_bytes", n)
            count("scrub_files")
        except ChecksumMismatch as e:
            log.warning("scrub: corruption in %s: %s", path, e)
            count("corruptions_detected")
            out["corrupt"].append(path)
        except OSError:
            pass
    return out


def scrub_engine(engine, limiter: RateLimiter | None = None,
                 stop: threading.Event | None = None,
                 on_corruption=None) -> dict:
    """One full sweep over every open vnode of a TsKv engine."""
    total = {"bytes": 0, "files": 0, "corrupt": []}
    with engine.lock:
        vnodes = list(engine.vnodes.items())
    for (owner, vid), vnode in vnodes:
        if stop is not None and stop.is_set():
            break
        try:
            res = scrub_vnode(vnode, limiter, stop)
        except CnosError as e:  # vnode closed mid-sweep
            log.debug("scrub: skipping vnode %s/%s: %s", owner, vid, e)
            continue
        total["bytes"] += res["bytes"]
        total["files"] += res["files"]
        total["corrupt"].extend(res["corrupt"])
        if res["corrupt"] and on_corruption is not None:
            on_corruption(owner, vid, res["corrupt"])
    return total


# ---------------------------------------------------------------------------
# background worker
# ---------------------------------------------------------------------------
class Scrubber:
    """Daemon thread running ``scrub_engine`` every ``interval`` seconds.

    ``on_corruption(owner, vnode_id, paths)`` (optional) is the bridge to
    the coordinator: marking the vnode BROKEN so scans fail over, and
    letting the anti-entropy sweep repair it from a replica."""

    def __init__(self, engine, interval: int, mb_per_sec: int = 8,
                 on_corruption=None):
        self.engine = engine
        self.interval = max(1, int(interval))
        self.limiter = RateLimiter(max(1, int(mb_per_sec)) * (1 << 20))
        self.on_corruption = on_corruption
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_sweep: dict | None = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="scrubber", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def sweep_once(self) -> dict:
        """Synchronous full sweep (the /debug/scrub trigger); rate-limited
        like the background loop."""
        res = scrub_engine(self.engine, self.limiter, self._stop,
                           self.on_corruption)
        self.last_sweep = res
        return res

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sweep_once()
            except Exception:  # noqa: BLE001 — the sweep must never die
                log.exception("scrub sweep failed")
