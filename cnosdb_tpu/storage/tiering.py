"""Tiered object-store cold storage with near-data pruning.

ROADMAP open item 2 (Taurus, arxiv 2506.20010; "Should I Hide My Duck in
the Lake?", arxiv 2602.18775): retention stops being capped by local disk
by aging sealed TSM files into the object store while keeping a local
**skip-index sidecar** — the file's trailing metadata section (chunk/page
meta with zone maps and trigram ngram signatures, bloom filter, footer) —
so per-page pruning (time range, value stats, tag domains, LIKE '%x%')
runs entirely locally *before* any byte is downloaded. Surviving pages
fetch via byte-range GETs (utils/objstore.py) through a capped local
block cache and feed the existing device/native/py decode lanes
unchanged.

Physical layout per tiered file ``_{id:06d}.tsm``:

* object store: the complete original file at key
  ``{prefix}/vnode_{vid}/f{id:06d}.tsm`` (bit-identical — rehydration is
  a download, and scrub can verify it against the sidecar's footer);
* local sidecar ``_{id:06d}.tsmc`` (same delta/tsm subdir; the ``.tsm``
  suffix GC in summary.py never touches it):
  ``[magic u32][ver u8][orig_size u64][tail_off u64]`` + the original
  bytes ``[tail_off:]`` where ``tail_off = footer.meta_off`` — pages live
  in ``[5, meta_off)`` and stay remote;
* per-vnode registry ``cold.json`` mapping file_id → {key, size,
  tail_off}, consulted by ``Version.reader`` (summary.py) to open a
  :class:`ColdTsmReader` instead of the mmap reader.

Every exit out of the cold lane books a (lane, reason) into
``cnosdb_cold_tier_total`` — enforced by the ``cold-tier-accounting``
lint rule — so download-vs-decode time and silent fallbacks stay visible
on /metrics and in EXPLAIN ANALYZE (``cold.*`` stages).
"""
from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib

from .. import faults
from ..errors import ChecksumMismatch, StorageError, TsmError
from ..utils import lockwatch, stages
from ..utils import objstore
from .tombstone import tombstone_path
from .tsm import FOOTER_SIZE, TsmReader, parse_tail

SIDECAR_MAGIC = 0x7C05DBC1
SIDECAR_VERSION = 1
_SIDECAR_HDR = struct.Struct("<IBQQ")

faults.register_point("tiering.registry", __name__,
                      desc="cold.json rewrite, between fsync and rename")
SIDECAR_SUFFIX = ".tsmc"
REGISTRY_NAME = "cold.json"

# pruned-page gaps smaller than this ride along inside one coalesced
# range GET — a second request round-trip costs more than the bytes
COALESCE_GAP = int(os.environ.get("CNOSDB_COLD_COALESCE_GAP", 64 * 1024))


def enabled() -> bool:
    """Whether the tiering plane may *move* data (CNOSDB_COLD_TIER=0 is
    the parity knob: nothing tiers, everything scans hot). Reading
    already-tiered files is never gated — the bytes only exist remotely."""
    return os.environ.get("CNOSDB_COLD_TIER", "1") != "0" and configured()


# ---------------------------------------------------------------------------
# store configuration (process-global, set from config/server wiring;
# credentials live here and are never persisted into cold.json)
# ---------------------------------------------------------------------------
_cfg_lock = lockwatch.Lock("tiering.config")
_cfg: dict = {"uri": "", "options": {}, "store": None, "prefix": ""}


def configure(uri: str | None, options: dict | None = None) -> None:
    """Point the cold tier at `uri` (s3://…, gcs://…, azblob://…, or a
    local directory path); empty/None unconfigures."""
    with _cfg_lock:
        _cfg["uri"] = (uri or "").strip()
        _cfg["options"] = dict(options or {})
        _cfg["store"] = None
        _cfg["prefix"] = ""


def configured() -> bool:
    with _cfg_lock:
        return bool(_cfg["uri"])


def _store_and_prefix():
    """→ (store, key_prefix). The store client is built once per
    configure() and shared — stores are stateless over HTTP."""
    with _cfg_lock:
        if not _cfg["uri"]:
            raise StorageError("cold tier not configured (storage.tiering_uri)")
        if _cfg["store"] is None:
            store, prefix = objstore.store_for(_cfg["uri"], _cfg["options"])
            _cfg["store"] = store
            _cfg["prefix"] = prefix.rstrip("/")
        return _cfg["store"], _cfg["prefix"]


def _object_key(vnode_id: int, file_id: int) -> str:
    _, prefix = _store_and_prefix()
    rel = f"vnode_{vnode_id}/f{file_id:06d}.tsm"
    return f"{prefix}/{rel}" if prefix else rel


# ---------------------------------------------------------------------------
# accounting — cnosdb_cold_tier_total{lane,reason}
# ---------------------------------------------------------------------------
_counts_lock = lockwatch.Lock("tiering.counters")
_counts: dict[tuple[str, str], int] = {}


def _count_cold(lane: str, reason: str, n: int = 1) -> None:
    with _counts_lock:
        _counts[(lane, reason)] = _counts.get((lane, reason), 0) + n


def cold_tier_snapshot() -> dict[tuple[str, str], int]:
    with _counts_lock:
        return dict(_counts)


def counters_reset() -> None:
    with _counts_lock:
        _counts.clear()


# ---------------------------------------------------------------------------
# block cache — fetched page ranges, keyed (object_key, page_offset) and
# LRU'd by dict reinsertion with a byte cap, like the coordinator's scan
# cache (parallel/coordinator.py _cache_store)
# ---------------------------------------------------------------------------
BLOCK_CACHE_MAX_BYTES = int(os.environ.get(
    "CNOSDB_COLD_BLOCK_CACHE_MAX_BYTES", 64 * 1024 * 1024))

_cache_lock = lockwatch.Lock("tiering.block_cache")
_cache: dict[tuple[str, int], bytes] = {}
_cache_bytes = 0


def _cache_get(key: str, offset: int) -> bytes | None:
    with _cache_lock:
        raw = _cache.pop((key, offset), None)
        if raw is not None:
            _cache[(key, offset)] = raw   # LRU: reinsert on hit
        return raw


def _cache_put(key: str, offset: int, raw: bytes) -> None:
    global _cache_bytes
    if len(raw) > BLOCK_CACHE_MAX_BYTES:
        return
    with _cache_lock:
        old = _cache.pop((key, offset), None)
        if old is not None:
            _cache_bytes -= len(old)
        _cache[(key, offset)] = raw
        _cache_bytes += len(raw)
        while _cache_bytes > BLOCK_CACHE_MAX_BYTES and _cache:
            oldest = next(iter(_cache))     # LRU head: first-inserted key
            _cache_bytes -= len(_cache.pop(oldest))


def block_cache_stats() -> dict:
    with _cache_lock:
        return {"entries": len(_cache), "bytes": _cache_bytes,
                "max_bytes": BLOCK_CACHE_MAX_BYTES}


def block_cache_clear() -> None:
    global _cache_bytes
    with _cache_lock:
        _cache.clear()
        _cache_bytes = 0


def _block_cache_reclaim(target_bytes: int) -> int:
    """Broker reclaim callback: shed LRU block-cache entries until
    `target_bytes` are freed — a lost block is just a re-fetch."""
    global _cache_bytes
    freed = 0
    with _cache_lock:
        while _cache and freed < target_bytes:
            oldest = next(iter(_cache))
            freed += len(_cache.pop(oldest))
        _cache_bytes = max(0, _cache_bytes - freed)
    return freed


def _register_block_cache_pool() -> None:
    # module-level cache, module-level (import-time) registration: the
    # memory-governance broker can shrink the cold block cache when the
    # node crosses its soft watermark
    from ..server import memory as _memory

    _memory.register_pool(
        "block_cache",
        usage_fn=lambda: block_cache_stats()["bytes"],
        reclaim=_block_cache_reclaim)


_register_block_cache_pool()


# ---------------------------------------------------------------------------
# per-vnode cold registry (cold.json)
# ---------------------------------------------------------------------------
_reg_lock = lockwatch.Lock("tiering.registry")
_registry: dict[str, tuple[float, dict[int, dict]]] = {}   # dir → (mtime, map)


def _registry_path(dir_path: str) -> str:
    return os.path.join(dir_path, REGISTRY_NAME)


def cold_map(dir_path: str) -> dict[int, dict]:
    """file_id → {key, size, tail_off} for one vnode dir; {} when the
    vnode has no cold files. mtime-validated cache — tier/rehydrate go
    through _registry_mutate which rewrites the file atomically."""
    path = _registry_path(dir_path)
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return {}
    with _reg_lock:
        hit = _registry.get(dir_path)
        if hit is not None and hit[0] == mtime:
            return hit[1]
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        m = {int(fid): e for fid, e in raw.get("files", {}).items()}
    except (OSError, ValueError) as e:
        # a registry that exists but does not parse must be LOUD: treating
        # it as empty would drop every cold file from scans and let the
        # next _registry_mutate rewrite cold.json without them — silent
        # data loss (found by the crash-point sweep's torn-registry arm).
        # TsmError rides the coordinator's recover-and-retry path, where
        # recover_vnode() rebuilds the registry from the local sidecars.
        _count_cold("registry", "unreadable")
        stages.count_error("tiering.registry")
        raise TsmError(f"cold registry unreadable (rebuild via "
                       f"recover_vnode): {path}: {e}") from e
    with _reg_lock:
        _registry[dir_path] = (mtime, m)
    return m


def cold_entry(dir_path: str, file_id: int) -> dict | None:
    return cold_map(dir_path).get(file_id)


def cold_ids(dir_path: str) -> frozenset[int]:
    return frozenset(cold_map(dir_path))


def cold_objects(dir_path: str) -> list[str]:
    """The tiering-store object keys a vnode's cold files reference. The
    DR manifest (storage/backup.py) records these as referenced-not-
    copied: a restored vnode keeps reading the SAME tiering objects
    through the cold.json it restored (entries carry full keys, so a
    restore onto a different vnode id still resolves them), which keeps
    backups incremental over cold data."""
    return sorted(e["key"] for e in cold_map(dir_path).values())


def _registry_write(dir_path: str, m: dict[int, dict]) -> None:
    """Install a full registry image atomically (tmp + fsync + rename).
    The `tiering.registry` fault point sits between the durable tmp and
    the rename — `crash` there leaves the OLD registry intact (atomicity
    witness), `torn(n)` installs a truncated image (bit-rot model that
    cold_map now refuses loudly instead of reading as empty)."""
    path = _registry_path(dir_path)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"files": {str(fid): e for fid, e in sorted(m.items())}}, f)
        f.flush()
        os.fsync(f.fileno())
    torn = False
    if faults.ENABLED:
        hit = faults.fire("tiering.registry", dir=dir_path, path=path)
        if hit is not None and hit[0] == "torn":
            with open(tmp, "r+b") as tf:
                tf.truncate(int(hit[1] or 8))
            torn = True
    os.replace(tmp, path)
    with _reg_lock:
        if torn:
            # the on-disk image is damaged: caching the good in-memory
            # map would mask the tear from this very process and defer
            # discovery to the next boot — drop the entry so the next
            # read hits the disk image and the recover path
            _registry.pop(dir_path, None)
        else:
            _registry[dir_path] = (os.stat(path).st_mtime_ns, m)


def _registry_mutate(dir_path: str, file_id: int, entry: dict | None) -> None:
    """Add (entry != None) or remove one cold record, atomically. Callers
    hold the vnode lock, serializing mutators."""
    m = dict(cold_map(dir_path))
    if entry is None:
        m.pop(file_id, None)
    else:
        m[file_id] = entry
    _registry_write(dir_path, m)


# ---------------------------------------------------------------------------
# sidecar
# ---------------------------------------------------------------------------
def sidecar_path(data_path: str) -> str:
    base, _ = os.path.splitext(data_path)
    return base + SIDECAR_SUFFIX


def write_sidecar(data_path: str, orig_size: int, tail_off: int,
                  tail: bytes) -> str:
    side = sidecar_path(data_path)
    tmp = side + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_SIDECAR_HDR.pack(SIDECAR_MAGIC, SIDECAR_VERSION,
                                  orig_size, tail_off))
        f.write(tail)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, side)
    return side


def read_sidecar(data_path: str) -> tuple[int, int, bytes]:
    """→ (orig_size, tail_off, tail_bytes); raises TsmError on a missing
    or malformed sidecar (recover_vnode rebuilds it from the store)."""
    side = sidecar_path(data_path)
    try:
        with open(side, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        raise TsmError("sidecar missing", path=side)
    if len(raw) < _SIDECAR_HDR.size + FOOTER_SIZE:
        raise TsmError("sidecar too small", path=side)
    magic, ver, orig_size, tail_off = _SIDECAR_HDR.unpack_from(raw, 0)
    if magic != SIDECAR_MAGIC or ver != SIDECAR_VERSION:
        raise TsmError("bad sidecar magic", path=side)
    return orig_size, tail_off, raw[_SIDECAR_HDR.size:]


# ---------------------------------------------------------------------------
# cold reader
# ---------------------------------------------------------------------------
class ColdTsmReader(TsmReader):
    """Reader over a tiered TSM file: metadata parses from the local
    sidecar, page bytes fetch on demand via byte-range GETs through the
    block cache. Inherits every decode path from TsmReader — the device
    lane (`read_field_page_split`), the py lane (`read_time_page` /
    `read_field_page`) and the per-series fallbacks all route through
    `_read_page`. The *native* batch lane needs a whole-file mmap and is
    routed away by scan.py (`is_cold`)."""

    is_cold = True

    def __init__(self, data_path: str, key: str, size: int, tail_off: int,
                 store=None):
        # no super().__init__ — there is no local data file to mmap.
        # self.path keeps the logical hot path so ChecksumMismatch ctx /
        # quarantine-by-path keep their identity.
        self.path = data_path
        self.key = key
        self.size = int(size)
        self._f = None
        self._buf = b""
        self._store = store if store is not None else _store_and_prefix()[0]
        orig_size, side_tail_off, tail = read_sidecar(data_path)
        if orig_size != self.size:
            _count_cold("open", "sidecar_size_mismatch")
            raise TsmError("sidecar/registry size mismatch", path=data_path)
        self.tail_off = int(side_tail_off)
        self.groups, self.bloom, self.footer = parse_tail(
            tail, data_path, tail_off=self.tail_off)
        self.min_ts = self.footer.min_ts
        self.max_ts = self.footer.max_ts
        self.series_count = self.footer.series_count

    def close(self):
        self._buf_arr = None
        self._buf = b""

    def buffer_array(self):
        _count_cold("scan", "buffer_array_refused")
        raise StorageError(
            f"cold reader {self.path} has no local buffer — the native "
            f"batch lane must not be routed cold pages")

    # -- page fetch ------------------------------------------------------
    def fetch_pages(self, pms) -> int:
        """Ensure every page in `pms` is block-cached, coalescing adjacent
        ranges (gap ≤ COALESCE_GAP) into few range GETs. → bytes actually
        downloaded. This is the scan prefetch entry: one batched round of
        GETs for all admitted pages instead of a request per page."""
        want = []
        for pm in pms:
            if _cache_get(self.key, pm.offset) is None:
                want.append((pm.offset, pm.size))
        if not want:
            _count_cold("fetch", "prefetch_all_cached")
            return 0
        want.sort()
        ranges: list[list[int]] = []
        for off, size in want:
            if ranges and off - (ranges[-1][0] + ranges[-1][1]) \
                    <= COALESCE_GAP:
                ranges[-1][1] = off + size - ranges[-1][0]
            else:
                ranges.append([off, size])
        downloaded = 0
        with stages.stage("cold.fetch_ms"):
            for start, length in ranges:
                raw = self._store.get_range(self.key, start, length)
                downloaded += len(raw)
                for off, size in want:
                    if start <= off and off + size <= start + len(raw):
                        _cache_put(self.key, off,
                                   raw[off - start:off - start + size])
        stages.count("cold.range_gets", len(ranges))
        stages.count("cold.pages_fetched", len(want))
        stages.count("cold.bytes_downloaded", downloaded)
        _count_cold("fetch", "range_gets", len(ranges))
        _count_cold("fetch", "pages_fetched", len(want))
        _count_cold("fetch", "bytes_downloaded", downloaded)
        return downloaded

    def _page_raw(self, pm) -> bytes:
        raw = _cache_get(self.key, pm.offset)
        if raw is not None:
            _count_cold("cache", "hit")
            return raw
        _count_cold("cache", "miss")
        self.fetch_pages([pm])
        raw = _cache_get(self.key, pm.offset)
        if raw is not None:
            _count_cold("cache", "miss_filled")
            return raw
        # page larger than the whole cache: fetch uncached
        _count_cold("cache", "page_exceeds_cache")
        return self._store.get_range(self.key, pm.offset, pm.size)

    def _read_page(self, pm) -> bytes:
        raw = self._page_raw(pm)
        if len(raw) < 8:
            _count_cold("fetch", "page_truncated")
            raise ChecksumMismatch("page truncated", path=self.path,
                                   offset=pm.offset)
        plen, crc = struct.unpack_from("<II", raw, 0)
        payload = raw[8:8 + plen]
        if len(payload) < plen:
            _count_cold("fetch", "page_truncated")
            raise ChecksumMismatch("page truncated", path=self.path,
                                   offset=pm.offset)
        if zlib.crc32(payload) != crc:
            _count_cold("fetch", "page_crc_mismatch")
            raise ChecksumMismatch("page crc", path=self.path,
                                   offset=pm.offset)
        return payload


def open_cold_reader(data_path: str, entry: dict) -> ColdTsmReader:
    """summary.Version.reader's hook: build the cold reader for a manifest
    file whose id appears in cold.json."""
    return ColdTsmReader(data_path, entry["key"], entry["size"],
                         entry["tail_off"])


# ---------------------------------------------------------------------------
# tiering operations
# ---------------------------------------------------------------------------
def eligible_files(vnode, boundary_ns: int, min_level: int = 1) -> list:
    """Sealed files wholly older than `boundary_ns` that may tier: level
    ≥ min_level (L0 delta churn belongs to compaction), not already cold,
    and carrying no tombstone sidecar (pending deletes must rewrite
    locally first)."""
    version = vnode.summary.version
    cold = cold_ids(vnode.dir)
    out = []
    for fm in version.all_files():
        if fm.file_id in cold or fm.level < min_level:
            continue
        if fm.max_ts >= boundary_ns:
            continue
        if os.path.exists(tombstone_path(version.file_path(fm))):
            continue
        out.append(fm)
    return out


def tier_vnode(vnode, boundary_ns: int, limit: int | None = None) -> int:
    """Age every eligible sealed file of `vnode` into the object store.
    → number of files tiered. Uploads run outside the vnode lock; the
    registry flip + local unlink revalidate under it."""
    if not enabled():
        _count_cold("tier", "disabled")
        return 0
    store, _ = _store_and_prefix()
    n = 0
    try:
        for fm in eligible_files(vnode, boundary_ns):
            if limit is not None and n >= limit:
                _count_cold("tier", "limit_reached")
                return n
            if _tier_file(vnode, store, fm):
                n += 1
    finally:
        if n:
            _serving_invalidate(vnode)
    return n


def _serving_invalidate(vnode) -> None:
    """Tiering moved this vnode's bytes WITHOUT bumping data_version
    (deliberate: a tiered scan is bit-identical, so coordinator scan
    caches stay valid) — which means ScanToken revalidation cannot see
    the move, and this push eviction is the only thing that retires
    serving-plane entries now backed by cold storage. Losing it is still
    safe (a hit serves identical bytes), just unhygienic. The owner
    string is the vnode directory's parent name (engine layout
    data/<owner>/<id>)."""
    try:
        from ..server import serving

        owner = os.path.basename(os.path.dirname(vnode.dir))
        if "." in owner:
            serving.invalidate_owner(owner)
    except Exception:
        from ..utils import stages

        stages.count_error("serving.invalidate")


def _tier_file(vnode, store, fm) -> bool:
    version = vnode.summary.version
    path = version.file_path(fm)
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        _count_cold("tier", "file_vanished")
        return False
    if len(data) < FOOTER_SIZE + 5:
        _count_cold("tier", "file_malformed")
        return False
    # meta_off is the first u64 of the footer body — everything from it to
    # EOF (meta + bloom + footer) becomes the local skip-index sidecar
    (tail_off,) = struct.unpack_from("<Q", data, len(data) - FOOTER_SIZE)
    if not 5 <= tail_off <= len(data) - FOOTER_SIZE:
        _count_cold("tier", "file_malformed")
        return False
    key = _object_key(vnode.vnode_id, fm.file_id)
    store.put(key, data)                       # slow: outside the lock
    write_sidecar(path, len(data), tail_off, data[tail_off:])
    with vnode.lock:
        version = vnode.summary.version
        live = any(f2.file_id == fm.file_id for f2 in version.all_files())
        if not live:
            # compaction replaced the file mid-upload: the object + sidecar
            # are garbage; drop the sidecar, leave the object for purge
            _unlink_quiet(sidecar_path(path))
            _count_cold("tier", "file_vanished")
            return False
        _registry_mutate(vnode.dir, fm.file_id, {
            "key": key, "size": len(data), "tail_off": int(tail_off)})
        version.drop_reader(fm.file_id)
        _unlink_quiet(path)
    _count_cold("tier", "files_tiered")
    _count_cold("tier", "bytes_uploaded", len(data))
    return True


def rehydrate_file(vnode, file_id: int) -> bool:
    """Download a cold file back to its hot path (repair / un-tier): the
    object is bit-identical to the original, so this is a verify-and-
    rename. → True when the file is hot again."""
    entry = cold_entry(vnode.dir, file_id)
    if entry is None:
        _count_cold("rehydrate", "not_cold")
        return False
    store, _ = _store_and_prefix()
    data = store.get(entry["key"])
    if len(data) != entry["size"]:
        _count_cold("rehydrate", "size_mismatch")
        raise ChecksumMismatch("cold object size mismatch",
                               path=entry["key"])
    with vnode.lock:
        version = vnode.summary.version
        fm = next((f for f in version.all_files()
                   if f.file_id == file_id), None)
        if fm is None:
            _count_cold("rehydrate", "file_vanished")
            return False
        path = version.file_path(fm)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".rehydrate"
        with open(tmp, "wb") as f:  # lint: disable=lock-blocking (registry flip + data landing must be atomic vs readers)
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _registry_mutate(vnode.dir, file_id, None)
        version.drop_reader(file_id)
        _unlink_quiet(sidecar_path(path))
    _count_cold("rehydrate", "files_rehydrated")
    return True


def rehydrate_vnode(vnode) -> int:
    """Bring every cold file of `vnode` back to the hot tier (disaster
    repair: the object store acts as an extra replica source)."""
    n = 0
    for fid in sorted(cold_map(vnode.dir)):
        if rehydrate_file(vnode, fid):
            n += 1
    return n


def _rebuild_registry(vnode) -> int:
    """Inverse disaster path: cold.json torn/corrupt while the sidecars
    survived — reconstruct each entry from its sidecar header (size and
    tail_off live there; the object key is re-derived from vnode/file id)
    and install a fresh registry atomically. A file with neither a hot
    copy nor a parseable sidecar cannot be recovered locally and is left
    out (the scrubber's repair re-vote handles it from a replica).
    → entries rebuilt."""
    m: dict[int, dict] = {}
    with vnode.lock:
        version = vnode.summary.version
        for fm in version.all_files():
            path = version.file_path(fm)
            if os.path.exists(path):
                continue               # hot: was never (or no longer) cold
            try:
                size, tail_off, _tail = read_sidecar(path)
            except (TsmError, OSError):
                _count_cold("registry", "entry_unrecoverable")
                continue
            m[fm.file_id] = {"key": _object_key(vnode.vnode_id, fm.file_id),
                             "size": int(size), "tail_off": int(tail_off)}
        _registry_write(vnode.dir, m)
    _count_cold("registry", "entries_rebuilt", len(m))
    return len(m)


def recover_vnode(vnode) -> int:
    """Disaster path: local skip-index sidecars lost or corrupt while
    cold.json survived — re-fetch each tiered file's tail section from
    the object store and rebuild the sidecar. Metadata-only rehydration:
    page bytes stay cold. The mirror-image failure (cold.json torn,
    sidecars intact) is healed first via _rebuild_registry. → sidecars
    rebuilt."""
    if not configured():
        _count_cold("rehydrate", "not_configured")
        return 0
    store, _ = _store_and_prefix()
    healed = 0
    try:
        cold_map(vnode.dir)
    except TsmError:
        # counts toward the return value even when the fresh image is
        # empty: a registry-only heal (sidecars intact) is still a
        # recovery, and callers retrying a failed scan key off a truthy
        # result
        healed = max(1, _rebuild_registry(vnode))
    with vnode.lock:
        version = vnode.summary.version
        work = [(fm, cold_entry(vnode.dir, fm.file_id))
                for fm in version.all_files()]
    n = 0
    for fm, entry in work:
        if entry is None:
            continue
        path = version.file_path(fm)
        intact = False
        if os.path.exists(sidecar_path(path)):
            try:
                r = ColdTsmReader(path, entry["key"], entry["size"],
                                  entry["tail_off"], store)
                r.close()
                intact = True
            except (TsmError, ChecksumMismatch, OSError):
                intact = False      # malformed: rebuild below
        if intact:
            continue
        tail_off = int(entry["tail_off"])
        tail = store.get_range(entry["key"], tail_off,
                               int(entry["size"]) - tail_off)
        # validate before installing: parse_tail CRC-checks the footer
        parse_tail(tail, path, tail_off=tail_off)
        with vnode.lock:
            write_sidecar(path, int(entry["size"]), tail_off, tail)
            vnode.summary.version.drop_reader(fm.file_id)
        n += 1
    _count_cold("rehydrate", "sidecars_rebuilt", n)
    return n + healed


def verify_cold_file(vnode, file_id: int) -> int:
    """Scrub hook: cheap integrity pass over one tiered file — the local
    sidecar must parse, and the remote object must still answer a ranged
    footer read that matches the sidecar's footer bytes. → bytes verified
    (0 when the file is not/no longer cold); raises ChecksumMismatch on
    divergence."""
    entry = cold_entry(vnode.dir, file_id)
    if entry is None:
        _count_cold("scrub", "not_cold")
        return 0
    version = vnode.summary.version
    fm = next((f for f in version.all_files() if f.file_id == file_id), None)
    if fm is None:
        _count_cold("scrub", "file_vanished")
        return 0
    path = version.file_path(fm)
    try:
        _size, tail_off, tail = read_sidecar(path)
        parse_tail(tail, path, tail_off=tail_off)
    except TsmError as e:
        _count_cold("scrub", "sidecar_damaged")
        raise ChecksumMismatch(f"cold sidecar: {e}", path=path)
    store, _ = _store_and_prefix()
    remote_footer = store.get_range(entry["key"],
                                    int(entry["size"]) - FOOTER_SIZE,
                                    FOOTER_SIZE)
    if remote_footer != tail[-FOOTER_SIZE:]:
        _count_cold("scrub", "remote_footer_mismatch")
        raise ChecksumMismatch("cold object footer diverged from sidecar",
                               path=path)
    _count_cold("scrub", "cold_files_verified")
    return len(tail) + FOOTER_SIZE


def purge_vnode(dir_path: str) -> int:
    """Best-effort deletion of a dropped vnode's cold objects (the
    tier-then-expire path): the replica's objects are private to it, so
    dropping the vnode orphans them unless removed here."""
    m = cold_map(dir_path)
    if not m or not configured():
        _count_cold("purge", "nothing_to_purge")
        return 0
    store, _ = _store_and_prefix()
    n = 0
    for fid in sorted(m):
        try:
            store.delete(m[fid]["key"])
            n += 1
        except objstore.ObjectStoreError:
            _count_cold("purge", "delete_failed")
    _count_cold("purge", "objects_deleted", n)
    return n


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass   # already gone / racing cleanup: the manifest state holds


# ---------------------------------------------------------------------------
# background tiering job
# ---------------------------------------------------------------------------
class TieringJob:
    """Background aging daemon (server wiring mirrors the Scrubber): every
    `interval_s`, walk the engine's open vnodes and tier sealed files
    whose newest row is older than `cold_after_s`."""

    def __init__(self, engine, interval_s: float, cold_after_s: float,
                 on_error=None):
        self.engine = engine
        self.interval_s = float(interval_s)
        self.cold_after_s = float(cold_after_s)
        self.on_error = on_error
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _boundary_ns(self) -> int:
        # data timestamps ARE wall-clock ns; the age boundary must be too
        return time.time_ns() - int(self.cold_after_s * 1e9)

    def sweep_once(self) -> int:
        with self.engine.lock:
            vnodes = list(self.engine.vnodes.values())
        total = 0
        for v in vnodes:
            if self._stop.is_set():
                _count_cold("tier", "sweep_stopped")
                return total
            try:
                total += tier_vnode(v, self._boundary_ns())
            except (OSError, StorageError, objstore.ObjectStoreError) as e:
                _count_cold("tier", "sweep_error")
                if self.on_error is not None:
                    self.on_error(v, e)
        return total

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self.sweep_once()

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="tiering", daemon=True)
            self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
