"""Engine facade: the per-node storage service owning all local vnodes.

Role-parity with the reference's TsKv (tskv/src/kvcore.rs:35-406 — Engine
trait impl: open/write/flush/drop, background compaction) plus VersionSet
(version_set.rs): a registry of VnodeStorage keyed by (owner, vnode_id),
schema propagation from meta, and background flush/compaction driving.
"""
from __future__ import annotations

import os
import threading

from ..models.points import WriteBatch
from ..models.schema import TskvTableSchema
from .compaction import Picker
from .vnode import VnodeStorage
from ..utils import lockwatch


class TsKv:
    def __init__(self, data_dir: str,
                 memcache_bytes: int = 128 * 1024 * 1024,
                 wal_sync: bool = False,
                 picker: Picker | None = None,
                 background_compaction: bool = True):
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.memcache_bytes = memcache_bytes
        self.wal_sync = wal_sync
        self.picker = picker
        self.background_compaction = background_compaction
        self.lock = lockwatch.RLock("engine.registry")
        self.vnodes: dict[tuple[str, int], VnodeStorage] = {}
        self.schemas: dict[str, dict[str, TskvTableSchema]] = {}  # owner → tables
        # background workers drive compactions (reference CompactJob pool,
        # compaction/job.rs max_concurrent_compaction) so merges never sit
        # in the write path; per-vnode dedup + the vnode lock keep one
        # merge per vnode, workers parallelize ACROSS vnodes
        from concurrent.futures import ThreadPoolExecutor

        workers = max(1, min(4, (os.cpu_count() or 1) - 1) or 1)
        self._compactor = ThreadPoolExecutor(workers,
                                             thread_name_prefix="compact")
        self._compact_pending: set[tuple[str, int]] = set()
        # (owner, vnode_id) flush notifications — set by the materialized
        # rollup maintainer; must be cheap and non-blocking
        self.flush_listener = None
        # memory-governance plane: the memcache pool (active + immutable
        # caches = the unflushed-WAL rows) is reclaimed by flushing.
        # Registration is latest-wins, matching engine lifetime in-process.
        from ..server import memory as _memory

        _memory.register_pool("memcache",
                              usage_fn=self.memcache_bytes_used,
                              reclaim=self._reclaim_memcache)

    # ---------------------------------------------------------------- vnodes
    def vnode_dir(self, owner: str, vnode_id: int) -> str:
        return os.path.join(self.data_dir, "data", owner, str(vnode_id))

    def open_vnode(self, owner: str, vnode_id: int) -> VnodeStorage:
        with self.lock:
            key = (owner, vnode_id)
            v = self.vnodes.get(key)
            if v is None:
                v = VnodeStorage(
                    vnode_id, self.vnode_dir(owner, vnode_id),
                    schemas=self.schemas.setdefault(owner, {}),
                    memcache_bytes=self.memcache_bytes,
                    wal_sync=self.wal_sync,
                    picker=self.picker or Picker())
                v.on_flush = \
                    lambda o=owner, vid=vnode_id: self._notify_flush(o, vid)
                self.vnodes[key] = v
            return v

    def _notify_flush(self, owner: str, vnode_id: int):
        cb = self.flush_listener
        if cb is not None:
            cb(owner, vnode_id)

    def vnode(self, owner: str, vnode_id: int) -> VnodeStorage | None:
        v = self.vnodes.get((owner, vnode_id))
        if v is None and os.path.isdir(self.vnode_dir(owner, vnode_id)):
            return self.open_vnode(owner, vnode_id)
        return v

    def open_existing(self):
        """Reopen every vnode found on disk (node restart)."""
        base = os.path.join(self.data_dir, "data")
        if not os.path.isdir(base):
            return
        for owner in os.listdir(base):
            od = os.path.join(base, owner)
            if not os.path.isdir(od):
                continue
            for vid in os.listdir(od):
                if vid.isdigit():
                    self.open_vnode(owner, int(vid))

    def local_vnodes(self, owner: str) -> list[VnodeStorage]:
        """Every vnode of `owner`, including ones not yet opened this
        process (lazily opened from disk) — admin ops like drop/delete must
        reach on-disk vnodes, not just in-memory ones."""
        with self.lock:
            od = os.path.join(self.data_dir, "data", owner)
            if os.path.isdir(od):
                for vid in os.listdir(od):
                    if vid.isdigit() and (owner, int(vid)) not in self.vnodes:
                        self.open_vnode(owner, int(vid))
            return [v for (o, _), v in self.vnodes.items() if o == owner]

    # ---------------------------------------------------------------- schema
    def set_table_schema(self, owner: str, schema: TskvTableSchema):
        self.schemas.setdefault(owner, {})[schema.name] = schema

    def remove_table_schema(self, owner: str, table: str):
        self.schemas.get(owner, {}).pop(table, None)

    # ---------------------------------------------------------------- ops
    def write(self, owner: str, vnode_id: int, batch: WriteBatch,
              sync: bool = False) -> int:
        v = self.open_vnode(owner, vnode_id)
        seq = v.write(batch, sync=sync)
        if self.background_compaction:
            self._maybe_schedule_compact(owner, vnode_id, v)
        return seq

    def _maybe_schedule_compact(self, owner: str, vnode_id: int,
                                v: VnodeStorage):
        # cheap L0 check inline; the merge itself runs on the worker.
        # Either enough small files piled up, or a flush-sized file is
        # ready for the rewrite-free L1 promotion
        version = v.summary.version
        l0 = version.levels[0]
        promo_ready = False
        if l0:
            # mirror pick_promotions' oldest-first prefix + id rule — a
            # promote-sized file stuck behind a small older one must not
            # resubmit a guaranteed-no-op job on every write
            oldest = min(l0.values(), key=lambda f: f.file_id)
            promo_ready = (oldest.size >= v.picker.promote_file_size
                           and oldest.file_id
                           > max(version.levels[1], default=0))
        if len(l0) < v.picker.l0_trigger and not promo_ready:
            return
        key = (owner, vnode_id)
        with self.lock:
            if key in self._compact_pending:
                return
            self._compact_pending.add(key)

        def run():
            try:
                v.compact_full()
            finally:
                with self.lock:
                    self._compact_pending.discard(key)

        self._compactor.submit(run)

    def memcache_bytes_used(self) -> int:
        """Unflushed bytes across every open vnode (active + immutable
        caches) — the memcache pool's usage feed. Dirty read by design:
        a write racing this sum skews one broker sample, never a
        result."""
        total = 0
        for v in list(self.vnodes.values()):
            caches = [v.active, *v.immutables]
            total += sum(c.approx_bytes for c in caches)
        return total

    def _reclaim_memcache(self, target_bytes: int) -> int:
        """Broker reclaim callback: flush the fattest vnodes until
        `target_bytes` have been persisted (or nothing is left). Runs on
        whichever thread crossed the watermark — flushing inline IS the
        backpressure."""
        before = self.memcache_bytes_used()
        with self.lock:
            victims = sorted(
                self.vnodes.values(),
                key=lambda v: sum(c.approx_bytes
                                  for c in [v.active, *v.immutables]),
                reverse=True)
        freed = 0
        for v in victims:
            if freed >= target_bytes:
                break
            v.flush(sync=False)
            freed = before - self.memcache_bytes_used()
        return max(0, freed)

    def flush_all(self, sync: bool = True):
        with self.lock:
            for v in self.vnodes.values():
                v.flush(sync=sync)

    def compact_all(self):
        """User-triggered COMPACT: full (major) compaction per vnode."""
        with self.lock:
            for v in self.vnodes.values():
                v.compact_major()

    def drop_table(self, owner: str, table: str):
        for v in self.local_vnodes(owner):
            v.drop_table(table)
        self.remove_table_schema(owner, table)

    def drop_database(self, owner: str):
        import shutil

        with self.lock:
            for key in [k for k in self.vnodes if k[0] == owner]:
                self.vnodes[key].close()
                del self.vnodes[key]
            self.schemas.pop(owner, None)
            d = os.path.join(self.data_dir, "data", owner)
            if os.path.isdir(d):
                from . import tiering

                for name in os.listdir(d):
                    tiering.purge_vnode(os.path.join(d, name))
                shutil.rmtree(d, ignore_errors=True)

    def close_database(self, owner: str):
        """Release a database's vnodes WITHOUT touching disk (soft DROP:
        files stay for RECOVER; purge later hard-deletes)."""
        with self.lock:
            for key in [k for k in self.vnodes if k[0] == owner]:
                self.vnodes[key].close()
                del self.vnodes[key]
            self.schemas.pop(owner, None)

    def drop_vnode(self, owner: str, vnode_id: int,
                   purge_cold: bool = False):
        """`purge_cold` also deletes the vnode's cold-tier objects
        (best-effort) — the tier-then-expire path: TTL tiers data first,
        then the drop reclaims both local disk and the object store."""
        import shutil

        with self.lock:
            key = (owner, vnode_id)
            v = self.vnodes.pop(key, None)
            if v:
                v.close()
            d = self.vnode_dir(owner, vnode_id)
            if purge_cold and os.path.isdir(d):
                from . import tiering

                tiering.purge_vnode(d)
            if os.path.isdir(d):
                shutil.rmtree(d, ignore_errors=True)

    def close(self):
        self._compactor.shutdown(wait=True)
        with self.lock:
            for v in self.vnodes.values():
                v.close()
            self.vnodes.clear()
