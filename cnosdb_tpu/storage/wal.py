"""Write-ahead log — also the replication log.

Mirrors the reference's WAL-is-the-raft-log design (tskv/src/wal/
wal_store.rs:22-150 RaftEntryStorage over wal files; recover :429): one WAL
per vnode, made of numbered segment files of CRC records. Entries carry a
monotonically increasing sequence; recovery replays entries with
seq > flushed watermark. The replication layer stores its raft entries
through this same API, so there is exactly one durable log per vnode.

Entry record layout (inside a record-file payload):
    seq u64 | entry_type u8 | term u64 | ts u64 | data...

`term` is 0 for unreplicated vnodes; the raft layer stores its term here so
one durable log serves both recovery paths. `ts` is the wall-clock append
time in ns — the disaster-recovery plane (storage/backup.py) replays
archived entries "up to TIMESTAMP T" by this stamp, so it rides every
entry rather than living in a side channel.
"""
from __future__ import annotations

import os
import re
import struct
import time
from dataclasses import dataclass

from .. import faults
from ..utils import stages
from ..errors import WalError
from .record_file import FILE_MAGIC, RecordReader, RecordWriter

SEGMENT_PATTERN = re.compile(r"^wal_(\d{10})\.log$")
_ENTRY_HDR = struct.Struct("<QBQQ")

faults.register_point("wal.append", __name__,
                      desc="WAL entry append (torn-tail site)")
faults.register_point("wal.sync", __name__, desc="WAL fsync")
faults.register_point("wal.roll", __name__, desc="WAL segment roll")


class WalEntryType:
    WRITE = 1          # point write batch
    DELETE_TABLE = 2
    DELETE_SERIES = 3
    UPDATE_TAGS = 4
    RAFT_BLANK = 5     # raft no-op/membership entries
    RAFT_MEMBERSHIP = 6
    DELETE_TIME_RANGE = 7


@dataclass
class WalEntry:
    seq: int
    entry_type: int
    data: bytes
    term: int = 0
    ts: int = 0          # wall-clock append time, ns (PITR replay bound)

    def encode(self) -> bytes:
        return _ENTRY_HDR.pack(self.seq, self.entry_type, self.term,
                               self.ts) + self.data

    @classmethod
    def decode(cls, payload: bytes) -> "WalEntry":
        seq, et, term, ts = _ENTRY_HDR.unpack_from(payload, 0)
        return cls(seq, et, payload[_ENTRY_HDR.size:], term, ts)


class Wal:
    """Segmented WAL for one vnode."""

    def __init__(self, dir_path: str, max_segment_size: int = 64 * 1024 * 1024,
                 sync_on_append: bool = False):
        self.dir = dir_path
        self.max_segment_size = max_segment_size
        self.sync_on_append = sync_on_append
        os.makedirs(dir_path, exist_ok=True)
        self._segments: list[int] = self._list_segments()
        self._next_seq = 1
        self._min_seq = 1
        self._writer: RecordWriter | None = None
        self.purge_listeners: list = []  # called with (seq) after purge_to
        # DR hooks (storage/backup.py): seal_listeners fire with the
        # sealed segment id after every roll (archive trigger);
        # archive_fence(seg_id)->bool gates purge_to so GC can never
        # outrun the archived watermark. Both default to seed behavior.
        self.seal_listeners: list = []
        self.archive_fence = None
        if self._segments:
            entries = list(self.replay())
            if entries:
                self._min_seq = entries[0].seq
                self._next_seq = entries[-1].seq + 1
        # Sequences must never restart below a previously handed-out seq even
        # when every segment holding them has been purged (roll + purge_to can
        # leave only an empty active segment). A durable tail marker records
        # the high-water next_seq; on open we take the max of replayed tail
        # and marker so post-restart appends stay above the flushed watermark.
        marker = self._read_tail_marker()
        if marker > self._next_seq:
            self._next_seq = marker
            self._min_seq = max(self._min_seq, marker)
        self._open_writer()

    # -- tail marker ------------------------------------------------------
    @property
    def _tail_path(self) -> str:
        return os.path.join(self.dir, "wal.tail")

    def _read_tail_marker(self) -> int:
        try:
            with open(self._tail_path, "rb") as f:
                return struct.unpack("<Q", f.read(8))[0]
        except Exception:
            return 1

    def _persist_tail_marker(self):
        tmp = self._tail_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(struct.pack("<Q", self._next_seq))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._tail_path)

    # -- segments --------------------------------------------------------
    def _list_segments(self) -> list[int]:
        out = []
        for name in os.listdir(self.dir):
            m = SEGMENT_PATTERN.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _seg_path(self, seg_id: int) -> str:
        return os.path.join(self.dir, f"wal_{seg_id:010d}.log")

    def _open_writer(self):
        if not self._segments:
            self._segments.append(0)
        self._writer = RecordWriter(self._seg_path(self._segments[-1]))

    def _roll(self):
        if faults.ENABLED:
            faults.fire("wal.roll", dir=self.dir)
        self._writer.close()
        self._persist_tail_marker()
        sealed = self._segments[-1]
        self._segments.append(sealed + 1)
        self._writer = RecordWriter(self._seg_path(self._segments[-1]))
        # archive trigger: a failed upload must never fail the write path
        # (catch_up() re-archives later); crash-action faults still fire
        for cb in self.seal_listeners:
            try:
                cb(sealed)
            except Exception:
                stages.count_error("swallow.wal.seal_listener")

    def seal_active(self) -> int | None:
        """Force-roll the active segment so its entries become archivable
        (BACKUP's consistency cut). → sealed segment id, or None when the
        active segment holds no entries."""
        if self._writer is None or self._writer.size <= len(FILE_MAGIC):
            return None
        sealed = self._segments[-1]
        self._roll()
        return sealed

    # -- append/replay ---------------------------------------------------
    @property
    def next_seq(self) -> int:
        return self._next_seq

    @property
    def min_seq(self) -> int:
        return self._min_seq

    def append(self, entry_type: int, data: bytes, seq: int | None = None,
               term: int = 0) -> int:
        """Append one entry; returns its seq. Explicit `seq` is used by the
        replication layer (raft log index); it must be >= current tail."""
        if seq is None:
            seq = self._next_seq
        elif seq < self._next_seq:
            # raft log truncation-on-conflict: drop tail entries >= seq first
            self.truncate_from(seq)
        if faults.ENABLED:
            faults.fire("wal.append", dir=self.dir, seq=seq,
                        entry_type=entry_type)
        e = WalEntry(seq, entry_type, data, term, time.time_ns())
        self._writer.append(e.encode())
        if self.sync_on_append:
            self._writer.sync()
        self._next_seq = seq + 1
        if self._writer.size >= self.max_segment_size:
            self._roll()
        return seq

    def sync(self):
        if faults.ENABLED:
            faults.fire("wal.sync", dir=self.dir)
        if self._writer:
            self._writer.sync()

    def replay(self, from_seq: int = 0):
        """Yield entries with seq >= from_seq in log order.

        Later duplicates of a seq win (post-truncation re-appends)."""
        entries: dict[int, WalEntry] = {}
        tail_seq = 0
        for seg in self._list_segments():
            try:
                rr = RecordReader(self._seg_path(seg))
            except Exception:
                continue
            for payload in rr:
                e = WalEntry.decode(payload)
                if e.seq <= tail_seq:
                    # append at seq s after truncation invalidates all > s
                    # (rare path: only on post-conflict rewrites)
                    entries = {k: v for k, v in entries.items() if k < e.seq}
                entries[e.seq] = e
                tail_seq = e.seq
        for seq in sorted(entries):
            if seq >= from_seq:
                yield entries[seq]

    def truncate_from(self, seq: int):
        """Logical truncation of entries >= seq (raft conflict). Physical
        bytes stay; replay() honors the rewrite rule above."""
        if seq < self._min_seq:
            self._min_seq = seq
        self._next_seq = seq
        if self._read_tail_marker() > seq:
            self._persist_tail_marker()

    # -- GC --------------------------------------------------------------
    def purge_to(self, seq: int):
        """Drop whole segments whose entries are all < seq (post-flush GC,
        reference SnapshotPolicy purge multi_raft.rs:107-138)."""
        self._min_seq = max(self._min_seq, seq)
        self._persist_tail_marker()
        segs = self._list_segments()
        # Delete only segments provably below the watermark; unreadable
        # segments and everything after them are kept (log order matters),
        # as is the active segment. The archive fence additionally keeps
        # any segment not yet uploaded — and everything after it, since
        # deleting later segments around a retained one would tear the
        # archived log's order.
        for seg in segs[:-1]:
            if self.archive_fence is not None \
                    and not self._fence_allows(seg):
                break
            try:
                max_seq = 0
                for payload in RecordReader(self._seg_path(seg)):
                    max_seq = max(max_seq, WalEntry.decode(payload).seq)
            except Exception:
                break
            if max_seq >= seq:
                break
            os.unlink(self._seg_path(seg))
        for cb in self.purge_listeners:
            try:
                cb(seq)
            except Exception:
                stages.count_error("swallow.wal.purge_listener")

    def _fence_allows(self, seg: int) -> bool:
        """A fence that errors fails CLOSED (segment kept): dropping WAL
        bytes on an archiver hiccup is the exact data loss the fence
        exists to prevent."""
        try:
            return bool(self.archive_fence(seg))
        except Exception:
            stages.count_error("swallow.wal.archive_fence")
            return False

    def total_size(self) -> int:
        return sum(os.path.getsize(self._seg_path(s)) for s in self._list_segments())

    def close(self):
        if self._writer:
            self._writer.close()
            self._writer = None
            self._persist_tail_marker()
