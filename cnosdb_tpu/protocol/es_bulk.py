"""Elasticsearch-style `_bulk` JSON ingestion (log API).

Role-parity with the reference's ES bulk endpoint (common/protocol_parser/
src/json_protocol/ feeding the `_bulk` log API in http_service.rs): NDJSON
pairs of action metadata + document. Documents map to rows:
  - time: `time` / `@timestamp` / `timestamp` field (ISO string, ms, or ns)
  - keys named in `tag_keys` → tags; other strings → STRING fields;
    numbers → DOUBLE/BIGINT; bools → BOOLEAN.
"""
from __future__ import annotations

import json
import time as _time

from ..errors import ParserError
from ..models.points import SeriesRows, WriteBatch
from ..models.schema import ValueType
from ..models.series import SeriesKey, Tag
from ..sql.parser import parse_timestamp_string


def _doc_time(doc: dict) -> int:
    from ._time import normalize_ts_ns

    for k in ("time", "@timestamp", "timestamp"):
        if k in doc:
            v = doc.pop(k)
            if isinstance(v, str):
                return parse_timestamp_string(v)
            return normalize_ts_ns(v)
    return int(_time.time() * 1e9)


def parse_es_bulk(body: str, table: str = "logs",
                  tag_keys: tuple[str, ...] = ()) -> WriteBatch:
    lines = [l for l in body.splitlines() if l.strip()]
    groups: dict[tuple, dict] = {}
    i = 0
    while i < len(lines):
        try:
            meta = json.loads(lines[i])
        except json.JSONDecodeError as e:
            raise ParserError(f"bad bulk meta line {i + 1}: {e}")
        i += 1
        action = next(iter(meta), "index")
        if action in ("delete",):
            continue
        if i >= len(lines):
            raise ParserError("bulk action without document")
        try:
            doc = json.loads(lines[i])
        except json.JSONDecodeError as e:
            raise ParserError(f"bad bulk doc line {i + 1}: {e}")
        i += 1
        ts = _doc_time(doc)
        tags = {}
        fields = {}
        for k, v in doc.items():
            if k in tag_keys:
                tags[k] = str(v)
            elif isinstance(v, bool):
                fields[k] = (ValueType.BOOLEAN, v)
            elif isinstance(v, (int, float)):
                # JSON has one number type; ES and the reference's
                # json_protocol treat it as double — so do we (mixing 12
                # and 12.5 in one stream must not conflict)
                fields[k] = (ValueType.FLOAT, float(v))
            elif isinstance(v, str):
                fields[k] = (ValueType.STRING, v)
            else:
                fields[k] = (ValueType.STRING, json.dumps(v))
        key = tuple(sorted(tags.items()))
        g = groups.setdefault(key, {"tags": tags, "rows": []})
        g["rows"].append((ts, fields))
    # type-conflict check spans the WHOLE batch (not per series group): a
    # column's type is global to the table
    fnames: dict[str, ValueType] = {}
    for g in groups.values():
        for _, fs in g["rows"]:
            for n, (vt, _v) in fs.items():
                prev = fnames.setdefault(n, vt)
                if prev != vt:
                    raise ParserError(
                        f"field {n!r} type conflict in bulk batch: "
                        f"{prev.name} vs {vt.name}")
    wb = WriteBatch()
    for key, g in groups.items():
        ts_list = [r[0] for r in g["rows"]]
        fields = {}
        for n, vt in fnames.items():
            vals = [r[1].get(n, (None, None))[1] for r in g["rows"]]
            if any(v is not None for v in vals):
                fields[n] = (int(vt), vals)
        sk = SeriesKey(table, [Tag(k, v) for k, v in g["tags"].items()])
        wb.add_series(table, SeriesRows(sk, ts_list, fields))
    return wb
