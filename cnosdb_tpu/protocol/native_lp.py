"""ctypes bridge to the native line-protocol parser (native/lineproto.cpp).

The C++ parser mirrors the Python implementation exactly and rejects any
input it cannot prove it handles identically (exotic unicode whitespace,
overflowing literals, type conflicts) — `try_parse` then returns None and
the caller runs the Python path, which either parses or raises the
canonical ParserError. Success returns a WriteBatch whose timestamp and
fully-present numeric columns are typed numpy arrays — the zero-copy fast
ingest shape (models.points.SeriesRows array form).
"""
from __future__ import annotations

import ctypes
import struct

import numpy as np

from ..models.points import SeriesRows, WriteBatch
from ..models.schema import ValueType
from ..models.series import SeriesKey
from ..storage import native as _native

_CONFIGURED = False
_LP_OK = False


def _configure(lib) -> bool:
    global _CONFIGURED, _LP_OK
    if _CONFIGURED:
        return _LP_OK
    _CONFIGURED = True
    if lib is None or not all(
            hasattr(lib, s) for s in ("lp_parse", "lp_buf", "lp_size", "lp_free")):
        return False
    lib.lp_parse.restype = ctypes.c_void_p
    lib.lp_parse.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                             ctypes.c_longlong, ctypes.c_longlong,
                             ctypes.c_char_p, ctypes.c_size_t]
    lib.lp_buf.restype = ctypes.c_void_p
    lib.lp_buf.argtypes = [ctypes.c_void_p]
    lib.lp_size.restype = ctypes.c_size_t
    lib.lp_size.argtypes = [ctypes.c_void_p]
    lib.lp_free.restype = None
    lib.lp_free.argtypes = [ctypes.c_void_p]
    _LP_OK = True
    return True


def available() -> bool:
    return _configure(_native.get_lib())


def try_parse(text: str, default_ts: int, factor: int) -> WriteBatch | None:
    """Parse via the native library; None = caller must use the Python path
    (library unavailable, or input outside the native parser's proven set)."""
    lib = _native.get_lib()
    if not _configure(lib):
        return None
    raw = text.encode()
    err = ctypes.create_string_buffer(160)
    h = lib.lp_parse(raw, len(raw), default_ts, factor, err, len(err))
    if not h:
        return None
    try:
        buf = ctypes.string_at(lib.lp_buf(h), lib.lp_size(h))
    finally:
        lib.lp_free(h)
    try:
        return _decode(buf)
    except Exception:
        return None  # malformed meta walk: the Python path is canonical


def _decode(buf: bytes) -> WriteBatch:
    total, data_base = struct.unpack_from("<QQ", buf, 0)
    off = 16
    (n_groups,) = struct.unpack_from("<I", buf, off)
    off += 4
    wb = WriteBatch()
    for _ in range(n_groups):
        measurement, off = _str16(buf, off)
        (n_tags,) = struct.unpack_from("<H", buf, off)
        off += 2
        tags = []
        for _ in range(n_tags):
            k, off = _str16(buf, off)
            v, off = _str16(buf, off)
            tags.append((k, v))
        n_rows, ts_rel = struct.unpack_from("<IQ", buf, off)
        off += 12
        ts = np.frombuffer(buf, np.int64, n_rows, offset=data_base + ts_rel).copy()
        (n_fields,) = struct.unpack_from("<H", buf, off)
        off += 2
        fields = {}
        for _ in range(n_fields):
            name, off = _str16(buf, off)
            vt, missing, data_rel, present_rel = struct.unpack_from("<BBQQ", buf, off)
            off += 18
            base = data_base + data_rel
            if vt == ValueType.STRING:
                offs = np.frombuffer(buf, np.uint32, n_rows + 1, offset=base)
                blob_base = base + 4 * (n_rows + 1)
                mv = memoryview(buf)
                vals = [str(mv[blob_base + offs[r]: blob_base + offs[r + 1]], "utf-8")
                        for r in range(n_rows)]
                if missing:
                    present = np.frombuffer(buf, np.uint8, n_rows,
                                            offset=data_base + present_rel)
                    vals = [v if p else None for v, p in zip(vals, present)]
            else:
                if vt == ValueType.FLOAT:
                    arr = np.frombuffer(buf, np.float64, n_rows, offset=base)
                elif vt == ValueType.UNSIGNED:
                    arr = np.frombuffer(buf, np.int64, n_rows, offset=base).view(np.uint64)
                else:  # INTEGER / BOOLEAN ride as i64
                    arr = np.frombuffer(buf, np.int64, n_rows, offset=base)
                if vt == ValueType.BOOLEAN:
                    arr = arr != 0
                if missing:
                    present = np.frombuffer(buf, np.uint8, n_rows,
                                            offset=data_base + present_rel)
                    obj = arr.astype(object)
                    obj[present == 0] = None
                    vals = obj.tolist()
                else:
                    vals = arr.copy()
            fields[name] = (int(vt), vals)
        sk = SeriesKey(measurement, tags)
        wb.add_series(measurement, SeriesRows(sk, ts, fields))
    return wb


def _str16(buf: bytes, off: int) -> tuple[str, int]:
    (n,) = struct.unpack_from("<H", buf, off)
    off += 2
    return buf[off:off + n].decode(), off + n
