"""Prometheus remote-write ingestion.

Role-parity with the reference's prom remote server (query_server/query/
src/prom/remote_server.rs:478): snappy-compressed protobuf WriteRequest →
point writes. Snappy rides the system libsnappy via ctypes (no Python
binding in the environment); the prompb WriteRequest subset is decoded
directly from the protobuf wire format (varint/length-delimited) — the
message shape is tiny and stable:

    WriteRequest { repeated TimeSeries timeseries = 1; }
    TimeSeries   { repeated Label labels = 1; repeated Sample samples = 2; }
    Label        { string name = 1; string value = 2; }
    Sample       { double value = 1; int64 timestamp = 2; }  # ms
"""
from __future__ import annotations

import ctypes
import ctypes.util
import struct

from ..errors import ParserError
from ..models.points import SeriesRows, WriteBatch
from ..models.schema import ValueType
from ..models.series import SeriesKey, Tag

_snappy = None
_snappy_tried = False


def _get_snappy():
    global _snappy, _snappy_tried
    if _snappy is not None or _snappy_tried:
        return _snappy
    _snappy_tried = True
    path = ctypes.util.find_library("snappy") or "libsnappy.so.1"
    try:
        lib = ctypes.CDLL(path)
        lib.snappy_uncompressed_length.restype = ctypes.c_int
        lib.snappy_uncompressed_length.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_size_t)]
        lib.snappy_uncompress.restype = ctypes.c_int
        lib.snappy_uncompress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_size_t)]
        _snappy = lib
    except OSError:
        _snappy = None
    return _snappy


def snappy_available() -> bool:
    return _get_snappy() is not None


def snappy_compress(data: bytes) -> bytes:
    """Used by tests and the remote-read response path."""
    lib = _get_snappy()
    if lib is None:
        raise ParserError("snappy library unavailable")
    lib.snappy_max_compressed_length.restype = ctypes.c_size_t
    lib.snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
    lib.snappy_compress.restype = ctypes.c_int
    lib.snappy_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_size_t)]
    max_len = lib.snappy_max_compressed_length(len(data))
    buf = ctypes.create_string_buffer(max_len)
    n = ctypes.c_size_t(max_len)
    if lib.snappy_compress(data, len(data), buf, ctypes.byref(n)) != 0:
        raise ParserError("snappy compress failed")
    return buf.raw[:n.value]


def snappy_uncompress(data: bytes) -> bytes:
    lib = _get_snappy()
    if lib is None:
        raise ParserError("snappy library unavailable")
    out_len = ctypes.c_size_t()
    if lib.snappy_uncompressed_length(data, len(data), ctypes.byref(out_len)) != 0:
        raise ParserError("bad snappy frame")
    buf = ctypes.create_string_buffer(out_len.value)
    n = ctypes.c_size_t(out_len.value)
    if lib.snappy_uncompress(data, len(data), buf, ctypes.byref(n)) != 0:
        raise ParserError("snappy decompress failed")
    return buf.raw[:n.value]


# ---------------------------------------------------------------------------
# minimal protobuf wire decoding
# ---------------------------------------------------------------------------
def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7
        if shift > 70:
            raise ParserError("varint overflow")


def _fields(buf: bytes):
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field_no, wire = key >> 3, key & 7
        if wire == 0:       # varint
            v, i = _read_varint(buf, i)
            yield field_no, v
        elif wire == 1:     # 64-bit
            if i + 8 > n:
                raise ParserError("truncated fixed64 field")
            v = buf[i:i + 8]
            i += 8
            yield field_no, v
        elif wire == 2:     # length-delimited
            ln, i = _read_varint(buf, i)
            if i + ln > n:
                raise ParserError("truncated length-delimited field")
            v = buf[i:i + ln]
            i += ln
            yield field_no, v
        elif wire == 5:     # 32-bit
            if i + 4 > n:
                raise ParserError("truncated fixed32 field")
            v = buf[i:i + 4]
            i += 4
            yield field_no, v
        else:
            raise ParserError(f"unsupported wire type {wire}")


def parse_remote_write(body: bytes, compressed: bool = True) -> WriteBatch:
    raw = snappy_uncompress(body) if compressed else body
    wb = WriteBatch()
    for fno, ts_raw in _fields(raw):
        if fno != 1:
            continue
        labels = {}
        samples = []
        for f2, v in _fields(ts_raw):
            if f2 == 1:
                name = value = ""
                for f3, lv in _fields(v):
                    if f3 == 1:
                        name = lv.decode()
                    elif f3 == 2:
                        value = lv.decode()
                labels[name] = value
            elif f2 == 2:
                val = 0.0
                ts_ms = 0
                for f3, sv in _fields(v):
                    if f3 == 1:
                        val = struct.unpack("<d", sv)[0]
                    elif f3 == 2:
                        ts_ms = sv if isinstance(sv, int) else 0
                samples.append((_zig_int64(ts_ms), val))
        metric = labels.pop("__name__", None)
        if not metric or not samples:
            continue
        key = SeriesKey(metric, [Tag(k, v) for k, v in labels.items()])
        ts_list = [s[0] * 1_000_000 for s in samples]  # ms → ns
        vals = [s[1] for s in samples]
        wb.add_series(metric, SeriesRows(
            key, ts_list, {"value": (int(ValueType.FLOAT), vals)}))
    return wb


def _zig_int64(v: int) -> int:
    """protobuf int64 arrives as two's-complement varint (not zigzag)."""
    if v >= 1 << 63:
        v -= 1 << 64
    return v


# ---------------------------------------------------------------------------
# remote READ (reference prom/remote_server.rs:478 remote_read): hand-rolled
# prompb ReadRequest decode + ReadResponse encode, mirroring the write path
# ---------------------------------------------------------------------------
MATCH_EQ, MATCH_NEQ, MATCH_RE, MATCH_NRE = 0, 1, 2, 3


def parse_read_request(body: bytes, compressed: bool = True) -> list[dict]:
    """→ [{"start_ms", "end_ms", "matchers": [(type, name, value)]}]"""
    raw = snappy_uncompress(body) if compressed else body
    queries = []
    for fno, q_raw in _fields(raw):
        if fno != 1:
            continue
        q = {"start_ms": 0, "end_ms": 0, "matchers": []}
        for f2, v in _fields(q_raw):
            if f2 == 1:
                q["start_ms"] = _zig_int64(v)
            elif f2 == 2:
                q["end_ms"] = _zig_int64(v)
            elif f2 == 3:
                mtype, name, value = MATCH_EQ, "", ""
                for f3, mv in _fields(v):
                    if f3 == 1:
                        mtype = mv
                    elif f3 == 2:
                        name = mv.decode()
                    elif f3 == 3:
                        value = mv.decode()
                q["matchers"].append((mtype, name, value))
        queries.append(q)
    return queries


def _w_varint(out: bytearray, v: int):
    if v < 0:
        v += 1 << 64
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _w_tag(out: bytearray, field_no: int, wire: int):
    _w_varint(out, (field_no << 3) | wire)


def _w_bytes(out: bytearray, field_no: int, raw: bytes):
    _w_tag(out, field_no, 2)
    _w_varint(out, len(raw))
    out += raw


def encode_read_response(per_query: list[list[tuple[dict, list]]],
                         compress: bool = True) -> bytes:
    """per_query: for each query, a list of (labels dict, [(ts_ms, value)])
    series → snappy'd prompb ReadResponse."""
    out = bytearray()
    for series_list in per_query:
        qr = bytearray()
        for labels, samples in series_list:
            ts_msg = bytearray()
            for name in sorted(labels):
                lbl = bytearray()
                _w_bytes(lbl, 1, name.encode())
                _w_bytes(lbl, 2, str(labels[name]).encode())
                _w_bytes(ts_msg, 1, bytes(lbl))
            for ts_ms, val in samples:
                smp = bytearray()
                _w_tag(smp, 1, 1)
                smp += struct.pack("<d", float(val))
                _w_tag(smp, 2, 0)
                _w_varint(smp, int(ts_ms))
                _w_bytes(ts_msg, 2, bytes(smp))
            _w_bytes(qr, 1, bytes(ts_msg))
        _w_bytes(out, 1, bytes(qr))
    raw = bytes(out)
    return snappy_compress(raw) if compress else raw
