"""OpenTSDB telnet `put` protocol parser.

Role-parity with common/protocol_parser/src/open_tsdb/: lines of
`put <metric> <ts> <value> tag=v ...` → WriteBatch (field name "value",
timestamps auto-scaled: seconds or milliseconds accepted like the
reference).
"""
from __future__ import annotations

from ..errors import ParserError
from ._time import normalize_ts_ns
from ..models.points import SeriesRows, WriteBatch
from ..models.schema import ValueType
from ..models.series import SeriesKey, Tag


def parse_opentsdb(text: str, precision=None) -> WriteBatch:
    """`precision` (a models.schema.Precision), when given, fixes the
    timestamp unit explicitly (the reference's write APIs take a
    precision parameter); otherwise seconds/milliseconds are
    auto-detected like the reference telnet service."""
    groups: dict[tuple[str, tuple], dict] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        parts = line.split()
        if parts[0] == "put":
            parts = parts[1:]
        if len(parts) < 3:
            raise ParserError(f"opentsdb line {lineno}: too few fields")
        metric, ts_s, val_s = parts[0], parts[1], parts[2]
        tags = {}
        for kv in parts[3:]:
            k, _, v = kv.partition("=")
            if not _:
                raise ParserError(f"opentsdb line {lineno}: bad tag {kv!r}")
            tags[k] = v
        try:
            ts = int(ts_s)
        except ValueError:
            raise ParserError(f"opentsdb line {lineno}: bad timestamp {ts_s!r}")
        ts = _scale_ts(ts, precision)
        try:
            val = float(val_s)
        except ValueError:
            raise ParserError(f"opentsdb line {lineno}: bad value {val_s!r}")
        _append(groups, metric, tags, ts, val)
    return _to_batch(groups)


def parse_opentsdb_json(text: str, precision=None) -> WriteBatch:
    """OpenTSDB JSON put bodies (reference open_tsdb json parser):
    one datapoint object or an array of them —
    {"metric": ..., "timestamp": ..., "value": ..., "tags": {...}}."""
    import json

    try:
        doc = json.loads(text)
    except ValueError as e:
        raise ParserError(f"opentsdb json: {e}")
    if isinstance(doc, dict):
        doc = [doc]
    if not isinstance(doc, list):
        raise ParserError("opentsdb json: expected object or array")
    groups: dict[tuple[str, tuple], dict] = {}
    for i, dp in enumerate(doc):
        if not isinstance(dp, dict):
            raise ParserError(f"opentsdb json datapoint {i}: not an object")
        try:
            metric = str(dp["metric"])
            ts = int(dp["timestamp"])
            val = float(dp["value"])
        except (KeyError, TypeError, ValueError) as e:
            raise ParserError(f"opentsdb json datapoint {i}: {e}")
        tags = {str(k): str(v) for k, v in (dp.get("tags") or {}).items()}
        _append(groups, metric, tags, _scale_ts(ts, precision), val)
    return _to_batch(groups)


def _scale_ts(ts: int, precision) -> int:
    if precision is None:
        return normalize_ts_ns(ts)
    return ts * precision.to_ns_factor()


def _append(groups: dict, metric: str, tags: dict, ts: int, val: float):
    key = (metric, tuple(sorted(tags.items())))
    g = groups.setdefault(key, {"tags": tags, "ts": [], "vals": []})
    g["ts"].append(ts)
    g["vals"].append(val)


def _to_batch(groups: dict) -> WriteBatch:
    wb = WriteBatch()
    for (metric, _), g in groups.items():
        sk = SeriesKey(metric, [Tag(k, v) for k, v in g["tags"].items()])
        wb.add_series(metric, SeriesRows(
            sk, g["ts"], {"value": (int(ValueType.FLOAT), g["vals"])}))
    return wb
