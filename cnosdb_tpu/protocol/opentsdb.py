"""OpenTSDB telnet `put` protocol parser.

Role-parity with common/protocol_parser/src/open_tsdb/: lines of
`put <metric> <ts> <value> tag=v ...` → WriteBatch (field name "value",
timestamps auto-scaled: seconds or milliseconds accepted like the
reference).
"""
from __future__ import annotations

from ..errors import ParserError
from ._time import normalize_ts_ns
from ..models.points import SeriesRows, WriteBatch
from ..models.schema import ValueType
from ..models.series import SeriesKey, Tag


def parse_opentsdb(text: str) -> WriteBatch:
    groups: dict[tuple[str, tuple], dict] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        parts = line.split()
        if parts[0] == "put":
            parts = parts[1:]
        if len(parts) < 3:
            raise ParserError(f"opentsdb line {lineno}: too few fields")
        metric, ts_s, val_s = parts[0], parts[1], parts[2]
        tags = {}
        for kv in parts[3:]:
            k, _, v = kv.partition("=")
            if not _:
                raise ParserError(f"opentsdb line {lineno}: bad tag {kv!r}")
            tags[k] = v
        try:
            ts = int(ts_s)
        except ValueError:
            raise ParserError(f"opentsdb line {lineno}: bad timestamp {ts_s!r}")
        ts = normalize_ts_ns(ts)
        try:
            val = float(val_s)
        except ValueError:
            raise ParserError(f"opentsdb line {lineno}: bad value {val_s!r}")
        key = (metric, tuple(sorted(tags.items())))
        g = groups.setdefault(key, {"tags": tags, "ts": [], "vals": []})
        g["ts"].append(ts)
        g["vals"].append(val)
    wb = WriteBatch()
    for (metric, _), g in groups.items():
        sk = SeriesKey(metric, [Tag(k, v) for k, v in g["tags"].items()])
        wb.add_series(metric, SeriesRows(
            sk, g["ts"], {"value": (int(ValueType.FLOAT), g["vals"])}))
    return wb
