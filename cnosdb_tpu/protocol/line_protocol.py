"""InfluxDB line protocol parser.

Role-parity with the reference's protocol parser
(common/protocol_parser/src/line_protocol/parser.rs:40-49 +
lines_convert.rs): text lines → WriteBatch grouped per (table, series),
which is the shape the coordinator and vnode apply path consume.

Format: measurement[,tag=v...] field=value[,field=value...] [timestamp]
Escapes: '\\,' '\\ ' '\\=' in names/tags; fields: 1.5 (float), 3i (int),
7u (unsigned), "text" (string), t/f/true/false (bool).
"""
from __future__ import annotations

import time as _time

from ..errors import ParserError
from ..models.points import SeriesRows, WriteBatch
from ..models.schema import Precision, ValueType
from ..models.series import SeriesKey, Tag


def parse_lines(text: str, precision: Precision = Precision.NS,
                default_time_ns: int | None = None) -> WriteBatch:
    factor = precision.to_ns_factor()
    now = default_time_ns if default_time_ns is not None else int(_time.time() * 1e9)
    if len(text) >= 512:
        # Native fast path (native/lineproto.cpp): same grouping/typing
        # semantics, columnar output. None = unavailable or input outside
        # its proven set — including anything malformed, so the Python path
        # below raises the canonical error.
        from . import native_lp

        wb = native_lp.try_parse(text, now, factor)
        if wb is not None:
            return wb
    return _parse_lines_py(text, factor, now)


def _parse_lines_py(text: str, factor: int, now: int) -> WriteBatch:
    groups: dict[tuple[str, tuple], dict] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        try:
            measurement, tags, fields, ts = _parse_line(line)
        except ParserError:
            raise
        except Exception as e:
            raise ParserError(f"line {lineno}: {e}", line=raw[:120])
        ts_ns = ts * factor if ts is not None else now
        key = (measurement, tuple(sorted(tags.items())))
        g = groups.get(key)
        if g is None:
            g = groups[key] = {"tags": tags, "ts": [], "fields": {}}
        idx = len(g["ts"])
        g["ts"].append(ts_ns)
        for fname, (vt, val) in fields.items():
            col = g["fields"].setdefault(fname, (vt, []))
            if col[0] != vt:
                raise ParserError(
                    f"line {lineno}: field {fname!r} type conflict in batch")
            vals = col[1]
            while len(vals) < idx:
                vals.append(None)
            vals.append(val)
    wb = WriteBatch()
    for (measurement, tag_items), g in groups.items():
        n = len(g["ts"])
        fields = {}
        for fname, (vt, vals) in g["fields"].items():
            while len(vals) < n:
                vals.append(None)
            fields[fname] = (int(vt), vals)
        sk = SeriesKey(measurement, [Tag(k, v) for k, v in g["tags"].items()])
        wb.add_series(measurement, SeriesRows(sk, g["ts"], fields))
    return wb


def _split_escaped(s: str, sep: str, unescape: bool = False) -> list[str]:
    """Split on unescaped `sep`. Escape sequences are PRESERVED unless
    `unescape` (so nested splits see them); unescape only at the last
    splitting level."""
    out = []
    cur = []
    i = 0
    n = len(s)
    in_quotes = False
    while i < n:
        c = s[i]
        if c == "\\" and i + 1 < n and not in_quotes:
            if unescape:
                cur.append(s[i + 1])
            else:
                cur.append(c)
                cur.append(s[i + 1])
            i += 2
            continue
        if c == '"':
            in_quotes = not in_quotes
            cur.append(c)
            i += 1
            continue
        if c == sep and not in_quotes:
            out.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _unescape(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(s[i + 1])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _parse_line(line: str):
    # split into up to 3 sections on unescaped spaces
    sections = _split_escaped(line, " ")
    sections = [s for s in sections if s != ""]
    if len(sections) < 2:
        raise ParserError("missing fields section")
    head = sections[0]
    field_str = sections[1]
    ts = None
    if len(sections) >= 3:
        ts = int(sections[2])
    head_parts = _split_escaped(head, ",")
    measurement = _unescape(head_parts[0])
    if not measurement:
        raise ParserError("empty measurement")
    tags = {}
    for t in head_parts[1:]:
        kv = _split_escaped(t, "=")
        if len(kv) != 2:
            raise ParserError(f"bad tag {t!r}")
        tags[_unescape(kv[0])] = _unescape(kv[1])
    fields = {}
    for f in _split_escaped(field_str, ","):
        kv = _split_escaped(f, "=")
        if len(kv) != 2:
            raise ParserError(f"bad field {f!r}")
        fields[_unescape(kv[0])] = _parse_field_value(kv[1])
    if not fields:
        raise ParserError("no fields")
    return measurement, tags, fields, ts


def _parse_field_value(v: str):
    if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
        return (ValueType.STRING, v[1:-1].replace('\\"', '"'))
    lv = v.lower()
    if lv in ("t", "true"):
        return (ValueType.BOOLEAN, True)
    if lv in ("f", "false"):
        return (ValueType.BOOLEAN, False)
    if v.endswith("i"):
        return (ValueType.INTEGER, int(v[:-1]))
    if v.endswith("u"):
        return (ValueType.UNSIGNED, int(v[:-1]))
    return (ValueType.FLOAT, float(v))
