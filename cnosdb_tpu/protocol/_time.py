"""Shared timestamp normalization for ingest protocols."""
from __future__ import annotations


def normalize_ts_ns(v: int) -> int:
    """Infer the unit of an integer timestamp by magnitude → ns.

    < 1e11  → seconds      (covers dates well past 5000 AD)
    < 1e14  → milliseconds
    < 1e17  → microseconds
    else    → nanoseconds
    """
    v = int(v)
    if v < 10**11:
        return v * 10**9
    if v < 10**14:
        return v * 10**6
    if v < 10**17:
        return v * 10**3
    return v
