"""Coordinator: routes writes to placed vnodes, fans scans out over them.

Role-parity with the reference's Coordinator trait / CoordService
(coordinator/src/lib.rs:56-140, service.rs:548-834): write_points splits a
WriteBatch per (bucket by timestamp → shard by series hash) placement from
meta, and table_vnodes enumerates the vnodes a predicate's time ranges
touch. In this single-process round every placed vnode is local; the
seams where gRPC fan-out goes later are `_write_vnode` / `scan_table`.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..models.points import SeriesRows, WriteBatch
from ..models.predicate import ColumnDomains, TimeRanges
from ..models.schema import TskvTableSchema, ValueType
from ..storage.engine import TsKv
from ..storage.scan import ScanBatch, scan_vnode
from .meta import MetaStore


@dataclass
class PlacedSplit:
    """One scan unit: a vnode plus the predicate pushed to it
    (reference data_source/split/mod.rs PlacedSplit)."""

    owner: str
    vnode_id: int
    table: str
    time_ranges: TimeRanges
    tag_domains: ColumnDomains


class Coordinator:
    SCAN_CACHE_SIZE = 32

    def __init__(self, meta: MetaStore, engine: TsKv):
        self.meta = meta
        self.engine = engine
        self._replica_mgr = None  # built on first multi-replica write
        # ScanBatch snapshots keyed by vnode data_version: repeated queries
        # reuse both the host batch and its device-resident twin (the
        # reference's TsmReader LRU cache, promoted to whole-scan snapshots
        # because host→device transfer dominates on this hardware)
        self._scan_cache: dict = {}
        # schema auto-creation callbacks land on meta; keep engine's view hot
        meta.watch(self._on_meta_event)

    def _on_meta_event(self, event: str, payload: dict):
        if event in ("create_table", "update_table"):
            owner = payload["owner"]
            tenant, db = owner.split(".", 1)
            schema = self.meta.table_opt(tenant, db, payload["table"])
            if schema is not None:
                self.engine.set_table_schema(owner, schema)
        elif event == "drop_table":
            self.engine.drop_table(payload["owner"], payload["table"])
        elif event == "drop_db":
            self.engine.drop_database(payload["owner"])

    # ---------------------------------------------------------------- write
    def write_points(self, tenant: str, db: str, batch: WriteBatch,
                     sync: bool = False):
        """Split per placement and write each vnode group
        (reference service.rs:565 write_lines)."""
        owner = f"{tenant}.{db}"
        self.meta.database(tenant, db)  # raises if missing
        per_rs: dict[int, tuple[object, WriteBatch]] = {}
        for table, series_list in batch.tables.items():
            self._ensure_schema(tenant, db, table, series_list)
            for sr in series_list:
                groups = self._split_series_by_bucket(tenant, db, sr)
                for rs, sub in groups:
                    entry = per_rs.get(rs.id)
                    if entry is None:
                        entry = per_rs[rs.id] = (rs, WriteBatch())
                    entry[1].add_series(table, sub)
        for rs, sub_batch in per_rs.values():
            self._write_replica_set(owner, rs, sub_batch, sync)

    def _split_series_by_bucket(self, tenant: str, db: str, sr: SeriesRows):
        """A series' rows can straddle buckets; split rows by bucket then
        route to `shard = hash % shard_num` within each."""
        h = sr.key.hash_id()
        if not sr.timestamps:
            return []
        # fast path: whole series fits one bucket (the common case)
        lo, hi = min(sr.timestamps), max(sr.timestamps)
        b_lo = self.meta.locate_bucket_for_write(tenant, db, lo)
        if b_lo.contains(hi):
            return [(b_lo.vnode_for(h), sr)]
        rs_rows: dict[int, tuple[object, list[int]]] = {}
        for i, ts in enumerate(sr.timestamps):
            bucket = self.meta.locate_bucket_for_write(tenant, db, ts)
            rs = bucket.vnode_for(h)
            rs_rows.setdefault(rs.id, (rs, []))[1].append(i)
        out = []
        for rs, idxs in rs_rows.values():
            if len(idxs) == len(sr.timestamps):
                out.append((rs, sr))
            else:
                sub = SeriesRows(
                    sr.key, [sr.timestamps[i] for i in idxs],
                    {k: (vt, [vals[i] for i in idxs])
                     for k, (vt, vals) in sr.fields.items()})
                out.append((rs, sub))
        return out

    def _write_replica_set(self, owner: str, rs, batch: WriteBatch,
                           sync: bool):
        """Single-replica sets write the engine directly; replicated sets go
        through raft consensus (reference service.rs write_replica_by_raft)."""
        if len(rs.vnodes) <= 1:
            self.engine.write(owner, rs.leader_vnode_id, batch, sync=sync)
            return
        from ..storage.wal import WalEntryType

        self.replica_manager().write(owner, rs, WalEntryType.WRITE,
                                     batch.encode(), sync=sync)

    def replica_manager(self):
        if self._replica_mgr is None:
            from .replica import ReplicaGroupManager

            self._replica_mgr = ReplicaGroupManager(self.engine)
        return self._replica_mgr

    def close(self):
        """Stop raft tickers BEFORE closing the engine — heartbeats append
        to the WAL, which must outlive them."""
        if self._replica_mgr is not None:
            self._replica_mgr.stop()
            self._replica_mgr = None
        self.engine.close()

    def _ensure_schema(self, tenant: str, db: str, table: str,
                       series_list: list[SeriesRows]):
        """Auto-create/evolve the table schema from incoming points
        (reference database.rs build_write_group schema inference)."""
        schema = self.meta.table_opt(tenant, db, table)
        if schema is None:
            tags = sorted({t.key for sr in series_list for t in sr.key.tags})
            fields = {}
            for sr in series_list:
                for name, (vt, _vals) in sr.fields.items():
                    fields.setdefault(name, ValueType(vt))
            schema = TskvTableSchema.new_measurement(
                tenant, db, table, tags, sorted(fields.items()),
                precision=self.meta.database(tenant, db).options.precision)
            self.meta.create_table(schema, if_not_exists=True)
            return
        from ..models.schema import ColumnType

        changed = False
        for sr in series_list:
            for t in sr.key.tags:
                if not schema.contains_column(t.key):
                    schema.add_column(t.key, ColumnType.tag())
                    changed = True
            for name, (vt, _vals) in sr.fields.items():
                if not schema.contains_column(name):
                    schema.add_column(name, ColumnType.field(ValueType(vt)))
                    changed = True
        if changed:
            self.meta.update_table(schema)

    # ---------------------------------------------------------------- read
    def table_vnodes(self, tenant: str, db: str, table: str,
                     time_ranges: TimeRanges,
                     tag_domains: ColumnDomains) -> list[PlacedSplit]:
        """Predicate → splits (reference SplitManager::splits +
        coord.table_vnodes)."""
        owner = f"{tenant}.{db}"
        lo = None if time_ranges.is_all else time_ranges.min_ts
        hi = None if time_ranges.is_all else time_ranges.max_ts
        splits = []
        seen = set()
        for bucket in self.meta.buckets_for(tenant, db, lo, hi):
            for rs in bucket.shard_group:
                vnode_id = rs.leader_vnode_id
                if len(rs.vnodes) > 1 and self._replica_mgr is not None:
                    # follow the live raft leader for read-your-writes
                    live = self._replica_mgr.current_leader_vnode(owner, rs)
                    if live is not None:
                        vnode_id = live
                if vnode_id in seen:
                    continue
                seen.add(vnode_id)
                splits.append(PlacedSplit(owner, vnode_id, table,
                                          time_ranges, tag_domains))
        return splits

    def scan_table(self, tenant: str, db: str, table: str,
                   time_ranges: TimeRanges | None = None,
                   tag_domains: ColumnDomains | None = None,
                   field_names: list[str] | None = None) -> list[ScanBatch]:
        """Fan a scan out over placed vnodes → one ScanBatch per vnode."""
        trs = time_ranges or TimeRanges.all()
        doms = tag_domains or ColumnDomains.all()
        batches = []
        for split in self.table_vnodes(tenant, db, table, trs, doms):
            v = self.engine.vnode(split.owner, split.vnode_id)
            if v is None:
                continue
            sids = None
            if not doms.is_all:
                sids = v.index.get_series_ids_by_domains(table, doms)
                if len(sids) == 0:
                    continue
            import hashlib

            sids_key = (hashlib.md5(np.ascontiguousarray(sids).tobytes())
                        .hexdigest() if sids is not None else None)
            key = (split.owner, split.vnode_id, table,
                   tuple(field_names) if field_names is not None else None,
                   tuple((r.min_ts, r.max_ts) for r in trs.ranges),
                   sids_key)
            hit = self._scan_cache.get(key)
            if hit is not None and hit[0] == v.data_version:
                b = hit[1]
                self._scan_cache[key] = self._scan_cache.pop(key)  # LRU touch
            else:
                b = scan_vnode(v, table, series_ids=sids, time_ranges=trs,
                               field_names=field_names)
                self._scan_cache.pop(key, None)  # supersede stale version
                while len(self._scan_cache) >= self.SCAN_CACHE_SIZE:
                    self._scan_cache.pop(next(iter(self._scan_cache)))
                self._scan_cache[key] = (v.data_version, b)
            if b.n_rows:
                batches.append(b)
        return batches

    # ---------------------------------------------------------------- admin
    def drop_table(self, tenant: str, db: str, table: str):
        self.meta.drop_table(tenant, db, table)

    def drop_database(self, tenant: str, db: str):
        self.meta.drop_database(tenant, db)

    def delete_from_table(self, tenant: str, db: str, table: str,
                          tag_domains: ColumnDomains, min_ts: int, max_ts: int):
        owner = f"{tenant}.{db}"
        for v in self.engine.local_vnodes(owner):
            sids = None
            if not tag_domains.is_all:
                sids = v.index.get_series_ids_by_domains(table, tag_domains)
                if len(sids) == 0:
                    continue
            v.delete_time_range(table, sids, min_ts, max_ts)

    def tag_values(self, tenant: str, db: str, table: str, tag_key: str) -> list[str]:
        owner = f"{tenant}.{db}"
        out = set()
        for v in self.engine.local_vnodes(owner):
            out.update(v.index.tag_values(table, tag_key))
        return sorted(out)

    def series_keys(self, tenant: str, db: str, table: str,
                    tag_domains: ColumnDomains | None = None) -> list:
        owner = f"{tenant}.{db}"
        doms = tag_domains or ColumnDomains.all()
        keys = {}
        for v in self.engine.local_vnodes(owner):
            for sid in v.index.get_series_ids_by_domains(table, doms):
                k = v.index.get_series_key(int(sid))
                if k is not None:
                    keys[(k.table, k.tags)] = k
        return [keys[k] for k in sorted(keys)]
