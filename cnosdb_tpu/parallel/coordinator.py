"""Coordinator: routes writes to placed vnodes, fans scans out over them.

Role-parity with the reference's Coordinator trait / CoordService
(coordinator/src/lib.rs:56-140, service.rs:548-834): write_points splits a
WriteBatch per (bucket by timestamp → shard by series hash) placement from
meta, and table_vnodes enumerates the vnodes a predicate's time ranges
touch. Vnodes placed on other nodes are reached over the msgpack-HTTP RPC
plane: writes forward to the replica leader's node with retry-on-leader-
change (reference tskv_executor.rs TskvLeaderExecutor + rpc/tskv.rs
RaftWrite), scans stream back as Arrow IPC (reference QueryRecordBatch),
and a scan that fails on the leader's node fails over to follower replicas
(reference reader/mod.rs:36 CheckedCoordinatorRecordBatchStream).
"""
from __future__ import annotations

import contextvars
import logging
import os
import queue as queue_mod
import threading
import time
from dataclasses import dataclass, field
from hashlib import blake2b

import numpy as np

from ..errors import ChecksumMismatch, CoordinatorError, DeadlineExceeded, \
    TsmError
from ..utils import stages
from ..utils import deadline as deadline_mod
from ..utils.backoff import Backoff
from ..models.points import SeriesRows, WriteBatch
from ..models.predicate import ColumnDomains, TimeRanges
from ..models.schema import TskvTableSchema, ValueType
from ..server import memory
from ..storage.engine import TsKv
from ..storage.scan import ScanBatch, scan_vnode
from . import health
from .meta import MetaStore
from ..utils import lockwatch

log = logging.getLogger(__name__)

# Per-node circuit breaker: after CB_THRESHOLD consecutive connection-level
# failures, calls to that node fast-fail for CB_COOLDOWN seconds instead of
# each eating a full RPC timeout (a dead peer would otherwise stall every
# split of every scan). One probe per cooldown window re-tests the node.
CB_THRESHOLD = int(os.environ.get("CNOSDB_CB_THRESHOLD", "3"))
CB_COOLDOWN = float(os.environ.get("CNOSDB_CB_COOLDOWN", "2.0"))
# Deadline-burn threshold for breaker resets: only a success faster than
# this fraction of the hop's timeout absolves accumulated failures — a
# single crawl-speed success from a browning-out node must not rearm it.
CB_BURN_FRACTION = float(os.environ.get("CNOSDB_CB_BURN_FRACTION", "0.5"))


@dataclass
class PlacedSplit:
    """One scan unit: a vnode plus the predicate pushed to it
    (reference data_source/split/mod.rs PlacedSplit)."""

    owner: str
    vnode_id: int
    table: str
    time_ranges: TimeRanges
    tag_domains: ColumnDomains
    node_id: int = 0
    # "hot" | "cold": cold = the vnode holds object-store-tiered files, so
    # its scan lane prunes against local sidecars and ranged-GETs only the
    # surviving pages (storage/tiering.py); informational for planning,
    # metrics and the cold-recovery retry — the readers themselves are
    # tier-transparent
    tier: str = "hot"
    # failover candidates: other replicas as (vnode_id, node_id)
    alternates: list = field(default_factory=list)
    # replicas currently marked BROKEN (self-heal on a successful scan)
    broken_ids: set = field(default_factory=set)


class Coordinator:
    SCAN_CACHE_SIZE = 32
    # byte cap across cached ScanBatches (sum of array nbytes): entry
    # count alone lets a few huge vnodes pin gigabytes of host memory
    SCAN_CACHE_MAX_BYTES = int(os.environ.get(
        "CNOSDB_CACHE_SCAN_CACHE_MAX_BYTES", str(1024 * 1024 * 1024)))

    def __init__(self, meta, engine: TsKv, node_id: int | None = None,
                 memory_pool=None):
        from ..utils.memory_pool import DEFAULT_POOL

        self.meta = meta
        self.engine = engine
        self.memory_pool = memory_pool or DEFAULT_POOL
        # distributed iff the catalog is a remote MetaClient: placement may
        # then name vnodes on other nodes, reached over RPC
        self.distributed = not isinstance(meta, MetaStore)
        self.node_id = node_id if node_id is not None else meta.node_id
        self._replica_mgr = None  # built on first multi-replica write
        # set by sql/matview.MatviewEngine; serves matview_partials RPCs
        self.matview_maintainer = None
        # ScanBatch snapshots keyed by vnode data_version: repeated queries
        # reuse both the host batch and its device-resident twin (the
        # reference's TsmReader LRU cache, promoted to whole-scan snapshots
        # because host→device transfer dominates on this hardware);
        # lock-guarded: node-service handler threads scan concurrently
        # key → (ScanToken, ScanBatch, nbytes); LRU by dict re-insertion
        self._scan_cache: dict = {}
        self._scan_cache_bytes = 0
        self._scan_cache_lock = lockwatch.Lock("coord.scan_cache")
        # memory-governance plane: the scan cache is an evictable pool —
        # the broker shrinks it LRU-first when the node crosses its soft
        # watermark (latest coordinator instance wins, like the engine)
        memory.register_pool("scan_cache",
                             usage_fn=lambda: self.scan_cache_stats()[1],
                             reclaim=self._reclaim_scan_cache)
        # schema auto-creation callbacks land on meta; keep engine's view hot
        meta.watch(self._on_meta_event)
        # seed the engine's schema view from the catalog for EVERY owner
        # (not just usage_schema's bootstrap tables): on restart no
        # create_table events replay, and WAL replay / flush need the
        # schema to re-key replayed fields by column id and to stamp
        # flushed chunks. MetaClient delegates `tables` to its cache
        # replica, so distributed nodes seed the same way (and keep
        # hydrating via watch events).
        for owner, tbls in getattr(meta, "tables", {}).items():
            for t in tbls.values():
                self.engine.set_table_schema(owner, t)
        # throttle clock + cumulative counters per usage metric key,
        # lock-guarded: executor/HTTP threads record concurrently
        self._usage_last: dict = {}
        self._usage_lock = lockwatch.Lock("coord.usage")
        # circuit breaker: node_id → [consecutive_failures, open_until]
        self._cb: dict = {}
        self._cb_lock = lockwatch.Lock("coord.circuit_breakers")
        # hedged-scan plane: per-coordinator in-flight hedge cap (hedges
        # add load exactly when the cluster is slow — bound them) and a
        # sequence for derived per-attempt hedge qids
        self._hedge_limiter = health.HedgeLimiter(health.HEDGE_MAX_INFLIGHT)
        self._hedge_seq = 0
        self._hedge_lock = lockwatch.Lock("coord.hedge_seq")

    def _rpc(self, node_id: int, method: str, payload: dict,
             timeout: float = 10.0, hedge: bool = False):
        from .net import RpcError, RpcThrottled, RpcUnavailable, rpc_call

        addr = self.meta.node_addr(node_id)
        if not addr:
            raise RpcUnavailable(f"node {node_id} has no address")
        now = time.monotonic()
        with self._cb_lock:
            st = self._cb.get(node_id)
            if st is not None and st[0] >= CB_THRESHOLD:
                if now < st[1]:
                    raise RpcUnavailable(
                        f"{method}@node {node_id}: circuit open after "
                        f"{st[0]} consecutive failures "
                        f"(probe in {st[1] - now:.1f}s)")
                # half-open: this call is the single probe; keep the
                # circuit closed to everyone else until it resolves
                st[1] = now + CB_COOLDOWN
                health.count_breaker(node_id, "half_open")
        if health.enabled() and method in health.HEDGEABLE \
                and not hedge and not health.SLOW_START.admit(node_id):
            # freshly-closed breaker still ramping: fast-fail this READ
            # to an alternate instead of piling full traffic back onto a
            # barely-recovered node (writes are raft-placed, no
            # alternate exists, so they always pass). Hedges bypass the
            # ramp: a hedge is a single limiter-capped rescue probe for
            # a query whose preferred replica is ALREADY browned out —
            # the ramping node may be its only fast alternate
            raise RpcThrottled(
                f"{method}@node {node_id}: slow-start ramp after breaker "
                f"close — read routed to an alternate")
        dl = deadline_mod.current()
        if dl is not None and dl.qid is not None:
            # remember every node this request sent work to, so a kill /
            # expiry / disconnect can fan best-effort cancel_scan out
            dl.remote_nodes.add(addr)
        t0 = time.monotonic()
        try:
            reply = rpc_call(addr, method, payload, timeout=timeout)
        except RpcUnavailable:
            if dl is not None and dl.dead():
                # the socket timed out because OUR budget ran dry (or the
                # query was killed mid-read), not because the peer is
                # sick: don't poison the breaker or mark replicas broken
                dl.check()  # raises DeadlineExceeded / cancelled
            with self._cb_lock:
                st = self._cb.setdefault(node_id, [0, 0.0])
                st[0] += 1
                if st[0] >= CB_THRESHOLD:
                    st[1] = time.monotonic() + CB_COOLDOWN
                    if st[0] == CB_THRESHOLD:
                        health.count_breaker(node_id, "open")
            # an opened breaker voids any in-progress readmission ramp
            health.SLOW_START.clear(node_id)
            raise
        except RpcError:
            # app-level rejection: the node answered, so it is alive
            self._cb_reset(node_id)
            raise
        if time.monotonic() - t0 < CB_BURN_FRACTION * timeout:
            self._cb_reset(node_id)
        # a slow success deliberately leaves the consecutive-failure
        # counter standing: the node answered, but at brownout speed —
        # resetting on it would let a node timing out for everyone else
        # rearm itself with one crawled reply
        return reply

    def _cb_reset(self, node_id: int) -> None:
        """Breaker success path: clear accumulated failures; when this
        closes an OPEN breaker, start the slow-start readmission ramp
        instead of readmitting full traffic at once."""
        with self._cb_lock:
            st = self._cb.pop(node_id, None)
        if st is not None and st[0] >= CB_THRESHOLD:
            health.count_breaker(node_id, "closed")
            health.SLOW_START.begin(node_id)

    def _on_meta_event(self, event: str, payload: dict):
        if event == "update_vnode":
            # placement changed: raft peer resolution + scan snapshots must
            # re-derive from the new replica-set layout
            if self._replica_mgr is not None:
                self._replica_mgr.invalidate(payload["owner"],
                                             payload["rs_id"])
            with self._scan_cache_lock:
                self._scan_cache.clear()
                self._scan_cache_bytes = 0
            return
        if event in ("create_table", "update_table", "recover_table"):
            owner = payload["owner"]
            tenant, db = owner.split(".", 1)
            schema = self.meta.table_opt(tenant, db, payload["table"])
            if schema is not None:
                self.engine.set_table_schema(owner, schema)
        elif event == "drop_table":
            self.engine.drop_table(payload["owner"], payload["table"])
        elif event == "purge_table":
            # a trashed incarnation was superseded by CREATE of the same
            # name: hard-delete its rows before the new table goes live
            self.engine.drop_table(payload["owner"], payload["table"])
            with self._scan_cache_lock:
                self._scan_cache.clear()
                self._scan_cache_bytes = 0
        elif event == "trash_table":
            # soft delete: schema gone, row data stays until purge
            self.engine.remove_table_schema(payload["owner"],
                                            payload["table"])
        elif event == "drop_db":
            self.engine.drop_database(payload["owner"])
        elif event == "purge_vnode":
            # targeted reclamation of one trashed incarnation's vnode
            self.engine.drop_vnode(payload["owner"], payload["vnode_id"])
        elif event == "trash_db":
            # soft delete: close vnodes, keep every file for RECOVER
            self.engine.close_database(payload["owner"])
            with self._scan_cache_lock:
                self._scan_cache.clear()
                self._scan_cache_bytes = 0
        elif event == "recover_db":
            owner = payload["owner"]
            tenant, db = owner.split(".", 1)
            for t in self.meta.tables.get(owner, {}).values():
                self.engine.set_table_schema(owner, t)

    # ---------------------------------------------------------------- write
    def write_points(self, tenant: str, db: str, batch: WriteBatch,
                     sync: bool = False):
        """Split per placement and write each vnode group
        (reference service.rs:565 write_lines)."""
        owner = f"{tenant}.{db}"
        self.meta.database(tenant, db)  # raises if missing
        # gate large ingests on the memory budget (reference raft/writer.rs
        # :58-84 gates writes on GreedyMemoryPool)
        est = batch.n_rows() * 128
        record = db != "usage_schema"
        if record:
            # memory-governance ladder at USER ingress only: internal
            # usage_schema rows and the raft apply/heartbeat plane are
            # never backpressured (they are how the node drains)
            memory.write_admit(est)
        pre_sizes = None
        if record:
            try:
                pre_sizes = self._vnode_cache_sizes(owner)
            except Exception:
                record = False   # metrics must never fail the write
        with self.memory_pool.reservation(est, f"write to {owner}"):
            self._write_points_inner(tenant, db, owner, batch, sync)
        if record:
            try:
                self._record_write_usage(tenant, db, owner, est, pre_sizes)
            except Exception:
                stages.count_error("swallow.coord.record_write_usage")

    def _write_points_inner(self, tenant, db, owner, batch, sync):
        per_rs: dict[int, tuple[object, WriteBatch]] = {}
        prec = self.meta.database(tenant, db).options.precision
        factor = prec.to_ns_factor()
        if factor != 1:
            # ns inputs TRUNCATE to the database's precision
            # (db_precision.slt: us-db stores ...010001 as ...010000)
            for table, series_list in batch.tables.items():
                for sr in series_list:
                    ts = np.asarray(sr.timestamps, dtype=np.int64)
                    sr.timestamps = ts - (ts % factor)
        for table, series_list in batch.tables.items():
            self._ensure_schema(tenant, db, table, series_list)
            for sr in series_list:
                groups = self._split_series_by_bucket(tenant, db, sr)
                for rs, sub in groups:
                    entry = per_rs.get(rs.id)
                    if entry is None:
                        entry = per_rs[rs.id] = (rs, WriteBatch())
                    entry[1].add_series(table, sub)
        for rs, sub_batch in per_rs.values():
            self._write_replica_set(owner, rs, sub_batch, sync)

    # ----------------------------------------------------- usage metrics
    # The reference's metrics reporter (usage_schema.rs) writes REAL rows
    # into cnosdb.usage_schema: cumulative per-tenant counters
    # (coord_data_in/out, coord_writes/queries, sql/http_*) and per-vnode
    # gauges (vnode_cache_size pre+post around each write,
    # vnode_disk_storage after it). Metric writes never recurse (records
    # skip when the target db IS usage_schema) and never fail the caller.

    def _vnode_cache_sizes(self, owner: str) -> dict:
        # only already-open local vnodes — lazily opening every on-disk
        # vnode would defeat the point of a cheap gauge. Snapshot under
        # engine.lock: concurrent writes open vnodes mid-iteration.
        with self.engine.lock:
            vnodes = list(self.engine.vnodes.items())
        return {vid: v.active.usage_size
                for (o, vid), v in vnodes if o == owner}

    def record_usage(self, table: str, tags: dict, value: int,
                     throttle: bool = False, cumulative: bool = False):
        """Append one point to usage_schema.<table>. `throttle` caps the
        series at one sample per second; `cumulative` accumulates the
        value into a monotone counter first (prometheus-style)."""
        try:
            key = (table, tuple(sorted(tags.items())))
            now = time.monotonic()   # throttle interval, not a timestamp
            with self._usage_lock:
                if cumulative:
                    cnt = self._usage_last.setdefault(("c", key), [0])
                    cnt[0] += value
                    value = cnt[0]
                if throttle:
                    last = self._usage_last.get(("t", key))
                    if last is not None and now - last < 1.0:
                        return
                    self._usage_last[("t", key)] = now
            from ..models.points import SeriesRows, WriteBatch
            from ..models.schema import ValueType
            from ..models.series import SeriesKey, Tag

            sk = SeriesKey(table, [Tag(k, str(v)) for k, v in tags.items()])
            wb = WriteBatch()
            wb.add_series(table, SeriesRows(
                sk, [time.time_ns()],
                {"value": (int(ValueType.UNSIGNED), [int(value)])}))
            self.write_points("cnosdb", "usage_schema", wb)
        except Exception:
            stages.count_error("swallow.coord.report_usage")  # metrics must never fail or recurse into the caller

    def _record_write_usage(self, tenant, db, owner, est_bytes, pre_sizes):
        node = str(self.node_id)
        base = {"tenant": tenant, "database": db, "node_id": node}
        self.record_usage("coord_data_in", base, est_bytes,
                          throttle=True, cumulative=True)
        self.record_usage("coord_writes", base, 1,
                          throttle=True, cumulative=True)
        post = self._vnode_cache_sizes(owner)
        for vid, sz in post.items():
            pre = (pre_sizes or {}).get(vid, 0)
            if sz == pre and vid in (pre_sizes or {}):
                continue   # untouched vnode
            vt = {"tenant": tenant, "database": db, "node_id": node,
                  "vnode_id": str(vid)}
            self.record_usage("vnode_cache_size", vt, pre)
            self.record_usage("vnode_cache_size", vt, sz)
            v = self.engine.vnodes.get((owner, vid))
            if v is not None:
                self.record_usage("vnode_disk_storage", vt, v.disk_size())

    def _split_series_by_bucket(self, tenant: str, db: str, sr: SeriesRows):
        """A series' rows can straddle buckets; split rows by bucket then
        route to `shard = hash % shard_num` within each."""
        from ..models.points import ts_bounds

        h = sr.key.hash_id()
        if not len(sr.timestamps):
            return []
        # fast path: whole series fits one bucket (the common case)
        lo, hi = ts_bounds(sr.timestamps)
        b_lo = self.meta.locate_bucket_for_write(tenant, db, lo)
        if b_lo.contains(hi):
            return [(b_lo.vnode_for(h), sr)]
        rs_rows: dict[int, tuple[object, list[int]]] = {}
        for i, ts in enumerate(sr.timestamps):
            bucket = self.meta.locate_bucket_for_write(tenant, db, int(ts))
            rs = bucket.vnode_for(h)
            rs_rows.setdefault(rs.id, (rs, []))[1].append(i)

        def take(col, idxs):
            if isinstance(col, np.ndarray):
                return col[np.asarray(idxs, dtype=np.int64)]
            return [col[i] for i in idxs]

        out = []
        for rs, idxs in rs_rows.values():
            if len(idxs) == len(sr.timestamps):
                out.append((rs, sr))
            else:
                sub = SeriesRows(
                    sr.key, take(sr.timestamps, idxs),
                    {k: (vt, take(vals, idxs))
                     for k, (vt, vals) in sr.fields.items()})
                out.append((rs, sub))
        return out

    def _write_replica_set(self, owner: str, rs, batch: WriteBatch,
                           sync: bool):
        """Single-replica sets write the engine directly (locally or on the
        owning node); replicated sets go through raft consensus on the
        leader (reference service.rs write_replica_by_raft)."""
        from ..storage.wal import WalEntryType

        # stamp schema version/column ids before any encode: the WAL-bound
        # payload then replays correctly across RENAME/DROP on every path
        # (direct, RPC-forwarded, raft-replicated)
        batch.stamp_schema(self.engine.schemas.get(owner, {}))
        if len(rs.vnodes) <= 1:
            target = rs.vnodes[0].node_id if rs.vnodes else self.node_id
            if not self.distributed or target == self.node_id:
                self.engine.write(owner, rs.leader_vnode_id, batch, sync=sync)
            else:
                self._rpc(target, "write_vnode",
                          {"owner": owner, "vnode_id": rs.leader_vnode_id,
                           "data": batch.encode(), "sync": sync})
            return
        data = batch.encode()
        if not self.distributed:
            self.replica_manager().write(owner, rs, WalEntryType.WRITE,
                                         data, sync=sync)
            return
        self._write_replicated(owner, rs, WalEntryType.WRITE, data, sync)

    def _write_replicated(self, owner: str, rs, entry_type: int, data: bytes,
                          sync: bool, timeout: float = 15.0):
        """Find the raft leader across nodes, retrying on leader change /
        node loss (reference TskvLeaderExecutor::do_request retry loop).
        The caller's request deadline caps the whole retry budget — a
        short-deadline write fails fast instead of riding the 15 s
        default."""
        from .net import RpcError, RpcUnavailable
        from .raft import NotLeader

        timeout = deadline_mod.cap_current(timeout)
        deadline = time.monotonic() + timeout
        bo = Backoff(initial=0.05, cap=1.0)
        hint_vnode: int | None = None
        last_err = None
        has_local = any(v.node_id == self.node_id for v in rs.vnodes)
        while time.monotonic() < deadline:
            deadline_mod.check_current()
            # 1. a local member may be (or become) the leader
            if has_local:
                try:
                    return self.replica_manager().propose_local(
                        owner, rs, entry_type, data, sync=sync)
                except NotLeader as e:
                    hint_vnode = e.args[0] if e.args else None
                    last_err = e
            # 2. forward to the hinted leader's node, then every other node
            order = []
            if hint_vnode is not None:
                v = rs.vnode(hint_vnode)
                if v is not None and v.node_id != self.node_id:
                    order.append(v.node_id)
            order += [v.node_id for v in rs.vnodes
                      if v.node_id != self.node_id and v.node_id not in order]
            for nid in order:
                try:
                    r = self._rpc(nid, "write_replica",
                                  {"owner": owner, "rs": rs.to_dict(),
                                   "entry_type": entry_type, "data": data,
                                   "sync": sync})
                except (RpcUnavailable, RpcError) as e:
                    last_err = e
                    continue
                if r.get("ok"):
                    return r.get("index")
                hint_vnode = r.get("hint")
            if not bo.sleep(deadline):
                break
        raise CoordinatorError(
            f"no reachable leader for replica set {rs.id} of {owner}"
        ) from last_err

    def _replica_change_membership(self, owner: str, rs, members: list[int],
                                   timeout: float = 15.0) -> int:
        """Drive a single-step raft config change to whichever node leads
        the group (same retry/forward shape as _write_replicated;
        reference raft/manager.rs:323-566 change-membership admin)."""
        from ..errors import ReplicationError
        from .net import RpcError, RpcUnavailable
        from .raft import NotLeader

        timeout = deadline_mod.cap_current(timeout)
        deadline = time.monotonic() + timeout
        bo = Backoff(initial=0.05, cap=1.0)
        hint_vnode: int | None = None
        last_err = None
        has_local = not self.distributed or \
            any(v.node_id == self.node_id for v in rs.vnodes)
        while time.monotonic() < deadline:
            deadline_mod.check_current()
            if has_local:
                try:
                    return self.replica_manager().change_membership_local(
                        owner, rs, members)
                except NotLeader as e:
                    hint_vnode = e.args[0] if e.args else None
                    last_err = e
                except ReplicationError as e:
                    # leader is the member being removed (needs the pending
                    # stepdown to land) or a commit timeout: retry
                    last_err = e
            order = []
            if hint_vnode is not None:
                v = rs.vnode(hint_vnode)
                if v is not None and v.node_id != self.node_id:
                    order.append(v.node_id)
            order += [v.node_id for v in rs.vnodes
                      if v.node_id != self.node_id and v.node_id not in order]
            if self.distributed:
                for nid in order:
                    try:
                        r = self._rpc(nid, "replica_change_membership",
                                      {"owner": owner, "rs": rs.to_dict(),
                                       "members": members})
                    except (RpcUnavailable, RpcError) as e:
                        last_err = e
                        continue
                    if r.get("ok"):
                        return r.get("index")
                    hint_vnode = r.get("hint")
            if not bo.sleep(deadline):
                break
        raise CoordinatorError(
            f"membership change failed for replica set {rs.id} of {owner}"
        ) from last_err

    def _replica_stepdown(self, owner: str, rs, vnode_id: int) -> None:
        """Best-effort: ask the member (wherever it lives) to yield
        leadership before its removal/move."""
        v = rs.vnode(vnode_id)
        if v is None:
            return
        try:
            if not self.distributed or v.node_id == self.node_id:
                self.replica_manager().stepdown_local(owner, rs, vnode_id)
            else:
                self._rpc(v.node_id, "replica_stepdown",
                          {"owner": owner, "rs": rs.to_dict(),
                           "vnode_id": vnode_id})
        except Exception:
            stages.count_error("swallow.coord.replica_stepdown")

    def _replica_progress(self, owner: str, rs,
                          vnode_id: int) -> tuple[int, int] | None:
        """(match, commit) of a member as seen by the group leader."""
        if not self.distributed or \
                any(v.node_id == self.node_id for v in rs.vnodes):
            pr = self.replica_manager().member_progress(owner, rs, vnode_id)
            if pr is not None:
                return pr
        if self.distributed:
            members = [v for v in rs.vnodes if v.node_id != self.node_id]
            if health.enabled() and len(members) > 1:
                # read-only quorum probe: ask the healthiest member
                # first so one browning-out peer can't put its full RPC
                # timeout in front of every progress check
                members = health.SCORER.rank(
                    members,
                    lambda v: self.meta.node_addr(v.node_id)
                    or f"node:{v.node_id}")
            for v in members:
                try:
                    r = self._rpc(v.node_id, "replica_progress",
                                  {"owner": owner, "rs": rs.to_dict(),
                                   "vnode_id": vnode_id})
                except Exception:
                    continue
                if r.get("ok"):
                    return r["match"], r["commit"]
        return None

    def replica_manager(self):
        if self._replica_mgr is None:
            from .replica import ReplicaGroupManager

            self._replica_mgr = ReplicaGroupManager(
                self.engine,
                node_id=self.node_id if self.distributed else None,
                meta=self.meta if self.distributed else None)
        return self._replica_mgr

    def close(self):
        """Stop raft tickers BEFORE closing the engine — heartbeats append
        to the WAL, which must outlive them."""
        if self._replica_mgr is not None:
            self._replica_mgr.stop()
            self._replica_mgr = None
        self.engine.close()

    def _ensure_schema(self, tenant: str, db: str, table: str,
                       series_list: list[SeriesRows]):
        """Auto-create/evolve the table schema from incoming points
        (reference database.rs build_write_group schema inference)."""
        schema = self.meta.table_opt(tenant, db, table)
        if schema is None:
            tags = sorted({t.key for sr in series_list for t in sr.key.tags})
            fields = {}
            for sr in series_list:
                for name, (vt, _vals) in sr.fields.items():
                    fields.setdefault(name, ValueType(vt))
            schema = TskvTableSchema.new_measurement(
                tenant, db, table, tags, sorted(fields.items()),
                precision=self.meta.database(tenant, db).options.precision)
            self.meta.create_table(schema, if_not_exists=True)
            return
        from ..models.schema import ColumnType

        changed = False
        for sr in series_list:
            for t in sr.key.tags:
                if not schema.contains_column(t.key):
                    schema.add_column(t.key, ColumnType.tag(),
                                      sorted_insert=True)
                    changed = True
            for name, (vt, _vals) in sr.fields.items():
                if not schema.contains_column(name):
                    schema.add_column(name, ColumnType.field(ValueType(vt)),
                                      sorted_insert=True)
                    changed = True
        if changed:
            self.meta.update_table(schema)

    # ---------------------------------------------------------------- read
    def table_vnodes(self, tenant: str, db: str, table: str,
                     time_ranges: TimeRanges,
                     tag_domains: ColumnDomains) -> list[PlacedSplit]:
        """Predicate → splits (reference SplitManager::splits +
        coord.table_vnodes)."""
        owner = f"{tenant}.{db}"
        lo = None if time_ranges.is_all else time_ranges.min_ts
        hi = None if time_ranges.is_all else time_ranges.max_ts
        splits = []
        seen = set()
        for bucket in self.meta.buckets_for(tenant, db, lo, hi):
            for rs in bucket.shard_group:
                vnode_id = rs.leader_vnode_id
                if len(rs.vnodes) > 1 and self._replica_mgr is not None:
                    # follow the live raft leader for read-your-writes
                    live = self._replica_mgr.current_leader_vnode(owner, rs)
                    if live is not None:
                        vnode_id = live
                # prefer a RUNNING replica over a broken-marked leader
                from ..models.meta_data import VnodeStatus

                v = rs.vnode(vnode_id)
                if v is not None and v.status == VnodeStatus.BROKEN:
                    healthy = [x for x in rs.vnodes
                               if x.status == VnodeStatus.RUNNING]
                    if healthy:
                        v = healthy[0]
                        vnode_id = v.id
                # route to the chosen vnode's placement node
                node_id = v.node_id if v is not None \
                    else (rs.leader_node_id or self.node_id)
                if vnode_id in seen:
                    continue
                seen.add(vnode_id)
                # alternates: RUNNING replicas first; BROKEN ones stay as a
                # last resort (and self-heal when a scan succeeds); COPYING
                # replicas have no data yet and are never read
                running = [(a.id, a.node_id) for a in rs.vnodes
                           if a.id != vnode_id
                           and a.status == VnodeStatus.RUNNING]
                broken = [(a.id, a.node_id) for a in rs.vnodes
                          if a.id != vnode_id
                          and a.status == VnodeStatus.BROKEN]
                split = PlacedSplit(owner, vnode_id, table,
                                    time_ranges, tag_domains,
                                    node_id=node_id,
                                    tier=self._split_tier(owner, vnode_id,
                                                          node_id),
                                    alternates=running + broken)
                split.broken_ids = {a.id for a in rs.vnodes
                                    if a.status == VnodeStatus.BROKEN}
                splits.append(split)
        return splits

    def _split_tier(self, owner: str, vnode_id: int, node_id: int) -> str:
        """COLD iff the (locally-placed) vnode has object-store-tiered
        files — a registry peek, no vnode open; remote vnodes report hot
        (their own node makes the tier call when it scans)."""
        if node_id != self.node_id and self.distributed:
            return "hot"
        from ..storage import tiering

        d = self.engine.vnode_dir(owner, vnode_id)
        try:
            return "cold" if tiering.cold_ids(d) else "hot"
        except TsmError:
            # torn cold registry: the tier is only a planning hint, so
            # answer "cold" and let the scan hit the damage inside the
            # guarded path, where _recover_cold rebuilds and retries
            return "cold"

    def _recover_cold(self, owner: str, vnode_id: int) -> int:
        """Rebuild lost / corrupt cold-tier sidecars of a LOCAL vnode
        from the object store (ranged tail reads — no full download).
        → sidecars rebuilt; 0 when the vnode has no cold files or the
        rebuild failed (callers then fall back to replica repair)."""
        from ..storage import tiering

        try:
            v = self.engine.vnode(owner, vnode_id)
            if v is None:
                return 0
            try:
                if not tiering.cold_ids(v.dir):
                    return 0
            except TsmError:
                pass    # torn registry: exactly what recover_vnode heals
            n = tiering.recover_vnode(v)
        except Exception:
            log.exception("cold-tier recovery of vnode %s failed", vnode_id)
            return 0
        if n:
            self._drop_vnode_cache_entries(owner, vnode_id)
        return n

    def scan_table(self, tenant: str, db: str, table: str,
                   time_ranges: TimeRanges | None = None,
                   tag_domains: ColumnDomains | None = None,
                   field_names: list[str] | None = None,
                   page_filter=None,
                   fingerprint: str | None = None,
                   compressed_spec=None) -> list[ScanBatch]:
        """Fan a scan out over placed vnodes → one ScanBatch per vnode.

        `page_filter` (optional sql.expr tree) lets the storage scan prune
        pages its statistics prove can't match — the returned batches then
        only cover filter-relevant rows, so callers MUST apply that same
        filter. Cache entries are keyed by the filter's rendering.
        `compressed_spec` (storage/compressed_domain.CompressedSpec)
        additionally engages the compressed-domain lane: batches may come
        back with rows already dropped and `compressed_partials` attached
        (possibly with ZERO rows and only partials) — valid ONLY for
        queries with that exact spec, so engaged batches cache under a
        spec-extended key.
        """
        # a soft-dropped (trashed) table's rows stay on disk for RECOVER
        # but must not be readable until then
        if self.meta.table_opt(tenant, db, table) is None \
                and self.meta.external_opt(tenant, db, table) is None:
            return []
        trs = time_ranges or TimeRanges.all()
        doms = tag_domains or ColumnDomains.all()
        splits = self.table_vnodes(tenant, db, table, trs, doms)

        from ..utils import executor

        workers = min(executor.pool_size("scan"), len(splits))
        # divide the host's cores across concurrent vnode scans: the
        # native page decoder threads inside each scan multiply with the
        # pool width, and oversubscription thrashes the cold path
        ncpu = os.cpu_count() or 1
        n_threads = max(1, ncpu // max(1, workers))

        # extract pruning constraints + cache-key rendering ONCE per query
        # (the filter tree walk is per-query, not per-vnode); a filter
        # with no usable conjuncts degrades to a plain shared scan. The
        # key renders the CONSTRAINTS (not the whole filter) so two
        # filters that prune identically share one cache entry.
        page_constraints = filter_key = None
        if page_filter is not None:
            from ..storage.scan import _page_constraints

            page_constraints = _page_constraints(page_filter,
                                                 field_names or [])
            if page_constraints:
                filter_key = repr(sorted(
                    (c, [(op, repr(v)) for op, v in cons])
                    for c, cons in page_constraints.items()))
            else:
                page_constraints = None

        def one(split):
            if self.distributed and split.node_id != self.node_id:
                return self._scan_remote(split, field_names,
                                         fingerprint=fingerprint)
            try:
                return self._scan_local(split, field_names, page_constraints,
                                        filter_key, n_threads,
                                        compressed_spec)
            except TsmError as e:
                # cold-tier metadata damage (lost / corrupt skip-index
                # sidecar): repairable in place from the object store —
                # rebuild the sidecars via ranged tail reads and retry the
                # scan ONCE. Safe to retry locally: TsmError never
                # quarantines, so the manifest still names every file.
                if not self._recover_cold(split.owner, split.vnode_id):
                    raise
                log.warning("rebuilt cold sidecars on vnode %s after: %s",
                            split.vnode_id, e)
                return self._scan_local(split, field_names, page_constraints,
                                        filter_key, n_threads,
                                        compressed_spec)
            except ChecksumMismatch as e:
                # corruption already quarantined + vnode marked BROKEN by
                # _scan_local; fail the in-flight scan over to a replica
                # alternate rather than erroring the query. The corrupt
                # primary is NOT retried locally — post-quarantine it would
                # answer with silently-missing rows.
                alts = list(split.alternates)
                if not alts:
                    raise
                fo = PlacedSplit(split.owner, alts[0][0], split.table,
                                 split.time_ranges, split.tag_domains,
                                 node_id=alts[0][1], alternates=alts[1:],
                                 broken_ids=set(split.broken_ids))
                log.warning("scan failover after corruption on vnode %s: %s",
                            split.vnode_id, e)
                return self._scan_remote(fo, field_names)

        if len(splits) > 1:
            # vnode scans are independent: decode in parallel (the C++
            # codec calls and big numpy ops release the GIL, so the cold
            # TSM→columns path scales with cores — the reference's scan
            # fans out across DataFusion partitions the same way) on the
            # long-lived shared pool (utils/executor.py), not a per-call
            # ThreadPoolExecutor
            results = executor.run_all("scan", one, splits)
        else:
            results = [one(s) for s in splits]
        # a 0-row batch can still carry the whole vnode's answer as
        # compressed-domain partials — it must reach the executor's merge
        return [b for b in results if b is not None
                and (b.n_rows
                     or getattr(b, "compressed_partials", None))]

    def _scan_local(self, split: PlacedSplit, field_names,
                    page_constraints: dict | None = None,
                    filter_key: str | None = None,
                    n_threads: int = 1,
                    compressed_spec=None) -> ScanBatch | None:
        table, trs, doms = split.table, split.time_ranges, split.tag_domains
        v = self.engine.vnode(split.owner, split.vnode_id)
        if v is None:
            return None
        sids = None
        if not doms.is_all:
            sids = v.index.get_series_ids_by_domains(table, doms)
            if len(sids) == 0:
                return None
        sids_key = (blake2b(np.ascontiguousarray(sids).tobytes(),
                            digest_size=16).hexdigest()
                    if sids is not None else None)
        # a predicate-pruned batch holds only pages that can satisfy THAT
        # constraint set: it is cached under the constraints' rendering
        # and never serves a different query. The UNFILTERED entry remains
        # valid for any filtered query (superset + row filter), so probe
        # it as a fallback; and a scan the constraints didn't actually
        # prune is stored under the shared unfiltered key.
        # schema_version keys DDL: after ALTER (drop/add/rename column) a
        # cached batch may hold stale columns — especially under
        # field_names=None (SELECT *), where the requested set is
        # implicit and identical keys would collide across the ALTER
        schema = v.schemas.get(table)
        base_key = (split.owner, split.vnode_id, table,
                    getattr(schema, "schema_version", None),
                    tuple(field_names) if field_names is not None else None,
                    tuple((r.min_ts, r.max_ts) for r in trs.ranges),
                    sids_key)
        key = base_key + (filter_key,)
        key0 = base_key + (None,)
        # a compressed-domain batch may have rows dropped / pre-answered
        # that only THIS spec's filter+aggregates account for: it caches
        # under a spec-extended key. The plain/pruned entries stay valid
        # fallbacks for a spec'd query (superset + executor row filter),
        # but never the reverse — NOTE filter_key alone is not enough:
        # specs with different predicates can share a constraint
        # rendering (e.g. bool conjuncts render no constraints at all).
        spec_key = (base_key + (filter_key, compressed_spec.key)
                    if compressed_spec is not None else None)
        from ..utils import stages

        # token BEFORE probe/decode: a write racing the decode makes the
        # stored token conservative (its rows re-decode next delta and
        # dedup away), never stale
        token = v.scan_token()
        stale = None
        probes = (key, key0) if filter_key else (key0,)
        if spec_key is not None:
            probes = (spec_key,) + probes
        with self._scan_cache_lock:
            for k in probes:
                hit = self._scan_cache.get(k)
                if hit is None:
                    continue
                if hit[0].data_version == v.data_version:
                    self._scan_cache[k] = self._scan_cache.pop(k)  # LRU
                    stages.count("scan_hit")
                    return hit[1]
                if stale is None:
                    stale = (k, hit)
        try:
            if stale is not None:
                b = self._scan_delta(v, stale, token, table, trs, sids,
                                     field_names, page_constraints,
                                     key, key0, n_threads)
                if b is not None:
                    return b
            stages.count("scan_miss")
            with stages.stage("decode_ms"):
                b = scan_vnode(v, table, series_ids=sids, time_ranges=trs,
                               field_names=field_names,
                               page_constraints=page_constraints,
                               n_threads=n_threads,
                               upload_hook=self._upload_hook(),
                               decode_hook=self._decode_hook(),
                               compressed_spec=compressed_spec)
        except ChecksumMismatch as e:
            # quarantine-on-read: drop the corrupt file from the live
            # Version (manifest-durable, excluded from every future scan),
            # invalidate this vnode's cached batches, and mark the vnode
            # BROKEN so scans route to replica alternates until
            # anti-entropy repairs it. Runs HERE (not in the dispatcher)
            # so a remote scan_vnode RPC quarantines on the owning node.
            self._quarantine_on_read(split.owner, split.vnode_id, e)
            raise
        if getattr(b, "_compressed_engaged", False):
            key = spec_key   # lane-shaped batch: valid for this spec only
        elif not getattr(b, "_pages_pruned", False):
            key = key0   # nothing pruned: the batch is the full scan
        self._cache_store(key, token, b)
        return b

    def _scan_delta(self, v, stale, token, table, trs, sids, field_names,
                    page_constraints, key, key0, n_threads):
        """Incremental rescan off a stale cache entry: decode only the
        TSM files / memcache rows the entry's token doesn't cover, merge
        into the cached batch (and its device twin), re-cache under the
        advanced token. → the merged batch, or None when only a full
        rescan is sound (destructive mutation, files compacted away,
        schema drift between the batches)."""
        from ..storage.scan import DeltaVnodeView, merge_scan_batches
        from ..utils import stages

        hit_key, (old, cached, _nb) = stale
        if old.destructive_version != token.destructive_version:
            return None   # tombstones / tag re-keys: no delta can express
        if not (old.file_ids <= token.file_ids):
            return None   # files compacted away: cached rows may be gone
        if getattr(cached, "_compressed_engaged", False):
            # compressed-domain batches pre-answer pages as partials that
            # a merge can't extend — only a full rescan is sound
            return None
        new_fids = token.file_ids - old.file_ids
        if not new_fids and token.mem_seq <= old.mem_seq:
            # nothing actually new (e.g. an L0→L1 promotion kept the same
            # file ids): refresh the token on the cached batch
            stages.count("delta_hit")
            self._cache_store(hit_key, token, cached)
            return cached
        view = DeltaVnodeView(v, new_fids, old.mem_seq)
        with stages.stage("decode_ms"):
            delta = scan_vnode(view, table, series_ids=sids,
                               time_ranges=trs, field_names=field_names,
                               page_constraints=page_constraints,
                               n_threads=n_threads,
                               upload_hook=self._upload_hook(),
                               decode_hook=self._decode_hook())
        cached_pruned = getattr(cached, "_pages_pruned", False)
        pruned = cached_pruned or getattr(delta, "_pages_pruned", False)
        if delta.n_rows == 0:
            merged, gather = cached, None
        else:
            res = merge_scan_batches(cached, delta)
            if res is None:
                return None
            merged, gather = res
            merged._pages_pruned = pruned
            if gather is not None \
                    and getattr(cached, "_device_batch", None) is not None:
                try:
                    from ..ops.device_cache import merged_device_batch

                    with stages.stage("merge_ms"):
                        merged_device_batch(merged, cached, delta, gather)
                except Exception:
                    stages.count_error("scan.device_merge")
        stages.count("delta_hit")
        stages.count("delta_rows", delta.n_rows)
        # a pruned result is only valid for this constraint set: it must
        # live under the filtered key even when the stale hit was the
        # unfiltered fallback entry
        store_key = hit_key if hit_key == key else (key if pruned else key0)
        self._cache_store(store_key, token, merged)
        return merged

    def _cache_store(self, key, token, batch):
        # every batch cached here was decoded by THIS node's scan path:
        # its rows can upload straight onto the execution mesh, so the
        # shard-aware planner (ops/mesh_exec) may claim it. Remote
        # batches (msgpack replies in _scan_remote*) never pass through
        # and stay off-mesh — the executor merges those over the legacy
        # RPC path.
        batch._mesh_local = True
        nb = _batch_nbytes(batch)
        with self._scan_cache_lock:
            old = self._scan_cache.pop(key, None)
            if old is not None:
                self._scan_cache_bytes -= old[2]
            while self._scan_cache and (
                    len(self._scan_cache) >= self.SCAN_CACHE_SIZE
                    or self._scan_cache_bytes + nb
                    > self.SCAN_CACHE_MAX_BYTES):
                lru = next(iter(self._scan_cache))
                self._scan_cache_bytes -= self._scan_cache.pop(lru)[2]
            self._scan_cache[key] = (token, batch, nb)
            self._scan_cache_bytes += nb

    def scan_cache_stats(self) -> tuple[int, int]:
        """→ (entries, bytes) for /metrics."""
        with self._scan_cache_lock:
            return len(self._scan_cache), self._scan_cache_bytes

    def _reclaim_scan_cache(self, target_bytes: int) -> int:
        """Broker reclaim callback: evict LRU entries until
        `target_bytes` are freed (or the cache is empty). Safe to lose
        any entry — snapshots revalidate by ScanToken on the next
        scan."""
        freed = 0
        with self._scan_cache_lock:
            while self._scan_cache and freed < target_bytes:
                lru = next(iter(self._scan_cache))
                freed += self._scan_cache.pop(lru)[2]
            self._scan_cache_bytes = max(0,
                                         self._scan_cache_bytes - freed)
        return freed

    def table_tokens(self, tenant: str, db: str, table: str):
        """Serving-plane invalidation key: the table's schema version plus
        one ScanToken tuple per covering vnode, each captured under that
        vnode's lock. Equality of two captures proves no flush / delete /
        compaction / tier / DDL event touched the table's DATABASE in
        between (vnodes are shared per-database, so a write to a sibling
        table conservatively misses — never serves stale). Walks
        `meta.buckets_for` directly instead of `table_vnodes` to skip the
        per-split tier peek — this runs on every result-cache probe.

        → None when the table is dropped, a covering vnode is replicated
        (the scan may read a replica this capture didn't token), or a
        remote owner can't answer — callers must bypass caching then."""
        schema = self.meta.table_opt(tenant, db, table)
        if schema is None:
            return None
        owner = f"{tenant}.{db}"
        toks: dict = {"schema": getattr(schema, "schema_version", None)}
        seen = set()
        for bucket in self.meta.buckets_for(tenant, db, None, None):
            for rs in bucket.shard_group:
                if len(rs.vnodes) > 1:
                    return None
                vnode_id = rs.leader_vnode_id
                if vnode_id in seen:
                    continue
                seen.add(vnode_id)
                v = self.engine.vnode(owner, vnode_id)
                if v is not None:
                    t = v.scan_token()
                    toks[vnode_id] = (t.data_version,
                                      t.destructive_version,
                                      t.file_ids, t.mem_seq)
                    continue
                if not self.distributed:
                    return None
                info = rs.vnode(vnode_id)
                if info is None:
                    return None
                try:
                    r = self._rpc(info.node_id, "vnode_token",
                                  {"owner": owner, "vnode_id": vnode_id})
                except Exception:
                    return None
                t = r.get("token") if isinstance(r, dict) else None
                if t is None:
                    return None
                toks[vnode_id] = (t["data_version"],
                                  t["destructive_version"],
                                  frozenset(t["file_ids"]), t["mem_seq"])
        return toks

    def _upload_hook(self):
        """Eager-upload factory for the scan pipeline — only when queries
        will actually take the device path; on pure-CPU placements the
        staging copy is wasted work."""
        try:
            from ..ops.placement import scan_device
            from ..ops.tpu_exec import _FORCE_DEVICE

            if scan_device().platform != "cpu" or _FORCE_DEVICE():
                from ..ops.device_cache import EagerUploader

                return EagerUploader
        except Exception:  # lint: disable=swallowed-exception (device probe: no accelerator is the normal case on CPU hosts, not an error)
            pass
        return None

    def _decode_hook(self):
        """Device-decode lane factory for the scan pipeline: a fresh
        DeviceDecodeLane per scan when the plane is enabled (real TPU, or
        forced via CNOSDB_DEVICE_DECODE=1), else None — scans then use
        the native/Python host lanes exactly as before."""
        try:
            from ..ops import device_decode

            if device_decode.enabled():
                return device_decode.DeviceDecodeLane
        except Exception:  # lint: disable=swallowed-exception (device probe: no accelerator is the normal case on CPU hosts, not an error)
            pass
        return None

    def _scan_remote(self, split: PlacedSplit, field_names,
                     fingerprint: str | None = None) -> ScanBatch | None:
        """Scan one split on its owning node, failing over to replica
        alternates (reference opener.rs:84-120 remote open +
        reader/mod.rs:36 broken-replica failover). `fingerprint` tags the
        RPC with the serving-plane query identity so the owning node's
        scan cache + stage counters attribute the work cluster-wide.

        With the gray-failure plane on (the default), failover
        candidates are health-ranked instead of fixed-order and the scan
        is hedged against tail latency; CNOSDB_HEDGE=0 restores the
        legacy byte-identical routing below."""
        targets = [(split.vnode_id, split.node_id)] + list(split.alternates)
        if not health.enabled():
            return self._scan_remote_solo(split, targets, field_names,
                                          fingerprint)
        targets = self._rank_targets(targets, split)
        return self._scan_remote_hedged(split, targets, field_names,
                                        fingerprint)

    def _rank_targets(self, targets: list, split: PlacedSplit) -> list:
        """Health-ranked FAILOVER order for one split's (vnode, node)
        candidates. The planner's primary choice (the live raft leader,
        or its healthy stand-in when the leader is meta-BROKEN) stays
        pinned at the head: leader-follow is what gives scans
        read-your-writes — a follower that hasn't applied the tail of
        the log yet answers with silently-missing rows, so health may
        never promote a replica into the primary slot. Everything
        after the head is health-ordered: local placements first, then
        power-of-two-choices among scorer-HEALTHY replicas, DEGRADED
        next, scorer-BROKEN after — and meta-BROKEN replicas stay
        pinned at the very tail (meta marks them data-suspect; the
        scorer only judges responsiveness, never data state). A
        browned-out leader is therefore rescued by the hedge lane, not
        by re-routing the primary."""
        head, rest = targets[:1], targets[1:]
        live = [t for t in rest if t[0] not in split.broken_ids]
        tail = [t for t in rest if t[0] in split.broken_ids]

        def addr_of(t):
            if t[1] == self.node_id:
                return None
            return self.meta.node_addr(t[1]) or f"node:{t[1]}"

        return head + health.SCORER.rank(live, addr_of) + tail

    def _scan_remote_solo(self, split: PlacedSplit, targets, field_names,
                          fingerprint: str | None = None) -> ScanBatch | None:
        """Legacy fixed-order failover loop (CNOSDB_HEDGE=0 A/B path)."""
        from .ipc import decode_scan_batch
        from .net import RpcError, RpcUnavailable

        last_unreach = None
        last_reject = None
        for vnode_id, node_id in targets:
            if node_id == self.node_id:
                if self.engine.vnode(split.owner, vnode_id) is None:
                    # placement says local but the data is absent (dropped /
                    # never installed): other replicas may still have it
                    continue
                alt = PlacedSplit(split.owner, vnode_id, split.table,
                                  split.time_ranges, split.tag_domains)
                b = self._scan_local(alt, field_names)
                if vnode_id in split.broken_ids:
                    self._clear_vnode_broken(vnode_id)
                return b
            try:
                r = self._rpc(node_id, "scan_vnode", {
                    "owner": split.owner, "vnode_id": vnode_id,
                    "table": split.table,
                    "trs": split.time_ranges.to_wire(),
                    "doms": split.tag_domains.to_wire(),
                    "field_names": field_names,
                    "fp": fingerprint,
                })
            except RpcUnavailable as e:
                # connection-level failure only: an app-level RpcError
                # (e.g. a memory-pool rejection) is not a broken replica
                last_unreach = e
                self._mark_vnode_broken(vnode_id)
                continue
            except RpcError as e:
                last_reject = e
                continue
            if vnode_id in split.broken_ids:
                self._clear_vnode_broken(vnode_id)  # it answered: self-heal
            raw = r.get("ipc")
            if raw is None:
                return None
            # per-query accounting: the reply buffer + its decoded twin
            # are this request's to pay for (MemoryExceeded kills only it)
            memory.charge_query(len(raw), "rpc_result")
            return decode_scan_batch(raw)
        if last_reject is not None:
            # at least one replica ANSWERED and rejected the scan — an
            # app-level error, not an availability problem; its message is
            # the actionable one (e.g. memory-pool rejection)
            msg = (f"scan of vnode {split.vnode_id} of {split.owner} "
                   f"rejected: {last_reject}")
            if last_unreach is not None:
                msg += f" (other replicas unreachable: {last_unreach})"
            raise CoordinatorError(msg) from last_reject
        raise CoordinatorError(
            f"all replicas unreachable for vnode {split.vnode_id} "
            f"of {split.owner}") from last_unreach

    def _hedge_delay_s(self, node_id: int) -> float:
        """Adaptive hedge trigger for an attempt against `node_id`: that
        node's (addr, scan) p95, floored by [query] hedge_delay_ms_floor
        so a microsecond warm-cache p95 can't hedge every call."""
        floor_s = health.HEDGE_DELAY_FLOOR_MS / 1e3
        if node_id == self.node_id:
            return floor_s
        addr = self.meta.node_addr(node_id)
        if not addr:
            return floor_s
        return health.SCORER.hedge_delay(addr, "scan", floor_s=floor_s)

    def _scan_remote_hedged(self, split: PlacedSplit, targets, field_names,
                            fingerprint: str | None = None):
        """Hedged scan over health-ranked targets — the tail-latency
        defense (fires unless CNOSDB_HEDGE=0).

        The best-ranked target is tried exactly as the legacy path
        would; if it hasn't answered within the adaptive hedge delay
        (its (addr, scan) p95, floored by config and capped by the
        remaining Deadline budget), the SAME scan fires at the
        next-ranked replica under a derived child deadline carrying its
        OWN hedge qid. The first success wins bit-identically (replicas
        are raft-converged, and the winner's IPC bytes decode the same
        whoever served them); every other in-flight attempt is
        cancelled through the cancel_scan fan-out, which names only the
        loser's hedge qid so the query's scans of OTHER vnodes are
        untouched. A failed attempt triggers immediate failover to the
        next target — failovers are not hedges and skip the limiter.
        Every exit of this lane books into cnosdb_hedge_total
        (hedge-accounting lint rule)."""
        from .ipc import decode_scan_batch
        from .net import RpcError, RpcThrottled, RpcUnavailable

        parent = deadline_mod.current()
        base_qid = (parent.qid if parent is not None else None) or "scan"
        resq: queue_mod.Queue = queue_mod.Queue()
        inflight: dict[int, dict] = {}       # attempt idx → {dl, ...}
        hedges_fired = 0
        next_target = 0
        armed = True          # one suppression verdict per scan
        last_unreach = last_reject = None
        throttled_idxs: list[int] = []   # slow-start-refused targets

        def launch(is_hedge: bool, idx: int | None = None,
                   bypass_ramp: bool = False) -> None:
            nonlocal next_target, hedges_fired
            if idx is None:
                idx = next_target
                next_target += 1
            bypass_ramp = bypass_ramp or is_hedge
            vnode_id, node_id = targets[idx]
            with self._hedge_lock:
                self._hedge_seq += 1
                seq = self._hedge_seq
            child = deadline_mod.derived(f"{base_qid}#h{seq}")
            ctx = contextvars.copy_context()   # profile rides along
            holds_slot = is_hedge

            def attempt():
                try:
                    with deadline_mod.scope(child):
                        if node_id == self.node_id:
                            if self.engine.vnode(split.owner,
                                                 vnode_id) is None:
                                # placement says local but the data is
                                # absent (dropped / never installed)
                                resq.put((idx, "skip", None))
                                return
                            alt = PlacedSplit(split.owner, vnode_id,
                                              split.table,
                                              split.time_ranges,
                                              split.tag_domains)
                            resq.put((idx, "local",
                                      self._scan_local(alt, field_names)))
                            return
                        r = self._rpc(node_id, "scan_vnode", {
                            "owner": split.owner, "vnode_id": vnode_id,
                            "table": split.table,
                            "trs": split.time_ranges.to_wire(),
                            "doms": split.tag_domains.to_wire(),
                            "field_names": field_names,
                            "fp": fingerprint,
                        }, hedge=bypass_ramp)
                        resq.put((idx, "remote", r))
                except RpcThrottled as e:
                    # slow-start ramp refusal: the peer was never
                    # contacted — not evidence of a broken replica
                    resq.put((idx, "unreach", e))
                except RpcUnavailable as e:
                    self._mark_vnode_broken(vnode_id)
                    resq.put((idx, "unreach", e))
                except RpcError as e:
                    resq.put((idx, "reject", e))
                except BaseException as e:
                    # deadline expiry / cancel / local engine failure —
                    # the collector decides whether it unwinds the query
                    resq.put((idx, "error", e))
                finally:
                    if holds_slot:
                        self._hedge_limiter.release()

            inflight[idx] = {"dl": child, "vnode_id": vnode_id,
                             "node_id": node_id, "hedge": is_hedge,
                             "t0": time.monotonic()}
            if is_hedge:
                hedges_fired += 1
                health.count_hedge("fired")
                stages.count("hedge.fired")
            threading.Thread(target=ctx.run, args=(attempt,), daemon=True,
                             name=f"hedge-scan-{base_qid}-{seq}").start()

        def abandon(reason: str) -> None:
            """Cancel every still-in-flight attempt (their own hedge
            qids only) and book the cancellations. Each loser's
            elapsed-so-far is fed to the scorer as a censored latency
            sample — the loser IS at least this slow, and waiting for
            its reply to land before learning that would keep routing
            scans at a straggler for a full brownout-latency window."""
            now = time.monotonic()
            for o in inflight.values():
                o["dl"].cancel(reason)
                # best-effort cancel off the query thread: delivering it
                # to the loser synchronously would make every rescued
                # query pay the straggler's latency all over again
                threading.Thread(
                    target=self.cancel_remote_scans, args=(o["dl"],),
                    daemon=True,
                    name=f"hedge-cancel-{base_qid}").start()
                if o["node_id"] != self.node_id:
                    addr = self.meta.node_addr(o["node_id"])
                    if addr:
                        health.SCORER.observe_censored(
                            addr, "scan", now - o["t0"])
                health.count_hedge("cancelled")
                stages.count("hedge.cancelled")
            inflight.clear()

        launch(is_hedge=False)
        while inflight:
            wait_s = None
            if armed and inflight:
                # the hedge trigger is the cheaper of the NEWEST launched
                # attempt's scan p95 and the NEXT candidate's: a hedge is
                # worth firing once the outstanding call is slower than
                # what the alternate typically delivers (so a scan routed
                # to a known-slow replica — stale score, exploration — is
                # rescued at the fast replica's pace, not the slow one's).
                # Capped by the remaining deadline budget.
                wait_s = self._hedge_delay_s(targets[next_target - 1][1])
                if next_target < len(targets):
                    wait_s = min(wait_s,
                                 self._hedge_delay_s(targets[next_target][1]))
                if parent is not None:
                    rem = parent.remaining()
                    if rem is not None:
                        wait_s = min(wait_s, max(rem, 0.0))
            try:
                idx, kind, value = resq.get(timeout=wait_s)
            except queue_mod.Empty:
                # trigger elapsed, attempt still in flight: hedge — or
                # book exactly why not (the suppression accounting is
                # what proves hedging stays tail-only). A target that
                # was refused by the slow-start ramp stays eligible
                # HERE: the ramp gates organic reads, while a hedge is
                # a single limiter-capped rescue probe that bypasses it
                # — without the retry, a ramping replica plus a browned
                # primary leaves the query waiting out the full
                # brownout with no alternate at all.
                retry_idx = None
                if next_target >= len(targets):
                    if not throttled_idxs:
                        health.count_hedge("suppressed", "no_alternate")
                        stages.count("hedge.suppressed")
                        armed = False
                        continue
                    retry_idx = throttled_idxs[0]
                rem = parent.remaining() if parent is not None else None
                if parent is not None and (parent.dead()
                                           or (rem is not None
                                               and rem <= 0.05)):
                    # no budget left to pay for a second attempt; the
                    # in-flight socket timeout is capped by the same
                    # budget and will resolve the scan shortly
                    health.count_hedge("suppressed", "no_budget")
                    stages.count("hedge.suppressed")
                    armed = False
                    continue
                if not self._hedge_limiter.try_acquire(
                        health.HEDGE_MAX_INFLIGHT):
                    health.count_hedge("suppressed", "limiter")
                    stages.count("hedge.suppressed")
                    armed = False
                    continue
                if retry_idx is not None:
                    throttled_idxs.pop(0)
                launch(is_hedge=True, idx=retry_idx)
                continue
            a = inflight.pop(idx, None)
            if a is None:     # late result of an already-settled attempt
                continue
            if kind in ("local", "remote"):
                won_by_hedge = a["hedge"]
                abandon("hedge loser")
                if won_by_hedge:
                    health.count_hedge("won")
                    stages.count("hedge.won")
                lost = hedges_fired - (1 if won_by_hedge else 0)
                if lost > 0:
                    health.count_hedge("lost", n=lost)
                if a["vnode_id"] in split.broken_ids:
                    self._clear_vnode_broken(a["vnode_id"])  # self-heal
                if kind == "local":
                    return value
                raw = value.get("ipc")
                if raw is None:
                    return None
                memory.charge_query(len(raw), "rpc_result")
                return decode_scan_batch(raw)
            if kind == "error" and not a["hedge"]:
                # primary-lineage failure of the typed kind the legacy
                # loop propagates immediately (deadline gone, cancel,
                # local checksum damage): unwind instead of retrying
                # replicas with a budget/state that is already dead
                if hedges_fired:
                    health.count_hedge("lost", n=hedges_fired)
                abandon("hedge abort")
                raise value
            # failed / skipped attempt: record and fail over
            if kind == "unreach":
                last_unreach = value
                if isinstance(value, RpcThrottled):
                    throttled_idxs.append(idx)   # hedge may retry it
            elif kind in ("reject", "error"):
                last_reject = value
            if not inflight:
                if next_target < len(targets):
                    launch(is_hedge=False)   # failover, not a hedge
                elif throttled_idxs:
                    # nothing left but ramp-refused targets: a refusal
                    # is load-shedding, not unavailability — retry past
                    # the ramp rather than failing the whole scan
                    launch(is_hedge=False, idx=throttled_idxs.pop(0),
                           bypass_ramp=True)
        if hedges_fired:
            health.count_hedge("lost", n=hedges_fired)
        if last_reject is not None:
            # at least one replica ANSWERED and rejected the scan — an
            # app-level error, not an availability problem
            stages.count_error("hedge.exhausted")
            msg = (f"scan of vnode {split.vnode_id} of {split.owner} "
                   f"rejected: {last_reject}")
            if last_unreach is not None:
                msg += f" (other replicas unreachable: {last_unreach})"
            raise CoordinatorError(msg) from last_reject
        stages.count_error("hedge.exhausted")
        raise CoordinatorError(
            f"all replicas unreachable for vnode {split.vnode_id} "
            f"of {split.owner}") from last_unreach

    def cancel_remote_scans(self, dl) -> int:
        """Best-effort cancel fan-out: tell every node this request sent
        work to (recorded in `dl.remote_nodes` by `_rpc`) to stop scans
        for its qid. Fired on KILL QUERY, deadline expiry, and HTTP
        client disconnect. Runs with the deadline scope CLEARED — the
        whole point is that the request's own budget is already dead.
        Returns the number of nodes that acknowledged."""
        from .net import RpcError, rpc_call

        if dl is None or not dl.qid:
            return 0
        acked = 0
        with deadline_mod.scope(None):
            for addr in list(dl.remote_nodes):
                try:
                    rpc_call(addr, "cancel_scan", {"qid": dl.qid},
                             timeout=1.0)
                    acked += 1
                except RpcError:
                    pass  # best-effort: the node may be gone already
        return acked

    # ---------------------------------------------------------------- admin
    def drop_table(self, tenant: str, db: str, table: str):
        self.meta.drop_table(tenant, db, table)

    def drop_database(self, tenant: str, db: str,
                      if_exists: bool = True):
        self.meta.drop_database(tenant, db, if_exists=if_exists)

    def _mark_vnode_broken(self, vnode_id: int):
        """Failed-replica marking (reference reader/mod.rs:36
        CheckedCoordinatorRecordBatchStream → Broken status); readers then
        prefer RUNNING replicas. Self-heals when a later scan succeeds.
        Skips the meta write when already marked — a down node must not
        turn every scan retry into an O(catalog) meta broadcast."""
        from ..models.meta_data import VnodeStatus

        try:
            hit = self.meta.find_vnode(vnode_id)
            if hit is not None and hit[3].status == VnodeStatus.BROKEN:
                return
            self.meta.update_vnode(vnode_id, status=int(VnodeStatus.BROKEN))
        except Exception:
            stages.count_error("swallow.coord.mark_vnode_broken")  # advisory only; the scan already failed over

    def _clear_vnode_broken(self, vnode_id: int):
        from ..models.meta_data import VnodeStatus

        try:
            self.meta.update_vnode(vnode_id, status=int(VnodeStatus.RUNNING))
        except Exception:
            stages.count_error("swallow.coord.clear_vnode_broken")

    # ---------------------------------------------------------------- admin
    def move_vnode(self, vnode_id: int, to_node: int):
        """MOVE VNODE <id> TO NODE <n> (reference raft/manager.rs:323-566 +
        DownloadFile snapshot shipping): copy the data, flip placement,
        drop the source copy. Placement flips LAST so a failure at any
        earlier step leaves the original intact (the ResourceManager
        retry contract collapses to at-most-once placement mutation)."""
        hit = self.meta.find_vnode(vnode_id)
        if hit is None:
            raise CoordinatorError(f"unknown vnode {vnode_id}")
        owner, _b, rs, v = hit
        src_node = v.node_id
        if src_node == to_node:
            return
        if self.meta.node_addr(to_node) is None and self.distributed:
            raise CoordinatorError(f"unknown target node {to_node}")
        if len(rs.vnodes) > 1:
            # placement move of one raft MEMBER: same member id, new home.
            # Yield leadership if it leads, tear the member down at the
            # source (its WAL dies with the data), flip placement as
            # COPYING — readers must not trust the gutted replica until
            # the leader rebuilds it via log replay or file-level snapshot
            # install (reference manager.rs move = add_follower + remove).
            from ..models.meta_data import VnodeStatus

            self._replica_stepdown(owner, rs, vnode_id)
            if src_node == self.node_id or not self.distributed:
                if self._replica_mgr is not None:
                    self._replica_mgr.stop_member(owner, rs.id, vnode_id)
                self.engine.drop_vnode(owner, vnode_id)
            else:
                try:
                    self._rpc(src_node, "vnode_drop",
                              {"owner": owner, "vnode_id": vnode_id,
                               "rs_id": rs.id})
                except Exception:
                    stages.count_error("swallow.coord.vnode_drop_rpc")  # source unreachable: placement is authoritative
            self.meta.update_vnode(vnode_id, node_id=to_node,
                                   status=int(VnodeStatus.COPYING))
            hit2 = self.meta.find_replica_set(rs.id)
            rs2 = hit2[1] if hit2 is not None else rs
            self._wait_member_caught_up(owner, rs2, vnode_id,
                                        what=f"moved replica {vnode_id}")
            self.meta.update_vnode(vnode_id, status=int(VnodeStatus.RUNNING))
            return
        data = self._fetch_vnode_snapshot(owner, vnode_id, src_node)
        if data is not None:
            self._install_vnode_snapshot(owner, vnode_id, to_node, data)
        self.meta.update_vnode(vnode_id, node_id=to_node, status=0)
        try:
            if src_node == self.node_id:
                self.engine.drop_vnode(owner, vnode_id)
            elif self.distributed:
                self._rpc(src_node, "vnode_drop",
                          {"owner": owner, "vnode_id": vnode_id})
        except Exception:
            stages.count_error("swallow.coord.vnode_drop_rpc")  # orphaned source data is garbage, not corruption

    def copy_vnode(self, vnode_id: int, to_node: int) -> int:
        """COPY VNODE <id> TO NODE <n>: add a replica seeded from a
        snapshot (reference REPLICA ADD + add_follower). Restricted to
        non-raft (single-replica) sets — raft membership change is the
        round-3 path."""
        hit = self.meta.find_vnode(vnode_id)
        if hit is None:
            raise CoordinatorError(f"unknown vnode {vnode_id}")
        owner, _b, rs, v = hit
        if len(rs.vnodes) > 1:
            return self._copy_into_replicated(owner, rs, to_node)
        from ..models.meta_data import VnodeStatus

        data = self._fetch_vnode_snapshot(owner, vnode_id, v.node_id)
        # register as COPYING so readers skip it, install, THEN go RUNNING;
        # a failed install rolls the placeholder back out
        new_id = self.meta.add_replica_vnode(rs.id, to_node,
                                             status=int(VnodeStatus.COPYING))
        try:
            if data is not None:
                self._install_vnode_snapshot(owner, new_id, to_node, data)
            # the RUNNING flip is part of the same all-or-nothing publish:
            # a replica stranded in COPYING would hold storage but never
            # serve reads
            self.meta.update_vnode(new_id, status=int(VnodeStatus.RUNNING))
        except Exception:
            try:
                self.meta.remove_replica_vnode(new_id)
            except Exception:
                stages.count_error("swallow.coord.remove_placeholder")  # meta unreachable: placeholder stays; retryable
            raise
        return new_id

    def _copy_into_replicated(self, owner: str, rs, to_node: int) -> int:
        """REPLICA ADD on a live raft group: grow the placement (COPYING),
        extend the raft config via the leader, let the new member catch up
        from the log / a file-level snapshot, then publish it RUNNING
        (reference manager.rs:323-566 add_follower → wait → promote)."""
        from ..models.meta_data import VnodeStatus

        new_id = self.meta.add_replica_vnode(rs.id, to_node,
                                             status=int(VnodeStatus.COPYING))
        hit = self.meta.find_replica_set(rs.id)
        if hit is None:  # placement vanished under us
            raise CoordinatorError(f"replica set {rs.id} disappeared")
        rs_new = hit[1]
        members = sorted({v.id for v in rs.vnodes} | {new_id})
        try:
            self._replica_change_membership(owner, rs_new, members)
            self._wait_member_caught_up(owner, rs_new, new_id,
                                        what=f"new replica {new_id}")
            self.meta.update_vnode(new_id, status=int(VnodeStatus.RUNNING))
            return new_id
        except Exception:
            # roll back: shrink the config (best effort) and remove the
            # COPYING placeholder so readers/writers never trust it
            try:
                self._replica_change_membership(
                    owner, rs_new, sorted(v.id for v in rs.vnodes),
                    timeout=5.0)
            except Exception:
                stages.count_error("swallow.coord.membership_rollback")
            try:
                self.meta.remove_replica_vnode(new_id)
            except Exception:
                stages.count_error("swallow.coord.remove_placeholder")
            raise

    def _wait_member_caught_up(self, owner: str, rs, vnode_id: int,
                               what: str, timeout: float = 45.0) -> None:
        """Block until the member has ACKED a freshly-proposed no-op.

        The leader's match_index can hold a STALE pre-rebuild value (it is
        assigned, not monotonically validated, and nothing resets it when
        a member is gutted and rebuilt) — so catching up is proven by the
        member acknowledging an entry proposed AFTER the change: raft's
        consistency check means it can only ack an index whose whole log
        prefix (or snapshot) it actually holds."""
        from ..storage.wal import WalEntryType

        target = self._write_replicated(owner, rs, WalEntryType.RAFT_BLANK,
                                        b"", sync=False)
        deadline = time.monotonic() + timeout
        bo = Backoff(initial=0.05, cap=1.0)
        while True:
            pr = self._replica_progress(owner, rs, vnode_id)
            if pr is not None and pr[0] >= target:
                return
            if time.monotonic() > deadline:
                raise CoordinatorError(
                    f"{what} has not caught up (stays COPYING, unread; "
                    f"retry the admin op to re-check)")
            bo.sleep(deadline)

    def drop_replica(self, vnode_id: int):
        """REPLICA REMOVE: shrink the raft config via the leader (the
        member yields leadership first if it holds it), update placement,
        tear down the raft member, then drop the data on the OWNING node
        (node-aware — the vnode may not be local). A live raft ticker
        would recreate the WAL the drop removes, so the member stops
        before the data drop."""
        hit = self.meta.find_vnode(vnode_id)
        if hit is None:
            raise CoordinatorError(f"unknown vnode {vnode_id}")
        owner, _b, rs, v = hit
        node = v.node_id
        survivor_to_stop = None
        if len(rs.vnodes) > 2:
            members = sorted(x.id for x in rs.vnodes if x.id != vnode_id)
            self._replica_stepdown(owner, rs, vnode_id)
            self._replica_change_membership(owner, rs, members)
        elif len(rs.vnodes) == 2:
            # dropping to a single replica: the survivor leaves consensus
            # entirely (single-vnode sets bypass raft), so no config-change
            # commit is needed — its member stops AFTER placement updates
            # (a write racing the update must not rebuild it)
            survivor_to_stop = next(x for x in rs.vnodes if x.id != vnode_id)
            self._replica_stepdown(owner, rs, vnode_id)
        self.meta.remove_replica_vnode(vnode_id)
        if survivor_to_stop is not None:
            # stop the member WHERE IT LIVES — otherwise a remote survivor
            # keeps a live raft ticker on the same WAL the direct write
            # path now appends to
            if survivor_to_stop.node_id == self.node_id \
                    or not self.distributed:
                if self._replica_mgr is not None:
                    self._replica_mgr.stop_member(owner, rs.id,
                                                  survivor_to_stop.id)
            else:
                try:
                    self._rpc(survivor_to_stop.node_id, "replica_stop_member",
                              {"owner": owner, "rs_id": rs.id,
                               "vnode_id": survivor_to_stop.id})
                except Exception:
                    stages.count_error("swallow.coord.replica_stop_member")  # stale member is inert once placement updated
        if self._replica_mgr is not None:
            self._replica_mgr.stop_member(owner, rs.id, vnode_id)
        if node == self.node_id or not self.distributed:
            self.engine.drop_vnode(owner, vnode_id)
        else:
            try:
                self._rpc(node, "vnode_drop",
                          {"owner": owner, "vnode_id": vnode_id,
                           "rs_id": rs.id})
            except Exception:
                stages.count_error("swallow.coord.vnode_drop_rpc")  # orphaned data is garbage, placement is authoritative

    def destroy_replica_set(self, rs_id: int):
        """REPLICA DESTORY: tear down a (damaged) replica set wholesale —
        stop every member, remove the set from placement, drop the data
        (reference parser.rs:2046; manager.rs destory_replica_group)."""
        hit = self.meta.find_replica_set(rs_id)
        if hit is None:
            raise CoordinatorError(f"unknown replica set {rs_id}")
        owner, rs = hit
        removed = self.meta.remove_replica_set(rs_id)
        for v in removed:
            if v.node_id == self.node_id or not self.distributed:
                if self._replica_mgr is not None:
                    self._replica_mgr.stop_member(owner, rs_id, v.id)
                self.engine.drop_vnode(owner, v.id)
            else:
                try:
                    self._rpc(v.node_id, "vnode_drop",
                              {"owner": owner, "vnode_id": v.id,
                               "rs_id": rs_id})
                except Exception:
                    stages.count_error("swallow.coord.vnode_drop_rpc")  # unreachable node: placement is authoritative

    def compact_vnode(self, vnode_id: int):
        """COMPACT VNODE on whichever node owns it."""
        hit = self.meta.find_vnode(vnode_id)
        if hit is None:
            raise CoordinatorError(f"unknown vnode {vnode_id}")
        owner, _b, _rs, v = hit
        if v.node_id == self.node_id or not self.distributed:
            vn = self.engine.vnode(owner, vnode_id)
            if vn is not None:
                vn.compact_major()
        else:
            self._rpc(v.node_id, "vnode_compact",
                      {"owner": owner, "vnode_id": vnode_id})
        try:
            from ..server import serving

            serving.invalidate_owner(owner)
        except Exception:
            stages.count_error("serving.invalidate")

    def checksum_group(self, rs_id: int) -> list[tuple[int, int, str]]:
        """Per-replica content checksums for one replica set (reference
        compaction/check.rs ChecksumGroup): replicas must agree regardless
        of their physical flush/compaction state."""
        hit = self.meta.find_replica_set(rs_id)
        if hit is None:
            raise CoordinatorError(f"unknown replica set {rs_id}")
        owner, rs = hit
        out = []
        for v in rs.vnodes:
            if v.node_id == self.node_id or not self.distributed:
                vn = self.engine.vnode(owner, v.id)
                cs = vn.checksum() if vn is not None else ""
            else:
                try:
                    cs = self._rpc(v.node_id, "vnode_checksum",
                                   {"owner": owner, "vnode_id": v.id}) \
                        .get("checksum", "")
                except Exception:
                    cs = "<unreachable>"
            out.append((v.id, v.node_id, cs))
        return out

    # ------------------------------------------------------- integrity
    def _drop_vnode_cache_entries(self, owner: str, vnode_id: int) -> None:
        """Evict every cached ScanBatch of one vnode (quarantine/repair
        changed its on-disk truth; the data_version bump would catch a
        probe, but the entries must not pin memory either)."""
        with self._scan_cache_lock:
            for k in [k for k in self._scan_cache
                      if k[0] == owner and k[1] == vnode_id]:
                self._scan_cache_bytes -= self._scan_cache.pop(k)[2]

    def _quarantine_on_read(self, owner: str, vnode_id: int, exc) -> None:
        """A ChecksumMismatch surfaced during a scan: quarantine the
        offending TSM file and mark the vnode BROKEN. Advisory best-effort
        — the scan is failing over regardless."""
        from ..storage import scrub

        scrub.count("corruptions_detected")
        path = (getattr(exc, "ctx", None) or {}).get("path")
        try:
            v = self.engine.vnode(owner, vnode_id)
            if v is not None and path \
                    and v.quarantine_file(path=path) is not None:
                scrub.count("files_quarantined")
                log.warning("quarantined corrupt file %s on vnode %s",
                            path, vnode_id)
        except Exception:
            log.exception("quarantine of %s failed", path)
        self._drop_vnode_cache_entries(owner, vnode_id)
        self._mark_vnode_broken(vnode_id)
        self._stepdown_quarantined(vnode_id)

    def on_scrub_corruption(self, owner: str, vnode_id: int,
                            paths: list[str]) -> None:
        """Scrubber bridge (storage/scrub.py Scrubber on_corruption): the
        sweep already quarantined the files; finish the read-side story —
        evict cached batches and route scans away until repair."""
        self._drop_vnode_cache_entries(owner, vnode_id)
        self._mark_vnode_broken(vnode_id)
        self._stepdown_quarantined(vnode_id)

    def _stepdown_quarantined(self, vnode_id: int) -> None:
        """If the quarantined replica leads its raft group, step it down:
        file_snapshot() refuses to serve while quarantine evidence exists
        (a quarantined state machine diverged from its applied log), so a
        leader that later needed the snapshot fallback could never catch a
        follower up. A healthy peer should lead until repair. Advisory —
        the refusal alone already guarantees safety."""
        if self._replica_mgr is None:
            return
        try:
            hit = self.meta.find_vnode(vnode_id)
            if hit is not None:
                owner, _bucket, rs, _v = hit
                if self._replica_mgr.stepdown_local(owner, rs, vnode_id):
                    log.warning("stepped down quarantined raft leader "
                                "vnode %s", vnode_id)
        except Exception:
            log.exception("stepdown of quarantined vnode %s failed",
                          vnode_id)

    def anti_entropy_sweep(self) -> dict:
        """Cross-replica repair loop: for every multi-replica set, compare
        content checksums (checksum_group); rebuild each minority-divergent
        replica (bit rot, quarantined files, missed writes) from a majority
        peer via the vnode snapshot machinery, re-verify convergence, and
        clear its BROKEN mark (reference compaction/check.rs checksum admin
        + raft snapshot install, composed into an anti-entropy pass)."""
        report = {"checked": 0, "repaired": [], "failed": []}
        for owner in sorted(getattr(self.meta, "databases", {})):
            tenant, _, db = owner.partition(".")
            try:
                buckets = self.meta.buckets_for(tenant, db)
            except Exception:
                continue
            for bucket in buckets:
                for rs in bucket.shard_group:
                    if len(rs.vnodes) < 2:
                        continue
                    report["checked"] += 1
                    try:
                        self._repair_replica_set(owner, rs, report)
                    except Exception:
                        log.exception("anti-entropy on replica set %s "
                                      "failed", rs.id)
        return report

    def _replica_checksum(self, owner: str, vnode_id: int, node: int) -> str:
        if node == self.node_id or not self.distributed:
            v = self.engine.vnode(owner, vnode_id)
            return v.checksum() if v is not None else ""
        try:
            return self._rpc(node, "vnode_checksum",
                             {"owner": owner, "vnode_id": vnode_id}) \
                .get("checksum", "")
        except Exception:
            return "<unreachable>"

    def _repair_replica_set(self, owner: str, rs, report: dict) -> None:
        from collections import Counter

        from ..storage import scrub

        group = self.checksum_group(rs.id)
        usable = [(vid, nid, cs) for vid, nid, cs in group
                  if cs and cs != "<unreachable>"]
        if len(usable) < 2:
            return
        majority, votes = Counter(
            cs for _, _, cs in usable).most_common(1)[0]
        if votes * 2 <= len(usable):
            return  # no majority: cannot tell who holds the truth
        donors = [(vid, nid) for vid, nid, cs in usable if cs == majority]
        for vid, nid in ((v, n) for v, n, cs in usable if cs != majority):
            ok = False
            for d_vid, d_nid in donors:
                try:
                    data = self._fetch_vnode_snapshot(owner, d_vid, d_nid)
                    if data is None:
                        continue
                    self._install_vnode_snapshot(owner, vid, nid, data)
                    # converged = the repaired replica now matches its
                    # donor's CURRENT checksum (the donor may have taken
                    # writes since the group was sampled)
                    cs2 = self._replica_checksum(owner, vid, nid)
                    ok = bool(cs2) and cs2 != "<unreachable>" \
                        and cs2 == self._replica_checksum(owner, d_vid, d_nid)
                except Exception:
                    log.exception("repair of vnode %s from %s failed",
                                  vid, d_vid)
                    ok = False
                if ok:
                    break
            if not ok and (nid == self.node_id or not self.distributed):
                # no healthy peer could seed this replica: the cold tier
                # is the replica of last resort — rebuild sidecars from
                # the object store and re-vote
                if self._recover_cold(owner, vid):
                    cs2 = self._replica_checksum(owner, vid, nid)
                    ok = bool(cs2) and cs2 == majority
            if ok:
                scrub.count("repairs_ok")
                self._drop_vnode_cache_entries(owner, vid)
                self._clear_vnode_broken(vid)
                report["repaired"].append(vid)
                log.info("anti-entropy repaired vnode %s of %s", vid, owner)
            else:
                scrub.count("repairs_failed")
                report["failed"].append(vid)

    def copy_vnode_to_set(self, rs_id: int, to_node: int) -> int:
        """REPLICA ADD ON <rs> NODE <n>: seed a new replica from the set's
        current leader vnode."""
        hit = self.meta.find_replica_set(rs_id)
        if hit is None:
            raise CoordinatorError(f"unknown replica set {rs_id}")
        _owner, rs = hit
        return self.copy_vnode(rs.leader_vnode_id, to_node)

    def _fetch_vnode_snapshot(self, owner: str, vnode_id: int,
                              node: int) -> bytes | None:
        from .replica import VnodeStateMachine

        if node == self.node_id or not self.distributed:
            v = self.engine.vnode(owner, vnode_id)
            return VnodeStateMachine(v).snapshot() if v is not None else None
        return self._rpc(node, "vnode_snapshot",
                         {"owner": owner, "vnode_id": vnode_id}).get("data")

    def _install_vnode_snapshot(self, owner: str, vnode_id: int, node: int,
                                data: bytes):
        from .replica import VnodeStateMachine

        if node == self.node_id or not self.distributed:
            v = self.engine.open_vnode(owner, vnode_id)
            VnodeStateMachine(v).install_snapshot(data, 0, 0)
        else:
            self._rpc(node, "vnode_install",
                      {"owner": owner, "vnode_id": vnode_id, "data": data})

    # ------------------------------------------------------------ disaster
    # recovery: BACKUP / RESTORE fan-out (storage/backup.py owns the
    # archive-store mechanics; the coordinator supplies cluster routing)
    def backup_database(self, tenant: str, db: str,
                        incremental: bool = False) -> dict:
        """BACKUP DATABASE: cut every leader placement (remote ones via
        the backup_cut RPC) into one consistent, meta-recorded backup."""
        from ..storage import backup

        owner = f"{tenant}.{db}"

        def fetch_cut(vnode_id: int, node_id: int):
            if node_id == self.node_id or not self.distributed:
                return None       # engine.vnode already said "not here"
            reply = self._rpc(node_id, "backup_cut",
                              {"owner": owner, "vnode_id": vnode_id},
                              timeout=60.0)
            return reply.get("cut")

        return backup.create_backup(self.meta, self.engine, tenant, db,
                                    incremental=incremental,
                                    fetch_cut=fetch_cut)

    def restore_database(self, tenant: str, db: str,
                         backup_id: str | None = None,
                         to_ts: int | None = None,
                         new_name: str | None = None) -> dict:
        """RESTORE DATABASE [TO TIMESTAMP] [AS]: manifest → per-placement
        install, routed to whichever node owns each target vnode."""
        from ..storage import backup

        return backup.restore_backup(
            self.meta, self.engine, tenant, db, backup_id=backup_id,
            to_ts=to_ts, new_name=new_name,
            install=self._install_restored_vnode)

    def _install_restored_vnode(self, owner: str, vnode_id: int, vn: dict,
                                snap: dict, entries: list) -> None:
        from ..storage import backup

        hit = self.meta.find_vnode(vnode_id)
        node = hit[3].node_id if hit is not None else self.node_id
        if node == self.node_id or not self.distributed:
            backup.install_vnode(self.engine, owner, vnode_id, snap,
                                 entries)
        else:
            self._rpc(node, "restore_vnode",
                      {"owner": owner, "vnode_id": vnode_id, "snap": snap,
                       "entries": entries}, timeout=60.0)
        # the restored vnode's bytes changed under every cached scan
        self._drop_vnode_cache_entries(owner, vnode_id)

    def _peer_nodes(self, tenant: str, db: str) -> list[int]:
        """Other nodes hosting vnodes of this database."""
        if not self.distributed:
            return []
        nodes = set()
        for bucket in self.meta.buckets_for(tenant, db):
            for rs in bucket.shard_group:
                for v in rs.vnodes:
                    if v.node_id != self.node_id:
                        nodes.add(v.node_id)
        return sorted(nodes)

    def delete_from_table(self, tenant: str, db: str, table: str,
                          tag_domains: ColumnDomains, min_ts: int, max_ts: int):
        """Replicated sets delete through the raft log (the entry carries
        the tag predicate, resolved at apply time on every replica, so a
        down follower replays it on rejoin); single-replica vnodes delete
        directly, and an unreachable owner fails the statement — a silent
        skip would resurrect rows later."""
        owner = f"{tenant}.{db}"
        if not self.distributed:
            self.delete_local(owner, table, tag_domains, min_ts, max_ts)
            return
        import msgpack

        from ..storage.wal import WalEntryType
        from .net import RpcError, RpcUnavailable

        payload = msgpack.packb(
            {"table": table, "doms": tag_domains.to_wire(),
             "min_ts": min_ts, "max_ts": max_ts}, use_bin_type=True)
        failed = []
        for bucket in self.meta.buckets_for(tenant, db):
            for rs in bucket.shard_group:
                if len(rs.vnodes) > 1:
                    self._write_replicated(
                        owner, rs, WalEntryType.DELETE_TIME_RANGE, payload,
                        sync=False)
                    continue
                for v in rs.vnodes:
                    if v.node_id == self.node_id:
                        self.delete_vnode_local(owner, v.id, table,
                                                tag_domains, min_ts, max_ts)
                    else:
                        try:
                            self._rpc(v.node_id, "delete_vnode_range", {
                                "owner": owner, "vnode_id": v.id,
                                "table": table,
                                "doms": tag_domains.to_wire(),
                                "min_ts": min_ts, "max_ts": max_ts})
                        except (RpcUnavailable, RpcError) as e:
                            failed.append((v.node_id, e))
        if failed:
            raise CoordinatorError(
                f"delete failed on nodes {[n for n, _ in failed]}: "
                f"{failed[0][1]}")

    def delete_vnode_local(self, owner: str, vnode_id: int, table: str,
                           doms: ColumnDomains, min_ts: int, max_ts: int):
        v = self.engine.vnode(owner, vnode_id)
        if v is None:
            return
        sids = None
        if not doms.is_all:
            sids = v.index.get_series_ids_by_domains(table, doms)
            if len(sids) == 0:
                return
        v.delete_time_range(table, sids, min_ts, max_ts)

    def delete_local(self, owner: str, table: str,
                     tag_domains: ColumnDomains, min_ts: int, max_ts: int):
        for v in self.engine.local_vnodes(owner):
            sids = None
            if not tag_domains.is_all:
                sids = v.index.get_series_ids_by_domains(table, tag_domains)
                if len(sids) == 0:
                    continue
            v.delete_time_range(table, sids, min_ts, max_ts)

    def tag_values(self, tenant: str, db: str, table: str, tag_key: str) -> list[str]:
        """Index fan-out; an unreachable owner fails the query — a silent
        skip would return partial values as if complete."""
        out = set(self.tag_values_local(f"{tenant}.{db}", table, tag_key))
        from .net import RpcError, RpcUnavailable

        for nid in self._peer_nodes(tenant, db):
            try:
                r = self._rpc(nid, "tag_values", {
                    "owner": f"{tenant}.{db}", "table": table,
                    "tag_key": tag_key})
                out.update(r.get("values", []))
            except (RpcUnavailable, RpcError) as e:
                raise CoordinatorError(
                    f"tag scan failed on node {nid}: {e}") from e
        return sorted(out)

    def tag_values_local(self, owner: str, table: str, tag_key: str) -> list[str]:
        out = set()
        for v in self.engine.local_vnodes(owner):
            out.update(v.index.tag_values(table, tag_key))
        return sorted(out)

    def series_keys(self, tenant: str, db: str, table: str,
                    tag_domains: ColumnDomains | None = None) -> list:
        doms = tag_domains or ColumnDomains.all()
        keys = {}
        for k in self.series_keys_local(f"{tenant}.{db}", table, doms):
            keys[(k.table, k.tags)] = k
        from ..models.series import SeriesKey
        from .net import RpcError, RpcUnavailable

        for nid in self._peer_nodes(tenant, db):
            try:
                r = self._rpc(nid, "series_keys", {
                    "owner": f"{tenant}.{db}", "table": table,
                    "doms": doms.to_wire()})
                for raw in r.get("keys", []):
                    k = SeriesKey.decode(raw)
                    keys[(k.table, k.tags)] = k
            except (RpcUnavailable, RpcError) as e:
                raise CoordinatorError(
                    f"series scan failed on node {nid}: {e}") from e
        return [keys[k] for k in sorted(keys)]

    def series_keys_local(self, owner: str, table: str,
                          doms: ColumnDomains) -> list:
        keys = {}
        for v in self.engine.local_vnodes(owner):
            for sid in v.index.get_series_ids_by_domains(table, doms):
                k = v.index.get_series_key(int(sid))
                if k is not None:
                    keys[(k.table, k.tags)] = k
        return [keys[k] for k in sorted(keys)]


def _batch_nbytes(b: ScanBatch) -> int:
    """Host footprint of a cached ScanBatch (cache byte accounting).
    Dictionary-encoded string columns count codes + a per-unique-value
    estimate; exactness doesn't matter, monotonicity does."""
    n = int(b.ts.nbytes) + int(b.sid_ordinal.nbytes) \
        + int(b.series_ids.nbytes)
    for _name, (_vt, vals, valid) in b.fields.items():
        codes = getattr(vals, "codes", None)
        if codes is not None:   # DictArray
            n += int(codes.nbytes)
            n += sum(len(str(x)) + 49 for x in vals.values)
        else:
            n += int(vals.nbytes)
        n += int(valid.nbytes)
    return n
