"""Meta cluster service: the catalog's cross-process plane.

Role-parity with the reference's meta HTTP API + client (meta/src/service/
http.rs:58-236 /read /write /watch /dump /restore endpoints; meta/src/
client.rs:83-140 MetaHttpClient; meta/src/model/meta_admin.rs AdminMeta
watch loops): one MetaService process owns the authoritative MetaStore;
every data/query node runs a MetaClient holding a full local cache that
serves reads, forwards mutations, and follows a long-poll watch stream.

Wire model (over parallel.net msgpack-HTTP):
  meta_read   {}                      → {version, snapshot}
  meta_write  {method, kwargs}        → {version, snapshot, events, result}
  meta_watch  {after, timeout}        → {version, snapshot, events}   (long-poll)
  meta_dump   {}                      → {snapshot}
  meta_restore{snapshot}              → {version}

Mutations are dispatched by method name onto the authoritative store with
schema-typed arguments rehydrated from their dict forms; the full snapshot
rides back on every write (meta mutations are rare and the state is small —
same trade the reference makes shipping watch logs + periodic full syncs).
"""
from __future__ import annotations

import threading
import time

from .. import errors as _errors
from .. import faults
from ..utils import stages
from ..errors import CnosError, MetaError
from ..models.meta_data import BucketInfo
from ..models.schema import DatabaseSchema, TenantOptions, TskvTableSchema
from .meta import MetaStore
from .net import RpcError, RpcServer, rpc_call

faults.register_point("meta.propose", __name__, scope="cluster",
                      desc="meta mutation proposed to the replicated log")
faults.register_point("meta.apply", __name__, scope="cluster",
                      desc="committed meta entry applied to the store")
from ..utils import lockwatch

# mutation → {arg name → rehydrator} applied server-side
_ARG_HYDRATORS = {
    "create_tenant": {"options": lambda d: TenantOptions.from_dict(d) if d else None},
    "create_database": {"schema": DatabaseSchema.from_dict},
    "create_table": {"schema": TskvTableSchema.from_dict},
    "update_table": {"schema": TskvTableSchema.from_dict},
}

MUTATIONS = frozenset([
    "create_tenant", "drop_tenant", "create_user", "drop_user", "alter_user",
    "add_member", "remove_member", "create_database", "alter_database",
    "drop_database", "create_table", "update_table", "drop_table",
    "create_stream", "drop_stream", "create_matview", "drop_matview",
    "locate_bucket_for_write",
    "expire_buckets", "register_node", "report_heartbeat",
    "create_role", "drop_role", "grant_db_privilege", "revoke_db_privilege",
    "create_external_table", "drop_external_table",
    "update_vnode", "add_replica_vnode", "remove_replica_vnode",
    "promote_replica", "remove_replica_set",
    "recover_tenant", "recover_database", "recover_table", "purge_trash",
    "record_backup", "prune_backups",
])


def _dehydrate(result):
    from ..models.meta_data import VnodeInfo

    if isinstance(result, BucketInfo):
        return {"_type": "bucket", "v": result.to_dict()}
    if isinstance(result, list) and result and isinstance(result[0], BucketInfo):
        return {"_type": "buckets", "v": [b.to_dict() for b in result]}
    if isinstance(result, list) and result \
            and isinstance(result[0], VnodeInfo):
        return {"_type": "vnodes", "v": [x.to_dict() for x in result]}
    return {"_type": "raw", "v": result}


def _rehydrate(wrapped):
    t, v = wrapped["_type"], wrapped["v"]
    if t == "bucket":
        return BucketInfo.from_dict(v)
    if t == "buckets":
        return [BucketInfo.from_dict(b) for b in v]
    if t == "vnodes":
        from ..models.meta_data import VnodeInfo

        return [VnodeInfo.from_dict(x) for x in v]
    return v


class MetaStateMachine:
    """ApplyStorage over a MetaStore — the replicated-meta analog of the
    reference's heed state machine (meta/src/store/storage.rs:63
    ApplyStorage::apply → process_write_command). Commands are
    (method, kwargs) msgpack; apply returns live through a per-index
    result slot so the proposing leader can answer the client."""

    def __init__(self, store: MetaStore):
        self.store = store
        self._results: dict[str, object] = {}   # req_id → outcome
        # bounded FIFO of req ids, seeded from the store so dedup survives
        # restarts (the list is persisted atomically with applied_index)
        self._seen: dict[str, None] = dict.fromkeys(store.recent_req_ids)

    def _arm(self, req_id: str) -> None:
        """Record req_id in the dedup set AND the store's persisted list
        (written out by the mutation's own _persist, same atomic file)."""
        if req_id in self._seen:
            return
        self._seen[req_id] = None
        ids = self.store.recent_req_ids
        ids.append(req_id)
        if len(ids) > 2048:
            for k in ids[:1024]:
                self._seen.pop(k, None)
            del ids[:1024]

    def apply(self, entry):
        import msgpack as _mp

        if entry.index <= self.store.applied_index:
            # restart replay: the store already persisted this mutation
            # (applied_index rides inside the same atomic meta.json write).
            # Still ARM the dedup set: a retried duplicate of this entry
            # may sit later in the log, and _seen must reject it even when
            # the original's req_id predates this process
            req_id = _mp.unpackb(entry.data, raw=False)[2]
            with self.store.lock:
                self._arm(req_id)
            return
        method, kwargs, req_id = _mp.unpackb(entry.data, raw=False)
        if faults.ENABLED:
            # injected environmental failure: must fire BEFORE applied_index
            # advances, so the raft apply loop's stall-and-retry re-executes
            # this entry instead of skipping it as already-replayed
            faults.fire("meta.apply", method=method, index=entry.index)
        with self.store.lock:
            self.store.applied_index = entry.index
        if req_id in self._seen:
            # retried proposal whose first copy DID commit (propose timeout
            # or leadership change): applying twice would double-mutate.
            # Persist the watermark NOW so a restart replaying this
            # duplicate still skips it
            with self.store.lock:
                self.store._persist()
            return
        with self.store.lock:
            self._arm(req_id)
        for name, fix in _ARG_HYDRATORS.get(method, {}).items():
            if name in kwargs:
                kwargs[name] = fix(kwargs[name])
        # path-less stores have no durable copy to rollback-reload from, so
        # capture the pre-mutation state up front (cheap: meta state is
        # small and mutations are rare)
        pre_state = None
        if not self.store.path:
            with self.store.lock:
                pre_state = self.store._to_dict()
        try:
            result = getattr(self.store, method)(**kwargs)
            self._results[req_id] = ("ok", result)
        except CnosError as e:
            # deterministic validation failures replicate as no-ops —
            # every member reaches the same outcome from the same state
            self._results[req_id] = ("err", e)
        except Exception:
            # environmental failure (e.g. disk-full inside _persist):
            # applying "as a no-op" would silently diverge this member
            # from the group. Re-raise — the raft apply loop stalls at
            # this index and retries, keeping last_applied honest.
            self._rollback(entry, req_id, pre_state)
            raise
        if len(self._results) > 256:
            for k in list(self._results)[:128]:
                del self._results[k]

    def _rollback(self, entry, req_id: str, pre_state: dict | None) -> None:
        """Undo a half-applied mutation after an environmental failure.

        Store mutations mutate memory FIRST and persist second, so a
        failed _persist leaves the in-memory state ahead of disk; the
        raft stall-and-retry would then re-execute the mutation on top
        of its own partial effect (e.g. a second phantom replica vnode).
        Reload the last durable state — or the captured pre-apply state
        for path-less stores — so the retry starts clean."""
        with self.store.lock:
            restored = False
            try:
                import os as _os

                if pre_state is not None:
                    self.store._from_dict(pre_state)
                    restored = True
                elif self.store.path and _os.path.exists(self.store.path):
                    self.store._load()
                    restored = True
                if restored:
                    self._seen = dict.fromkeys(self.store.recent_req_ids)
            except Exception:
                stages.count_error("swallow.metasvc.restore")
            if not restored:
                # disk unreadable too: at least rewind the watermark and
                # dedup arming so the retry is not mistaken for a dup
                # (memory may keep a partial effect — but with the disk
                # gone this member is about to crash out anyway)
                self.store.applied_index = entry.index - 1
                self._seen.pop(req_id, None)
                if self.store.recent_req_ids \
                        and self.store.recent_req_ids[-1] == req_id:
                    self.store.recent_req_ids.pop()

    def take_result(self, req_id: str):
        """Missing slot = the result is unknowable (deduplicated retry or
        eviction) — that must surface as an uncertain-outcome error, never
        as a fabricated success."""
        hit = self._results.pop(req_id, None)
        if hit is None:
            return ("err", MetaError(
                "outcome unknown: the proposal was deduplicated or its "
                "result slot expired — re-check state before retrying"))
        return hit

    def snapshot(self) -> bytes:
        import msgpack as _mp

        with self.store.lock:
            return _mp.packb({"state": self.store._to_dict(),
                              "version": self.store.version},
                             use_bin_type=True)

    def install_snapshot(self, data: bytes, last_index: int, last_term: int):
        import msgpack as _mp

        obj = _mp.unpackb(data, raw=False, strict_map_key=False)
        with self.store.lock:
            self.store._from_dict(obj["state"])
            self.store.version = max(self.store.version, obj["version"])
            # the snapshot replaced recent_req_ids: reseed the dedup set
            # or retried duplicates sitting in the log AFTER the snapshot
            # point would re-execute on this member only
            self._seen = dict.fromkeys(self.store.recent_req_ids)
            self.store._persist()
        self.store._notify("restore")


class MetaService:
    """Hosts the authoritative MetaStore over RPC — standalone, or as one
    member of a replicated meta raft group (reference: the meta crate runs
    a single-group openraft cluster; `cnosdb-meta` binary).

    With `peers` = {node_id: "host:port"} and `node_id` set, mutations go
    through raft: the leader proposes (method, kwargs) entries, every
    member applies them to its own MetaStore, and non-leader members proxy
    client writes to the current leader."""

    def __init__(self, store: MetaStore, host: str = "127.0.0.1",
                 port: int = 0, node_id: int | None = None,
                 peers: dict[int, str] | None = None,
                 raft_dir: str | None = None):
        self.store = store
        self.node_id = node_id
        self.peers = dict(peers or {})
        self.raft: object | None = None
        self.server = RpcServer(host, port, {
            "ping": lambda p: {"ok": True, "version": store.version},
            "meta_read": self._read,
            "meta_write": self._write,
            "meta_watch": self._watch,
            "meta_beat": self._beat,
            "meta_dump": lambda p: {"snapshot": self.store._to_dict()},
            "meta_restore": self._restore,
            "raft_msg": self._raft_msg,   # HttpTransport peer messages
            "meta_status": self._status,
        })
        self.addr = self.server.addr
        if node_id is not None and len(self.peers) > 1:
            self._build_raft(raft_dir)

    def _build_raft(self, raft_dir: str | None):
        import os as _os

        from ..storage.wal import Wal
        from .raft import HttpTransport, MemoryLogStore, RaftNode, WalLogStore

        def resolver(_gid, peer_id):
            return self.peers.get(peer_id)

        if raft_dir:
            _os.makedirs(raft_dir, exist_ok=True)
            log = WalLogStore(Wal(_os.path.join(raft_dir, "wal")),
                              _os.path.join(raft_dir, "hardstate"))
        else:
            log = MemoryLogStore()
        self.sm = MetaStateMachine(self.store)
        self.raft = RaftNode("meta", self.node_id, sorted(self.peers),
                             log, self.sm, HttpTransport(resolver),
                             election_timeout=(0.3, 0.6),
                             heartbeat_interval=0.1,
                             initial_applied=self.store.applied_index)

    def start(self):
        self.server.start()
        return self

    def stop(self):
        if self.raft is not None:
            self.raft.stop()
        self.server.stop()

    def _raft_msg(self, p):
        if self.raft is None:
            return {"reply": None}
        return {"reply": self.raft.handle_message(p["msg"])}

    def _status(self, p):
        out = {"node_id": self.node_id, "version": self.store.version,
               "raft": self.raft is not None}
        if self.raft is not None:
            out.update(self.raft.metrics())
        return out


    def _read(self, p):
        with self.store.lock:
            return {"version": self.store.version,
                    "snapshot": self.store._to_dict()}

    def _write(self, p):
        method = p["method"]
        if method not in MUTATIONS:
            raise MetaError(f"not a meta mutation: {method}")
        if self.raft is not None:
            return self._write_raft(p, method)
        before = self.store.version
        kwargs = dict(p.get("kwargs") or {})
        for name, fix in _ARG_HYDRATORS.get(method, {}).items():
            if name in kwargs:
                kwargs[name] = fix(kwargs[name])
        result = getattr(self.store, method)(**kwargs)
        return self._write_reply(before, result)

    def _write_reply(self, before: int, result):
        with self.store.lock:
            out = {"version": self.store.version,
                   "events": [[v, e, kw] for v, e, kw in
                              self.store.events_since(before)],
                   "result": _dehydrate(result)}
            # the snapshot is O(catalog); omit it when nothing changed
            if self.store.version != before:
                out["snapshot"] = self.store._to_dict()
            return out

    def _write_raft(self, p, method: str):
        """Propose the mutation through the meta raft group; non-leaders
        proxy the whole request ONCE to the current leader (reference
        MetaHttpClient retries on the leader, meta/src/client.rs).
        Retried proposals carry one request id so the state machine
        dedups copies whose earlier append did commit."""
        import secrets as _secrets

        import msgpack as _mp

        from ..errors import ReplicationError
        from .raft import NotLeader

        req_id = p.get("_req_id") or _secrets.token_hex(8)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if self.raft.is_leader():
                before = self.store.version
                kwargs = dict(p.get("kwargs") or {})
                if method == "locate_bucket_for_write":
                    if not kwargs.get("nodes"):
                        # pin placement candidates at PROPOSAL time: apply
                        # must be deterministic across members, liveness
                        # is not
                        kwargs["nodes"] = self.store.placement_candidates()
                    if kwargs.get("now_ns") is None:
                        # the TTL expired-bucket check reads the clock —
                        # pinned here so every member (and log replay)
                        # accepts/rejects identically
                        kwargs["now_ns"] = time.time_ns()
                # wall-clock reads are likewise pinned at proposal: every
                # member must stamp/purge trash identically
                if method in ("drop_database", "drop_table",
                              "drop_tenant") and kwargs.get("at") is None:
                    kwargs["at"] = time.time()
                if method == "purge_trash" and kwargs.get("now") is None:
                    kwargs["now"] = time.time()
                if faults.ENABLED:
                    faults.fire("meta.propose", method=method)
                try:
                    self.raft.propose(
                        1, _mp.packb([method, kwargs, req_id],
                                     use_bin_type=True))
                except (NotLeader, ReplicationError):
                    time.sleep(0.1)
                    continue
                status, result = self.sm.take_result(req_id)
                if status == "err":
                    raise result
                return self._write_reply(before, result)
            # proxy by member id, never by address-string comparison (a
            # stepped-down leader's stale leader_id may still be itself,
            # and configured peer strings need not match the bound addr)
            lid = self.raft.leader_id
            if lid is not None and lid != self.node_id \
                    and not p.get("_proxied"):
                addr = self.peers.get(lid)
                if addr:
                    from .net import RpcUnavailable

                    try:
                        return rpc_call(addr, "meta_write",
                                        {**p, "_proxied": True,
                                         "_req_id": req_id}, timeout=10.0)
                    except RpcUnavailable:
                        pass  # leader moved/unreachable: re-evaluate
                    except RpcError as e:
                        # leader-side APPLICATION error: unwrap to the
                        # original class — swallowing it would turn a
                        # failed DDL into a silent success
                        _raise_remote(e)
            time.sleep(0.1)
        raise MetaError("meta raft group has no leader")

    def _beat(self, p):
        """Liveness beat — deliberately NOT a meta_write: no version bump,
        no snapshot serialization on the hot 3s path. In a replicated meta
        group, beats forward to the LEADER (it makes placement decisions);
        liveness stays runtime-local, never raft state."""
        # ALWAYS record locally first: if this member is later elected it
        # must not start with an empty liveness view (bucket placement
        # would fall back to all registered nodes, dead ones included)
        self.store.report_heartbeat(int(p["node_id"]))
        if self.raft is not None and not self.raft.is_leader() \
                and not p.get("_fwd"):
            lid = self.raft.leader_id
            addr = self.peers.get(lid) if lid not in (None, self.node_id) \
                else None
            if addr:
                try:
                    rpc_call(addr, "meta_beat", {**p, "_fwd": True},
                             timeout=5.0)
                except Exception:
                    stages.count_error("swallow.metasvc.beat_forward")  # beat is best-effort
        return {"ok": True}

    def _watch(self, p):
        after = int(p.get("after", 0))
        timeout = min(float(p.get("timeout", 25.0)), 55.0)
        version = self.store.wait_version(after, timeout)
        with self.store.lock:
            return {"version": version,
                    "snapshot": self.store._to_dict(),
                    "events": [[v, e, kw] for v, e, kw in
                               self.store.events_since(after)]}

    def _restore(self, p):
        with self.store.lock:
            self.store._from_dict(p["snapshot"])
            self.store._persist()
        self.store._notify("restore")
        with self.store.lock:
            return {"version": self.store.version}


def _raise_remote(e: RpcError):
    """Map a remote error name back to its local exception class.

    RpcError text is "<method>@<addr>: <ErrClass>: <message>"."""
    parts = str(e).split(": ", 2)
    if len(parts) == 3:
        cls = getattr(_errors, parts[1], None)
        if isinstance(cls, type) and issubclass(cls, CnosError):
            raise cls(parts[2])
    raise e


class MetaClient:
    """Full-cache meta client (reference AdminMeta + MetaHttpClient).

    Reads serve from the local MetaStore replica; mutations forward to the
    MetaService and synchronously apply the returned snapshot so callers get
    read-your-writes; a daemon watch thread keeps the cache fresh and fires
    the same watcher callbacks MetaStore would locally."""

    def __init__(self, addr: str, node_id: int = 0, watch: bool = True):
        self.addr = addr
        self.node_id = node_id
        self.cache = MetaStore(path=None, node_id=node_id, register_self=False)
        self._watchers: list = []
        self._seen_version = 0
        self._sync_lock = lockwatch.Lock("metasvc.sync")
        self._stop = threading.Event()
        self.refresh()
        self._watch_thread = None
        if watch:
            self._watch_thread = threading.Thread(target=self._watch_loop,
                                                  daemon=True)
            self._watch_thread.start()
        self._hb_thread = None

    # ---------------------------------------------------------------- sync
    def refresh(self):
        r = rpc_call(self.addr, "meta_read", timeout=10.0)
        self._apply(r["version"], r["snapshot"], [])
        # the snapshot already reflects every event up to its version; a
        # watch must never replay history from before it (a replayed
        # drop_table event would destroy live re-created data)
        with self._sync_lock:
            self._seen_version = max(self._seen_version, r["version"])

    def _apply(self, version: int, snapshot: dict | None, events: list):
        fire = []
        with self._sync_lock:
            with self.cache.lock:
                if snapshot is not None and version > self.cache.version:
                    self.cache._from_dict(snapshot)
                    self.cache.version = version
            for v, event, kw in events:
                if v > self._seen_version:
                    self._seen_version = v
                    fire.append((event, kw))
        for event, kw in fire:
            for w in list(self._watchers):
                try:
                    w(event, kw)
                except Exception:
                    stages.count_error("swallow.metasvc.watcher_cb")

    def _watch_loop(self):
        while not self._stop.is_set():
            try:
                r = rpc_call(self.addr, "meta_watch",
                             {"after": self._seen_version, "timeout": 25.0},
                             timeout=30.0)
                self._apply(r["version"], r["snapshot"], r["events"])
            except Exception:
                if self._stop.wait(1.0):
                    return

    def start_heartbeat(self, interval: float = 3.0):
        def beat():
            while not self._stop.wait(interval):
                try:
                    rpc_call(self.addr, "meta_beat",
                             {"node_id": self.node_id}, timeout=5.0)
                except Exception:
                    stages.count_error("swallow.metasvc.self_beat")
        self._hb_thread = threading.Thread(target=beat, daemon=True)
        self._hb_thread.start()

    def close(self):
        self._stop.set()

    # ------------------------------------------------------------ mutations
    def _forward(self, method: str, **kwargs):
        try:
            r = rpc_call(self.addr, "meta_write",
                         {"method": method, "kwargs": kwargs}, timeout=10.0)
        except RpcError as e:
            _raise_remote(e)
        self._apply(r["version"], r.get("snapshot"), r["events"])
        return _rehydrate(r["result"])

    def create_tenant(self, name, options=None):
        return self._forward("create_tenant", name=name,
                             options=options.to_dict() if options else None)

    def drop_tenant(self, name):
        return self._forward("drop_tenant", name=name)

    def create_user(self, name, password="", admin=False, comment=""):
        return self._forward("create_user", name=name, password=password,
                             admin=admin, comment=comment)

    def drop_user(self, name):
        return self._forward("drop_user", name=name)

    def alter_user(self, name, password=None):
        return self._forward("alter_user", name=name, password=password)

    def add_member(self, tenant, user, role="member"):
        return self._forward("add_member", tenant=tenant, user=user, role=role)

    def remove_member(self, tenant, user):
        return self._forward("remove_member", tenant=tenant, user=user)

    def create_database(self, schema, if_not_exists=False):
        return self._forward("create_database", schema=schema.to_dict(),
                             if_not_exists=if_not_exists)

    def alter_database(self, tenant, db, **opts):
        return self._forward("alter_database", tenant=tenant, db=db, **opts)

    def drop_database(self, tenant, db, if_exists=True):
        return self._forward("drop_database", tenant=tenant, db=db,
                             if_exists=if_exists)

    def create_table(self, schema, if_not_exists=False):
        return self._forward("create_table", schema=schema.to_dict(),
                             if_not_exists=if_not_exists)

    def update_table(self, schema):
        return self._forward("update_table", schema=schema.to_dict())

    def drop_table(self, tenant, db, table):
        return self._forward("drop_table", tenant=tenant, db=db, table=table)

    def create_stream(self, name, definition):
        return self._forward("create_stream", name=name, definition=definition)

    def drop_stream(self, name):
        return self._forward("drop_stream", name=name)

    def create_matview(self, name, definition):
        return self._forward("create_matview", name=name,
                             definition=definition)

    def drop_matview(self, name):
        return self._forward("drop_matview", name=name)

    def register_node(self, node_id, grpc_addr="", http_addr=""):
        return self._forward("register_node", node_id=node_id,
                             grpc_addr=grpc_addr, http_addr=http_addr)

    def create_role(self, tenant, name, inherit="member"):
        return self._forward("create_role", tenant=tenant, name=name,
                             inherit=inherit)

    def drop_role(self, tenant, name):
        return self._forward("drop_role", tenant=tenant, name=name)

    def grant_db_privilege(self, tenant, role, db, level):
        return self._forward("grant_db_privilege", tenant=tenant, role=role,
                             db=db, level=level)

    def revoke_db_privilege(self, tenant, role, db):
        return self._forward("revoke_db_privilege", tenant=tenant, role=role,
                             db=db)

    def create_external_table(self, tenant, db, name, path, fmt="csv",
                              header=True, if_not_exists=False,
                              options=None):
        return self._forward("create_external_table", tenant=tenant, db=db,
                             name=name, path=path, fmt=fmt, header=header,
                             if_not_exists=if_not_exists,
                             options=dict(options or {}))

    def drop_external_table(self, tenant, db, name):
        return self._forward("drop_external_table", tenant=tenant, db=db,
                             name=name)

    def update_vnode(self, vnode_id, node_id=None, status=None):
        return self._forward("update_vnode", vnode_id=vnode_id,
                             node_id=node_id, status=status)

    def add_replica_vnode(self, rs_id, node_id, status=0):
        return self._forward("add_replica_vnode", rs_id=rs_id,
                             node_id=node_id, status=status)

    def remove_replica_vnode(self, vnode_id):
        return self._forward("remove_replica_vnode", vnode_id=vnode_id)

    def promote_replica(self, vnode_id):
        return self._forward("promote_replica", vnode_id=vnode_id)

    def remove_replica_set(self, rs_id):
        return self._forward("remove_replica_set", rs_id=rs_id)

    def recover_tenant(self, name):
        return self._forward("recover_tenant", name=name)

    def recover_database(self, tenant, db):
        return self._forward("recover_database", tenant=tenant, db=db)

    def recover_table(self, tenant, db, table):
        return self._forward("recover_table", tenant=tenant, db=db,
                             table=table)

    def purge_trash(self, older_than_s=0.0):
        return self._forward("purge_trash", older_than_s=older_than_s)

    def record_backup(self, owner, entry):
        return self._forward("record_backup", owner=owner, entry=entry)

    def prune_backups(self, owner, keep):
        return self._forward("prune_backups", owner=owner, keep=keep)

    def expire_buckets(self, tenant, db, now_ns):
        return self._forward("expire_buckets", tenant=tenant, db=db,
                             now_ns=now_ns)

    def locate_bucket_for_write(self, tenant, db, ts):
        """Cache-first: only the bucket-creating miss pays an RPC."""
        owner = f"{tenant}.{db}"
        with self.cache.lock:
            for b in self.cache.buckets.get(owner, []):
                if b.contains(ts):
                    return b
        return self._forward("locate_bucket_for_write",
                             tenant=tenant, db=db, ts=ts)

    # ------------------------------------------------------------ watchers
    def watch(self, callback):
        self._watchers.append(callback)

    # ------------------------------------------------------------ reads
    def __getattr__(self, name):
        # read-only methods + attributes delegate to the cache replica
        return getattr(self.cache, name)
