"""Data-node RPC service: the node-to-node data plane.

Role-parity with the reference's gRPC TSKVService + RaftService servers
(main/src/rpc/tskv.rs:214-397 RaftWrite/QueryRecordBatch/TagScan/Admin,
replication/src/network_grpc.rs RaftCBServer): every data node hosts one
RpcServer (parallel.net) answering

  raft_msg        raft consensus messages for replica groups on this node
  write_vnode     single-replica point writes for a local vnode
  write_replica   propose on a replica-set whose raft leader lives here
  scan_vnode      scan one local vnode → Arrow IPC bytes
  tag_values / series_keys / delete_from_table   index/admin fan-out
  status          node liveness + vnode inventory

The service owns nothing itself: it is a thin dispatch onto the node's
Coordinator / ReplicaGroupManager / engine, so local and remote execution
share one code path.
"""
from __future__ import annotations

from ..models.points import WriteBatch
from ..models.predicate import ColumnDomains, TimeRanges
from .coordinator import Coordinator, PlacedSplit
from .ipc import encode_scan_batch
from .net import RpcServer
from .raft import NotLeader


class DataNodeService:
    def __init__(self, coord: Coordinator, host: str = "127.0.0.1",
                 port: int = 0):
        self.coord = coord
        self.server = RpcServer(host, port, node_id=coord.node_id, handlers={
            "ping": self._ping,
            "status": self._status,
            "raft_msg": self._raft_msg,
            "write_vnode": self._write_vnode,
            "write_replica": self._write_replica,
            "scan_vnode": self._scan_vnode,
            "cancel_scan": self._cancel_scan,
            "tag_values": self._tag_values,
            "series_keys": self._series_keys,
            "delete_vnode_range": self._delete_vnode_range,
            "vnode_snapshot": self._vnode_snapshot,
            "backup_cut": self._backup_cut,
            "restore_vnode": self._restore_vnode,
            "vnode_install": self._vnode_install,
            "vnode_drop": self._vnode_drop,
            "vnode_compact": self._vnode_compact,
            "vnode_token": self._vnode_token,
            "vnode_checksum": self._vnode_checksum,
            "matview_partials": self._matview_partials,
            "replica_change_membership": self._replica_change_membership,
            "replica_stepdown": self._replica_stepdown,
            "replica_progress": self._replica_progress,
            "replica_stop_member": self._replica_stop_member,
        })
        self.addr = self.server.addr

    def start(self):
        self.server.start()
        return self

    def stop(self):
        self.server.stop()

    # ------------------------------------------------------------ handlers
    def _ping(self, p):
        return {"ok": True, "node_id": self.coord.node_id}

    def _status(self, p):
        inv: dict[str, list[int]] = {}
        for (owner, vid) in list(self.coord.engine.vnodes):
            inv.setdefault(owner, []).append(vid)
        return {"node_id": self.coord.node_id,
                "vnodes": {o: sorted(vs) for o, vs in inv.items()}}

    def _raft_msg(self, p):
        reply = self.coord.replica_manager().handle_raft_msg(
            p["group"], p["to"], p["msg"])
        return {"reply": reply}

    def _matview_partials(self, p):
        """Sealed rollup partials for one LOCAL vnode (coordinator-side
        subsumption rewrite fan-out)."""
        me = getattr(self.coord, "matview_maintainer", None)
        if me is None:
            return {"hwm": None, "rows": []}
        return me.partials_for(p["view"], p["owner"], p["vnode_id"])

    def _write_vnode(self, p):
        batch = WriteBatch.decode(p["data"])
        self.coord.engine.write(p["owner"], p["vnode_id"], batch,
                                sync=p.get("sync", False))
        return {"ok": True}

    def _write_replica(self, p):
        from ..models.meta_data import ReplicationSet

        rs = ReplicationSet.from_dict(p["rs"])
        try:
            idx = self.coord.replica_manager().propose_local(
                p["owner"], rs, p["entry_type"], p["data"],
                sync=p.get("sync", False))
        except NotLeader as e:
            return {"ok": False, "hint": e.args[0] if e.args else None}
        return {"ok": True, "index": idx}

    def _scan_vnode(self, p):
        if p.get("fp"):
            # serving-plane-tagged scan: lets cluster-wide dashboards
            # attribute remote work to the originating query family
            from ..utils import stages

            stages.count("serving.remote_fp")
        split = PlacedSplit(
            p["owner"], p["vnode_id"], p["table"],
            TimeRanges.from_wire(p["trs"]),
            ColumnDomains.from_wire(p["doms"]))
        b = self.coord._scan_local(split, p.get("field_names"))
        if b is None:
            return {"ipc": None}
        return {"ipc": encode_scan_batch(b)}

    def _vnode_token(self, p):
        """Serving-plane result-cache validation: the LOCAL vnode's
        ScanToken, so a coordinating node can key / revalidate cached
        results whose data lives here."""
        v = self.coord.engine.vnode(p["owner"], p["vnode_id"])
        if v is None:
            return {"token": None}
        t = v.scan_token()
        return {"token": {"data_version": t.data_version,
                          "destructive_version": t.destructive_version,
                          "file_ids": sorted(t.file_ids),
                          "mem_seq": t.mem_seq}}

    def _cancel_scan(self, p):
        """Best-effort cancellation fan-in (reference kill_query over the
        coordinator's admin plane): flip the cancel flag of every handler
        currently working for this qid (registered by the RPC server on
        dispatch) and tombstone the qid so queued/delayed work for it is
        rejected on dequeue instead of executed."""
        from ..utils import deadline as deadline_mod

        qid = p.get("qid")
        if not qid:
            return {"ok": False, "cancelled": 0}
        n = deadline_mod.CANCELS.cancel(str(qid))
        return {"ok": True, "cancelled": n}

    def _tag_values(self, p):
        return {"values": self.coord.tag_values_local(
            p["owner"], p["table"], p["tag_key"])}

    def _series_keys(self, p):
        keys = self.coord.series_keys_local(
            p["owner"], p["table"], ColumnDomains.from_wire(p["doms"]))
        return {"keys": [k.encode() for k in keys]}

    def _delete_vnode_range(self, p):
        self.coord.delete_vnode_local(
            p["owner"], p["vnode_id"], p["table"],
            ColumnDomains.from_wire(p["doms"]), p["min_ts"], p["max_ts"])
        return {"ok": True}

    # vnode snapshot shipping (reference rpc/tskv.rs DownloadFile — the
    # MOVE/COPY VNODE data plane; logical snapshots here)
    def _vnode_snapshot(self, p):
        from .replica import VnodeStateMachine

        v = self.coord.engine.vnode(p["owner"], p["vnode_id"])
        if v is None:
            return {"data": None}
        return {"data": VnodeStateMachine(v).snapshot()}

    def _backup_cut(self, p):
        """BACKUP fan-out: one local vnode's consistency cut (files +
        digests + flushed_seq + scan token), with the forced WAL seal +
        archive catch_up baked into _local_cut."""
        from ..storage import backup

        v = self.coord.engine.vnode(p["owner"], p["vnode_id"])
        if v is None:
            return {"cut": None}
        return {"cut": backup._local_cut(v)}

    def _restore_vnode(self, p):
        """RESTORE fan-out: wipe + install one local vnode from shipped
        snapshot bytes, then replay the shipped archived-WAL entries."""
        from ..storage import backup

        backup.install_vnode(self.coord.engine, p["owner"], p["vnode_id"],
                             p["snap"], p["entries"])
        return {"ok": True}

    def _vnode_install(self, p):
        from .replica import VnodeStateMachine

        v = self.coord.engine.open_vnode(p["owner"], p["vnode_id"])
        VnodeStateMachine(v).install_snapshot(p["data"], 0, 0)
        return {"ok": True}

    def _vnode_drop(self, p):
        # stop any live raft member first: its ticker would recreate the
        # WAL the drop removes
        if p.get("rs_id") is not None and self.coord._replica_mgr is not None:
            self.coord._replica_mgr.stop_member(
                p["owner"], p["rs_id"], p["vnode_id"])
        self.coord.engine.drop_vnode(p["owner"], p["vnode_id"])
        return {"ok": True}

    def _vnode_compact(self, p):
        v = self.coord.engine.vnode(p["owner"], p["vnode_id"])
        if v is not None:
            v.compact_major()
        return {"ok": True}

    def _vnode_checksum(self, p):
        v = self.coord.engine.vnode(p["owner"], p["vnode_id"])
        return {"checksum": v.checksum() if v is not None else ""}

    # raft membership change (reference raft/manager.rs:323-566
    # add_follower / change-membership admin surface)
    def _replica_change_membership(self, p):
        from ..models.meta_data import ReplicationSet

        rs = ReplicationSet.from_dict(p["rs"])
        try:
            idx = self.coord.replica_manager().change_membership_local(
                p["owner"], rs, p["members"])
        except NotLeader as e:
            return {"ok": False, "hint": e.args[0] if e.args else None}
        return {"ok": True, "index": idx}

    def _replica_stepdown(self, p):
        from ..models.meta_data import ReplicationSet

        rs = ReplicationSet.from_dict(p["rs"])
        stepped = self.coord.replica_manager().stepdown_local(
            p["owner"], rs, p["vnode_id"])
        return {"ok": True, "stepped": stepped}

    def _replica_stop_member(self, p):
        """Stop a raft member WITHOUT dropping its data (a set shrinking
        to one replica leaves consensus; the vnode stays)."""
        mgr = self.coord._replica_mgr
        if mgr is not None:
            mgr.stop_member(p["owner"], p["rs_id"], p["vnode_id"])
        return {"ok": True}

    def _replica_progress(self, p):
        from ..models.meta_data import ReplicationSet

        rs = ReplicationSet.from_dict(p["rs"])
        prog = self.coord.replica_manager().member_progress(
            p["owner"], rs, p["vnode_id"])
        if prog is None:
            return {"ok": False}
        return {"ok": True, "match": prog[0], "commit": prog[1]}
