"""Gray-failure tolerance plane: per-node health scoring + hedging state.

The circuit breaker (parallel/coordinator.py) is binary — a node is
either answering connections or it is not — so a *brownout* node (GC
pause, disk stall, overloaded neighbor, lossy NIC) that still accepts
TCP keeps receiving scans and drags every query's tail toward the
deadline. This module is the continuous complement: a process-global
:class:`HealthScorer` fed from every ``rpc_call`` completion in
``net.py`` keeps a decayed latency EWMA plus a bounded quantile sketch
per (peer address, method class), tracks error-rate and deadline-burn
EWMAs, and classifies each node HEALTHY / DEGRADED / BROKEN:

  * HEALTHY  — errors rare, latency within the class's own baseline
  * DEGRADED — answering, but slow (burn or latency outliers) or with an
               elevated error rate: used only when no healthy replica
               holds the vnode, and hedged aggressively
  * BROKEN   — error rate so high the node is effectively down; the
               binary breaker usually agrees and fast-fails it

Consumers (coordinator read path):

  * ``rank()`` orders failover candidates by health — power-of-two-
    choices among HEALTHY replicas (seeded, so a test seed reproduces a
    routing decision), DEGRADED after, BROKEN last;
  * ``hedge_delay()`` returns the adaptive per-class hedge trigger (the
    class p95, floored by config) for `_scan_remote`'s hedged requests;
  * :class:`HedgeLimiter` caps concurrent hedges per coordinator so
    hedging can't storm an already-sick cluster;
  * ``SLOW_START`` ramps a freshly-closed breaker's admitted fraction
    instead of readmitting full blast.

Scope: hedging and health-ranked routing apply ONLY to the read-only
method classes in ``HEDGEABLE`` (scans and quorum probes). Replicated
writes stay raft-ordered — duplicating a write RPC would double-apply
or force dedup machinery the raft log already provides — so the write
path never consults this module for routing.

Everything here is observational bookkeeping: losing a sample or a
counter increment can skew a score, never corrupt a query, so the lock
is a plain leaf mutex and the hot path is O(1) appends.
"""
from __future__ import annotations

import os
import random
import threading
import time

from ..utils import lockwatch

# --------------------------------------------------------------- states
HEALTHY = "healthy"
DEGRADED = "degraded"
BROKEN = "broken"

# read-only RPC methods eligible for hedging / health-ranked routing;
# everything else (raft_msg, write_replica, vnode_install, ...) is
# either replicated-write-ordered or destructive and MUST keep the
# deterministic single-target path
HEDGEABLE = frozenset({
    "scan_vnode", "vnode_token", "vnode_checksum", "matview_partials",
    "tag_values", "series_keys", "replica_progress", "ping", "status",
})

# method → class: scores pool per class so one chatty method (raft
# heartbeats) can't mask a scan-lane brownout
_METHOD_CLASS = {
    "scan_vnode": "scan", "tag_values": "scan", "series_keys": "scan",
    "matview_partials": "scan",
    "vnode_token": "probe", "vnode_checksum": "probe",
    "replica_progress": "probe", "ping": "probe", "status": "probe",
    "write_vnode": "write", "write_replica": "write", "raft_msg": "write",
}

# outcome classes for observe(); "deadline" means OUR budget ran out
# mid-call — evidence of slowness, not of the peer being down
OK = "ok"
UNREACHABLE = "unreachable"
REJECTED = "rejected"
DEADLINE = "deadline"

_SKETCH_CAP = 128          # per-(addr, class) latency ring
_EWMA_ALPHA = 0.2          # latency smoothing
_RATE_ALPHA = 0.1          # error / burn rate smoothing
_DEGRADED_BURN = 0.5       # burn EWMA above this ⇒ DEGRADED
_DEGRADED_ERR = 0.1        # error-rate EWMA above this ⇒ DEGRADED
_BROKEN_ERR = 0.5          # error-rate EWMA above this ⇒ BROKEN
_DECAY_HALF_LIFE = 30.0    # idle seconds for a node's rates to halve


# Hedge knobs ([query] hedge_delay_ms_floor / hedge_max_inflight, env
# CNOSDB_QUERY_* overridable so harness subprocesses inherit them even
# without a config file; configure() applies a loaded QueryConfig)
HEDGE_DELAY_FLOOR_MS = float(os.environ.get(
    "CNOSDB_QUERY_HEDGE_DELAY_MS_FLOOR", "25"))
HEDGE_MAX_INFLIGHT = int(os.environ.get(
    "CNOSDB_QUERY_HEDGE_MAX_INFLIGHT", "8"))


def configure(query_cfg) -> None:
    """Apply [query] hedge knobs (called from server wiring)."""
    global HEDGE_DELAY_FLOOR_MS, HEDGE_MAX_INFLIGHT
    f = getattr(query_cfg, "hedge_delay_ms_floor", None)
    if f is not None:
        HEDGE_DELAY_FLOOR_MS = float(f)
    m = getattr(query_cfg, "hedge_max_inflight", None)
    if m:
        HEDGE_MAX_INFLIGHT = max(1, int(m))


def method_class(method: str) -> str:
    return _METHOD_CLASS.get(method, "admin")


def enabled() -> bool:
    """Master gate: CNOSDB_HEDGE=0 restores byte-identical legacy
    routing (fixed-order failover, no health ranking, no hedges).
    Read per call — harness processes flip it via env."""
    return os.environ.get("CNOSDB_HEDGE", "1") != "0"


class _ClassStats:
    """Latency EWMA + bounded sample ring for one (addr, class) cell."""

    __slots__ = ("ewma_s", "ring", "pos", "n")

    def __init__(self):
        self.ewma_s = 0.0
        self.ring: list[float] = []
        self.pos = 0
        self.n = 0

    def add(self, elapsed_s: float) -> None:
        # cold-start warm-up: the first few samples dominate (alpha
        # 1/(n+1)), so one cold-path outlier can't anchor a
        # rarely-sampled node's baseline for dozens of observations
        alpha = max(_EWMA_ALPHA, 1.0 / (self.n + 1))
        self.ewma_s = elapsed_s if self.n == 0 else (
            alpha * elapsed_s + (1 - alpha) * self.ewma_s)
        if len(self.ring) < _SKETCH_CAP:
            self.ring.append(elapsed_s)
        else:
            self.ring[self.pos] = elapsed_s
            self.pos = (self.pos + 1) % _SKETCH_CAP
        self.n += 1

    def quantile(self, q: float) -> float | None:
        if not self.ring:
            return None
        s = sorted(self.ring)
        return s[min(len(s) - 1, int(q * len(s)))]


class _NodeHealth:
    """All tracked signal for one peer address."""

    __slots__ = ("classes", "err_rate", "burn_rate", "last_seen")

    def __init__(self):
        self.classes: dict[str, _ClassStats] = {}
        self.err_rate = 0.0      # EWMA of {0,1} per completion
        self.burn_rate = 0.0     # EWMA of deadline-budget burn fraction
        self.last_seen = time.monotonic()

    def _decay(self, now: float) -> None:
        # idle decay: a node nobody talks to drifts back toward healthy
        # so a transient storm doesn't blacklist it forever — latency
        # EWMAs decay too (a routed-around node gets no fresh samples,
        # so forgetting is the only way its remembered slowness can
        # clear; one rescue re-marks it if it is in fact still slow)
        dt = now - self.last_seen
        if dt > 1.0:
            f = 0.5 ** (dt / _DECAY_HALF_LIFE)
            self.err_rate *= f
            self.burn_rate *= f
            for cs in self.classes.values():
                cs.ewma_s *= f
        self.last_seen = now

    def state(self) -> str:
        if self.err_rate >= _BROKEN_ERR:
            return BROKEN
        if self.err_rate >= _DEGRADED_ERR or self.burn_rate >= _DEGRADED_BURN:
            return DEGRADED
        return HEALTHY

    def score(self) -> float:
        """Lower is better: error weight dominates, then burn, then
        scan-class latency (the lane hedging cares about)."""
        lat = 0.0
        cs = self.classes.get("scan")
        if cs is not None:
            lat = cs.ewma_s
        return self.err_rate * 10.0 + self.burn_rate * 2.0 + lat


class HealthScorer:
    """Process-global gray-failure signal store (one per process, like
    deadline.CANCELS): RPC completions flow in, routing decisions and
    /debug/health flow out."""

    def __init__(self, seed: int | None = None):
        self._lock = lockwatch.Lock("health.scorer")
        self._nodes: dict[str, _NodeHealth] = {}
        # seeded: the p2c tiebreak is reproducible under a test seed
        self._rng = random.Random(seed if seed is not None else 0xC05)

    # ----------------------------------------------------------- ingest
    def observe(self, addr: str, method: str, elapsed_s: float,
                outcome: str, burn: float | None = None) -> None:
        """One RPC completion. `burn` = elapsed / effective-timeout for
        deadline-carrying calls (1.0 ⇒ the call ate its whole budget);
        None when the call ran without a deadline."""
        mclass = method_class(method)
        now = time.monotonic()
        with self._lock:
            nh = self._nodes.get(addr)
            if nh is None:
                nh = self._nodes[addr] = _NodeHealth()
            nh._decay(now)
            err = 1.0 if outcome == UNREACHABLE else 0.0
            nh.err_rate = _RATE_ALPHA * err + (1 - _RATE_ALPHA) * nh.err_rate
            if outcome in (OK, REJECTED):
                cs = nh.classes.get(mclass)
                if cs is None:
                    cs = nh.classes[mclass] = _ClassStats()
                cs.add(elapsed_s)
            if burn is not None:
                b = min(1.0, max(0.0, burn))
                if outcome == DEADLINE:
                    b = 1.0   # the peer ate the entire remaining budget
                nh.burn_rate = _RATE_ALPHA * b \
                    + (1 - _RATE_ALPHA) * nh.burn_rate

    def observe_censored(self, addr: str, mclass: str,
                         elapsed_s: float) -> None:
        """A *lower bound* on an in-flight call's latency — booked the
        moment a hedge wins against it, so routing sees the loser's
        slowness immediately instead of after the slow reply finally
        lands (back-to-back scans would otherwise keep picking the
        straggler for a full brownout-latency window). Weighted heavily
        (alpha ≥ 0.5): losing a hedge race is strong evidence, and one
        loss should push the node out of the near-tie band that lets
        exploration keep probing it. Feeds the ranking EWMA only — a
        censored sample in the quantile ring would bias the hedge
        trigger's p95 downward."""
        with self._lock:
            nh = self._nodes.get(addr)
            if nh is None:
                nh = self._nodes[addr] = _NodeHealth()
            nh._decay(time.monotonic())
            cs = nh.classes.get(mclass)
            if cs is None:
                cs = nh.classes[mclass] = _ClassStats()
            if elapsed_s > cs.ewma_s:
                alpha = max(0.5, 1.0 / (cs.n + 1))
                cs.ewma_s = elapsed_s if cs.n == 0 else (
                    alpha * elapsed_s + (1 - alpha) * cs.ewma_s)
                cs.n += 1

    # ---------------------------------------------------------- queries
    def state(self, addr: str) -> str:
        with self._lock:
            nh = self._nodes.get(addr)
            if nh is None:
                return HEALTHY   # never seen ⇒ no evidence against it
            nh._decay(time.monotonic())
            return nh.state()

    def score(self, addr: str) -> float:
        with self._lock:
            nh = self._nodes.get(addr)
            if nh is None:
                return 0.0
            nh._decay(time.monotonic())
            return nh.score()

    # trigger cap relative to the median: with few ring samples p95 ==
    # max, so one multi-second cold/startup outlier would push the
    # trigger above any realistic brownout and silently disable hedging
    TRIGGER_P50_MULT = 4.0

    def hedge_delay(self, addr: str, mclass: str = "scan",
                    floor_s: float = 0.01) -> float:
        """Adaptive hedge trigger: the (addr, class) p95 — "this call is
        already slower than 95% of its peers" — capped at
        TRIGGER_P50_MULT × the median (outlier robustness) and floored
        so a microsecond p95 on a warm cache can't fire hedges for
        every call."""
        with self._lock:
            nh = self._nodes.get(addr)
            cs = nh.classes.get(mclass) if nh is not None else None
            p95 = cs.quantile(0.95) if cs is not None else None
            p50 = cs.quantile(0.5) if cs is not None else None
        if p95 is None:
            return floor_s
        if p50 is not None:
            p95 = min(p95, self.TRIGGER_P50_MULT * p50)
        return max(floor_s, p95)

    def rank(self, candidates: list, addr_of) -> list:
        """Order failover candidates by health: HEALTHY first (power-of-
        two-choices among them — sampled pairs compared by score, so a
        stale score self-corrects instead of starving a replica),
        DEGRADED next by score, BROKEN last. `addr_of(candidate)` maps a
        candidate to its peer address (None ⇒ local, always first)."""
        local, tiers = [], {HEALTHY: [], DEGRADED: [], BROKEN: []}
        for c in candidates:
            addr = addr_of(c)
            if addr is None:
                local.append(c)
                continue
            tiers[self.state(addr)].append((self.score(addr), addr, c))
        healthy = [t[2] for t in self._p2c(tiers[HEALTHY])]
        degraded = [t[2] for t in sorted(tiers[DEGRADED],
                                         key=lambda t: t[0])]
        broken = [t[2] for t in sorted(tiers[BROKEN], key=lambda t: t[0])]
        return local + healthy + degraded + broken

    # probability a sampled NEAR-TIE pair emits the other candidate:
    # with few replicas p2c alone degenerates to deterministic
    # best-first, and a node whose last sample was a cold-path outlier
    # would never be re-probed. Exploration is restricted to near-ties
    # (both candidates good) so it costs ~nothing; a clearly-bad node is
    # NOT explored on the critical path — its score recovers through
    # idle decay instead, and one hedge-rescued probe re-marks it.
    EXPLORE = 0.05
    EXPLORE_TIE = 2.0      # "near-tie": worse ≤ TIE × better + 5 ms

    def _p2c(self, tier: list) -> list:
        """Power-of-two-choices ordering: repeatedly sample two
        remaining candidates, emit the better-scored one (the other for
        EXPLORE of near-tie pairs, so a stale score self-corrects
        instead of starving a replica). Degenerates to identity for 0/1
        candidates."""
        out, pool = [], list(tier)
        with self._lock:
            while len(pool) > 1:
                i = self._rng.randrange(len(pool))
                j = self._rng.randrange(len(pool) - 1)
                if j >= i:
                    j += 1
                pick = i if pool[i][0] <= pool[j][0] else j
                near_tie = max(pool[i][0], pool[j][0]) <= (
                    self.EXPLORE_TIE * min(pool[i][0], pool[j][0]) + 0.005)
                if near_tie and self._rng.random() < self.EXPLORE:
                    pick = j if pick == i else i
                out.append(pool.pop(pick))
        out.extend(pool)
        return out

    def snapshot(self) -> dict:
        """/debug/health wire shape: per-node state/score/rates plus
        per-class latency ewma + p50/p95 (ms)."""
        now = time.monotonic()
        with self._lock:
            out = {}
            for addr, nh in self._nodes.items():
                nh._decay(now)
                classes = {}
                for mclass, cs in nh.classes.items():
                    classes[mclass] = {
                        "ewma_ms": round(cs.ewma_s * 1e3, 3),
                        "p50_ms": round((cs.quantile(0.5) or 0.0) * 1e3, 3),
                        "p95_ms": round((cs.quantile(0.95) or 0.0) * 1e3, 3),
                        "samples": cs.n,
                    }
                out[addr] = {"state": nh.state(),
                             "score": round(nh.score(), 4),
                             "err_rate": round(nh.err_rate, 4),
                             "burn_rate": round(nh.burn_rate, 4),
                             "classes": classes}
            return out

    def reset(self) -> None:
        """Test isolation."""
        with self._lock:
            self._nodes.clear()
            self._rng = random.Random(0xC05)


class HedgeLimiter:
    """Per-coordinator in-flight hedge cap: hedges add load precisely
    when the cluster is slow, so an unbounded hedger turns one brownout
    into a self-inflicted storm. Non-blocking acquire — a denied hedge
    is a *suppressed* hedge (booked by the caller), never a wait."""

    def __init__(self, max_inflight: int = 8):
        self.max_inflight = max(1, int(max_inflight))
        self._lock = lockwatch.Lock("health.hedge_limiter")
        self._inflight = 0

    def try_acquire(self, limit: int | None = None) -> bool:
        lim = self.max_inflight if limit is None else max(1, int(limit))
        with self._lock:
            if self._inflight >= lim:
                return False
            self._inflight += 1
            return True

    def release(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    def inflight(self) -> int:
        with self._lock:
            return self._inflight


class SlowStart:
    """Half-open → closed breaker ramp: a node that just proved itself
    with one probe readmits traffic at a ramped fraction over RAMP_S
    seconds instead of full blast (full traffic on a barely-recovered
    node is how half-open breakers flap). Deterministic admission — a
    call is admitted when admitted_so_far ≤ total_so_far × fraction —
    so tests don't need to mock randomness."""

    RAMP_S = float(os.environ.get("CNOSDB_CB_RAMP_S", "5.0"))
    RAMP_MIN = 0.25   # fraction admitted the instant the breaker closes

    def __init__(self):
        self._lock = lockwatch.Lock("health.slow_start")
        # node_id → [ramp_started_at, admitted, total]
        self._ramps: dict = {}

    def begin(self, node_id) -> None:
        with self._lock:
            self._ramps[node_id] = [time.monotonic(), 0, 0]

    def clear(self, node_id) -> None:
        with self._lock:
            self._ramps.pop(node_id, None)

    def reset(self) -> None:
        """Test isolation."""
        with self._lock:
            self._ramps.clear()

    def admit(self, node_id) -> bool:
        """True ⇒ send the call; False ⇒ caller should treat the node
        as still-cooling (fast-fail to an alternate)."""
        with self._lock:
            st = self._ramps.get(node_id)
            if st is None:
                return True
            started, admitted, total = st
            frac = self.RAMP_MIN + (1.0 - self.RAMP_MIN) * min(
                1.0, (time.monotonic() - started) / max(1e-9, self.RAMP_S))
            if frac >= 1.0:
                del self._ramps[node_id]
                return True
            st[2] = total + 1
            if admitted <= total * frac:
                st[1] = admitted + 1
                return True
            return False

    def ramping(self) -> dict:
        with self._lock:
            return {n: {"admitted": st[1], "total": st[2]}
                    for n, st in self._ramps.items()}


# --------------------------------------------------- plane-wide counters
_ctr_lock = lockwatch.Lock("health.counters")
_counters: dict[tuple, int] = {}


def count_hedge(outcome: str, reason: str = "", n: int = 1) -> None:
    """Hedge-lane accounting (`cnosdb_hedge_total{outcome,reason}`):
    fired / won / lost / cancelled / suppressed(reason). Every early
    exit out of the hedge lane must book one of these — enforced by the
    hedge-accounting lint rule."""
    with _ctr_lock:
        k = (outcome, reason)
        _counters[k] = _counters.get(k, 0) + n


def count_breaker(node, state: str, n: int = 1) -> None:
    """Breaker state-transition accounting
    (`cnosdb_breaker_total{node,state}`): open / half_open / closed."""
    with _ctr_lock:
        k = ("breaker", str(node), state)
        _counters[k] = _counters.get(k, 0) + n


def counters_snapshot() -> tuple[dict, dict]:
    """→ (hedge counters {(outcome, reason): n},
          breaker counters {(node, state): n})."""
    with _ctr_lock:
        hedge = {k: v for k, v in _counters.items() if len(k) == 2}
        breaker = {(k[1], k[2]): v for k, v in _counters.items()
                   if len(k) == 3 and k[0] == "breaker"}
        return hedge, breaker


def reset_counters() -> None:
    """Test / bench isolation."""
    with _ctr_lock:
        _counters.clear()


SCORER = HealthScorer()
SLOW_START = SlowStart()
