"""Tenant rate limiting: token buckets.

Role-parity with the reference's limiter stack (common/limiter_bucket
CountBucket + meta/src/limiter/local_request_limiter.rs:44): each tenant's
TenantOptions may carry a `limiter` dict

    {"max_writes_per_sec": N, "max_queries_per_sec": N,
     "max_points_per_sec": N}

and the HTTP layer checks the matching bucket per request (reference
http_limiter_check_write in http_service.rs). Buckets refill continuously
(rate per second, burst = one second's allowance) and are purely local per
process — the reference's remote-bucket escalation to meta is a later
round."""
from __future__ import annotations

import threading
import time

from ..errors import LimiterError
from ..utils import lockwatch


class TokenBucket:
    """Continuous-refill token bucket (reference CountBucket)."""

    def __init__(self, rate_per_sec: float, burst: float | None = None):
        self.rate = float(rate_per_sec)
        self.capacity = float(burst if burst is not None else rate_per_sec)
        self.tokens = self.capacity
        self.t_last = time.monotonic()
        self.lock = lockwatch.Lock("limiter.bucket")

    def try_acquire(self, n: float = 1.0) -> bool:
        with self.lock:
            now = time.monotonic()
            self.tokens = min(self.capacity,
                              self.tokens + (now - self.t_last) * self.rate)
            self.t_last = now
            if self.tokens >= n:
                self.tokens -= n
                return True
            return False


class TenantLimiters:
    """Per-tenant bucket registry fed from TenantOptions.limiter."""

    def __init__(self, meta):
        self.meta = meta
        self._buckets: dict[tuple[str, str], TokenBucket] = {}
        self._lock = lockwatch.Lock("limiter.tenants")

    def _bucket(self, tenant: str, kind: str) -> TokenBucket | None:
        opts = self.meta.tenants.get(tenant)
        cfg = getattr(opts, "limiter", None) if opts is not None else None
        if not cfg:
            return None
        rate = cfg.get(f"max_{kind}_per_sec")
        if not rate:
            return None
        key = (tenant, kind)
        with self._lock:
            b = self._buckets.get(key)
            if b is None or b.rate != float(rate):
                b = self._buckets[key] = TokenBucket(rate)
            return b

    def check_write(self, tenant: str, n_points: int = 0):
        b = self._bucket(tenant, "writes")
        if b is not None and not b.try_acquire(1):
            raise LimiterError(f"tenant {tenant!r} write rate limit exceeded")
        if n_points:
            pb = self._bucket(tenant, "points")
            if pb is not None and not pb.try_acquire(n_points):
                raise LimiterError(
                    f"tenant {tenant!r} points rate limit exceeded")

    def check_query(self, tenant: str):
        b = self._bucket(tenant, "queries")
        if b is not None and not b.try_acquire(1):
            raise LimiterError(f"tenant {tenant!r} query rate limit exceeded")
