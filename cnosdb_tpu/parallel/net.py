"""Cross-process RPC plane: msgpack over HTTP.

This is the rebuild's counterpart of the reference's tonic/gRPC node-to-node
plane (common/protos/proto/kv_service.proto TSKVService + raft_service.proto,
replication/src/network_grpc.rs, meta/src/service/http.rs): a thread-per-
request HTTP server carrying msgpack request/reply bodies, and a client with
per-thread persistent connections. HTTP instead of gRPC because the callers
are synchronous engine/raft threads (thread-per-request matches the raft
tick/propose model the way tonic's tasks match tokio), and msgpack because
the payloads are already msgpack throughout the storage layer; Arrow IPC
rides inside scan replies as opaque bytes (reference serialize.rs:30
TonicRecordBatchEncoder ↔ BatchBytesResponse).

Wire form: POST /rpc/<method> with a msgpack body → 200 + msgpack reply,
or 500 + msgpack {"_err": class, "_msg": str} re-raised client-side.
"""
from __future__ import annotations

import hmac
import http.client
import logging
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from socket import timeout as socket_timeout

import msgpack

from .. import faults
from . import health
from ..errors import CnosError, DeadlineExceeded
from ..utils import deadline as deadline_mod
from ..utils import stages
from ..utils.backoff import Backoff
from ..utils import lockwatch

log = logging.getLogger("cnosdb.rpc")

faults.register_point("rpc.send", __name__, scope="cluster",
                      desc="client connect/send to a peer")
faults.register_point("rpc.response", __name__, scope="cluster",
                      desc="reply lost in flight after the server applied")
faults.register_point("rpc.server", __name__, scope="cluster",
                      desc="server-side dispatch of an inbound method")
faults.register_point("rpc.reply", __name__, scope="cluster",
                      desc="server reply serialization/drop")

# Intra-cluster shared secret (CNOSDB_CLUSTER_SECRET): when set, every RPC
# must carry it — the plane exposes destructive admin and file-installing
# methods (vnode_install, meta_restore, raft_msg), so any deployment that
# binds beyond loopback MUST either set this or isolate the network. Read
# at call time so harness-spawned processes inherit it from their env.
SECRET_HEADER = "x-cnosdb-cluster-secret"


def cluster_secret() -> str | None:
    return os.environ.get("CNOSDB_CLUSTER_SECRET") or None


class RpcError(CnosError):
    pass


class RpcUnauthorized(RpcError):
    """Missing/wrong cluster secret."""


class RpcUnavailable(RpcError):
    """Peer unreachable (connection refused / reset / timeout)."""


class RpcThrottled(RpcUnavailable):
    """Call refused locally by the breaker's slow-start ramp — the peer
    was never contacted, so this is NOT evidence of a broken replica
    (failover paths must not mark vnodes broken on it)."""


def pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(raw: bytes):
    return msgpack.unpackb(raw, raw=False, strict_map_key=False)


class RpcServer:
    """Serves `handlers[method](payload) -> reply` at POST /rpc/<method>.

    `node_id` (when the owner has one, e.g. DataNodeService) labels the
    per-request sub-profiles this server returns to profiling callers."""

    def __init__(self, host: str, port: int, handlers: dict,
                 node_id: int | None = None):
        self.node_id = node_id
        self.handlers = dict(handlers)
        if faults.CTL_ARMED:
            # runtime fault control for chaos harnesses — only exposed when
            # the process was launched with CNOSDB_FAULTS in its environment
            self.handlers.setdefault("_faults", faults.control)
            # memory-broker control (memory_pressure nemesis squeezes /
            # restores the budget at runtime) rides the same arming knob
            from ..server import memory as _memory

            self.handlers.setdefault("_memory", _memory.control)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # small replies otherwise stall ~40ms on Nagle + delayed-ACK
            # — a latency floor that buries every probe/cancel RPC and
            # poisons the health scorer's latency baselines
            disable_nagle_algorithm = True

            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b""
                method = self.path.rsplit("/", 1)[-1]
                secret = cluster_secret()
                if secret is not None and not hmac.compare_digest(
                        self.headers.get(SECRET_HEADER, ""), secret):
                    self._reply(403, pack({"_err": "RpcUnauthorized",
                                           "_msg": "cluster secret required"}))
                    return
                fn = outer.handlers.get(method)
                if fn is None:
                    self._reply(404, pack({"_err": "NoSuchMethod", "_msg": method}))
                    return
                from ..server.trace import GLOBAL_COLLECTOR

                try:
                    if faults.ENABLED:
                        # fail/delay/crash before dispatch (server-side fault)
                        faults.fire("rpc.server", method=method)
                    payload = unpack(body) if body else {}
                    # per-query profiling envelope: a profiling caller
                    # marks the payload; the handler then runs inside a
                    # node-local QueryProfile whose stage timings ride
                    # back in the reply for the coordinator to merge
                    want_profile = bool(
                        isinstance(payload, dict)
                        and payload.pop("_profile", False))
                    # request-lifecycle envelope: the caller's remaining
                    # deadline (wall-clock epoch ms) and query id ride in
                    # the payload; install them as this handler thread's
                    # context so nested work (scans, decode pool, further
                    # RPC hops) inherits the shrinking budget
                    dl = None
                    if isinstance(payload, dict) and (
                            "_deadline_ms" in payload or "_qid" in payload):
                        dl = deadline_mod.from_wire(
                            payload.pop("_deadline_ms", None),
                            qid=payload.pop("_qid", None))
                        if dl.expired() or (dl.qid and
                                            deadline_mod.CANCELS
                                            .is_cancelled(dl.qid)):
                            # reject already-dead work on dequeue instead
                            # of executing it (it sat in a queue/delay
                            # longer than the caller was willing to wait)
                            deadline_mod.bump("expired_rejected")
                            stages.count_error(f"rpc.{method}.expired")
                            self._reply(500, pack(
                                {"_err": "DeadlineExceeded",
                                 "_msg": f"{method}: work expired before "
                                         f"dispatch"}))
                            return
                    prof = stages.QueryProfile(node_id=outer.node_id) \
                        if want_profile else None
                    with stages.profile_scope(prof), \
                            stages.stage(f"rpc_{method}_ms"):
                        with GLOBAL_COLLECTOR.from_headers(
                                self.headers, f"rpc:{method}"):
                            if dl is not None and dl.qid:
                                deadline_mod.CANCELS.register(dl.qid, dl)
                                try:
                                    with deadline_mod.scope(dl):
                                        reply = fn(payload)
                                finally:
                                    deadline_mod.CANCELS.unregister(
                                        dl.qid, dl)
                            elif dl is not None:
                                with deadline_mod.scope(dl):
                                    reply = fn(payload)
                            else:
                                reply = fn(payload)
                    if prof is not None and isinstance(reply, dict):
                        # reply envelope: this handler's node-local
                        # sub-profile rides home for the caller to merge
                        reply = dict(reply)
                        reply["_profile"] = prof.to_wire()
                    if faults.ENABLED and faults.fire("rpc.reply",
                                                      method=method):
                        # injected lost ack: the handler HAS applied the
                        # mutation; drop the reply so the client sees a
                        # response-phase failure (net.py retry policy must
                        # not re-execute it)
                        self.close_connection = True
                        return
                    self._reply(200, pack(reply))
                except Exception as e:  # propagate to caller, keep serving
                    stages.count_error(f"rpc.{method}")
                    log.debug("rpc handler %s failed", method, exc_info=True)
                    self._reply(500, pack({"_err": type(e).__name__,
                                           "_msg": str(e)}))

            def _reply(self, status: int, raw: bytes):
                try:
                    self.send_response(status)
                    self.send_header("Content-Type", "application/msgpack")
                    self.send_header("Content-Length", str(len(raw)))
                    self.end_headers()
                    self.wfile.write(raw)
                except (BrokenPipeError, ConnectionResetError):
                    pass

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.addr = f"{host}:{self.port}"
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class _ConnPool:
    """Shared keep-alive connection pool keyed by peer address.

    Shared (not thread-local) because raft broadcast/election paths spawn
    short-lived sender threads — a per-thread cache would open a brand-new
    TCP connection for every raft message."""

    MAX_IDLE_PER_ADDR = 8
    # idle keep-alives older than this are closed instead of reused: a
    # peer restart leaves dead sockets behind, and every one of them
    # burns a connect-error + retry on its next use; age-evicting keeps
    # the stale-keep-alive race to the recently-active window
    MAX_IDLE_AGE_S = float(os.environ.get("CNOSDB_RPC_IDLE_MAX_AGE_S", "30"))

    def __init__(self):
        self.lock = lockwatch.Lock("net.conn_pool")
        # addr → [(conn, idle_since_monotonic), ...]; LIFO so the
        # freshest (least likely stale) connection is reused first
        self.idle: dict[str, list] = {}

    def get(self, addr: str, timeout: float):
        """→ (conn, reused) — reused connections may be stale keep-alives."""
        now = time.monotonic()
        stale, conn = [], None
        with self.lock:
            conns = self.idle.get(addr)
            while conns:
                c, t = conns.pop()
                if now - t > self.MAX_IDLE_AGE_S:
                    stale.append(c)
                    continue
                conn = c
                break
        for c in stale:   # close outside the pool lock
            c.close()
        if conn is not None:
            return conn, True
        host, _, port = addr.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
        try:
            # connect eagerly so TCP_NODELAY applies to the FIRST request
            # too; the ~40ms Nagle/delayed-ACK stall on small payloads
            # would otherwise dwarf every probe/cancel RPC
            conn.connect()
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass   # unreachable peers surface on send, same as before
        return conn, False

    def put(self, addr: str, conn):
        with self.lock:
            conns = self.idle.setdefault(addr, [])
            if len(conns) < self.MAX_IDLE_PER_ADDR:
                conns.append((conn, time.monotonic()))
                return
        conn.close()


_pool = _ConnPool()


def rpc_call(addr: str, method: str, payload: dict | None = None,
             timeout: float = 10.0):
    """One RPC to `addr` ("host:port") over a pooled keep-alive connection.

    Retry policy: ONLY a non-timeout failure on a REUSED connection is
    retried (the classic stale keep-alive race, where the request cannot
    have been processed). A timeout or a fresh-connection failure is NOT
    retried — the server may have fully applied a non-idempotent mutation
    whose reply was lost, and re-executing it would double-apply.

    Deadline integration: when the calling thread carries a request
    deadline (utils/deadline.py), the remaining budget caps the socket
    timeout for this hop, the payload gains `_deadline_ms`/`_qid` so the
    peer can reject expired work and register for cancel fan-out, and an
    already-expired/cancelled context refuses to send at all."""
    # lock-order watchdog: an RPC issued with any mutex held means one
    # slow peer can stall every thread queued on that mutex
    lockwatch.note_blocking(f"rpc:{method}")
    dl = deadline_mod.current()
    if dl is not None:
        # raises DeadlineExceeded / cancelled QueryError when no budget
        # remains — do not open a socket for work that cannot finish
        timeout = dl.cap(timeout)
        wire = dl.to_wire_ms()
        if wire is not None or dl.qid is not None:
            payload = dict(payload or {})
            if wire is not None:
                payload["_deadline_ms"] = wire
            if dl.qid is not None:
                payload["_qid"] = dl.qid
    prof = stages.current_profile()
    if prof is not None:
        # profiling envelope: ask the peer to run this dispatch inside a
        # node-local profile and return it in the reply
        payload = dict(payload or {})
        payload["_profile"] = True
    body = pack(payload or {})
    from ..server.trace import TRACE_HEADER, current_trace_header

    hdrs = {"Content-Type": "application/msgpack"}
    secret = cluster_secret()
    if secret is not None:
        hdrs[SECRET_HEADER] = secret
    tid = current_trace_header()
    if tid:
        hdrs[TRACE_HEADER] = tid

    # gray-failure signal: EVERY completion of this call (success, typed
    # rejection, unreachable, deadline) feeds the process-global health
    # scorer; burn = fraction of the capped budget the hop consumed, only
    # meaningful when a deadline bounded the hop
    t0 = time.perf_counter()
    bounded = dl is not None and dl.remaining() is not None

    def _obs(outcome: str) -> None:
        elapsed = time.perf_counter() - t0
        burn = (elapsed / timeout) if bounded and timeout > 0 else None
        health.SCORER.observe(addr, method, elapsed, outcome, burn=burn)

    if faults.ENABLED:
        try:
            # simulated network partition toward (addr, method): checked
            # once per call, before any bytes move — the peer never sees it
            faults.fire("rpc.send", addr=addr, method=method)
        except faults.FaultInjected as e:
            _obs(health.UNREACHABLE)
            raise RpcUnavailable(f"{method}@{addr}: {e}") from e
    for attempt in range(_ConnPool.MAX_IDLE_PER_ADDR + 1):
        conn, reused = _pool.get(addr, timeout)
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        try:
            conn.request("POST", f"/rpc/{method}", body, hdrs)
        except (ConnectionError, http.client.HTTPException, OSError,
                TimeoutError) as e:
            # send-phase failure: retry ONLY the stale-keep-alive case
            # (reused conn, non-timeout) — bounded by the pool size so a
            # flapping peer refilling the pool cannot loop us forever
            conn.close()
            if reused and not isinstance(e, (TimeoutError, socket_timeout)):
                continue
            _obs(health.UNREACHABLE)
            raise RpcUnavailable(f"{method}@{addr}: {e}") from e
        try:
            if faults.ENABLED:
                # reply lost in the network AFTER the server applied the
                # request — FaultInjected is an OSError, so it takes the
                # never-retry response-phase path below like a real loss
                faults.fire("rpc.response", addr=addr, method=method)
            resp = conn.getresponse()
            raw = resp.read()
            reply = unpack(raw) if raw else {}
        except (ConnectionError, http.client.HTTPException, OSError,
                TimeoutError) as e:
            # response-phase failure: the server may have fully processed a
            # non-idempotent mutation whose reply was lost — NEVER retry
            conn.close()
            _obs(health.UNREACHABLE)
            raise RpcUnavailable(f"{method}@{addr}: {e}") from e
        if resp.status == 200:
            _pool.put(addr, conn)
        else:
            # an errored exchange may leave the stream mid-frame (chunked
            # error bodies, aborted handlers): never pool it — the reuse
            # would surface as an unrelated stale-keep-alive failure later
            conn.close()
        if prof is not None and isinstance(reply, dict) \
                and "_profile" in reply:
            sub = reply.pop("_profile")
            if isinstance(sub, dict):
                # key the sub-profile by node/vnode/method so the
                # coordinator-side merge can attribute per node
                sub.setdefault("addr", addr)
                sub["method"] = method
                if isinstance(payload, dict) \
                        and payload.get("vnode_id") is not None:
                    sub["vnode"] = payload["vnode_id"]
                prof.merge_remote(sub)
        if resp.status == 403:
            # typed: auth misconfiguration is permanent — retry loops that
            # catch RpcError/RpcUnavailable must be able to fail fast
            _obs(health.REJECTED)
            raise RpcUnauthorized(f"{method}@{addr}: {reply.get('_msg')}")
        if resp.status != 200:
            if reply.get("_err") == "DeadlineExceeded":
                # typed: failover loops must unwind, not try the next
                # replica with a budget that is already gone
                _obs(health.DEADLINE)
                raise DeadlineExceeded(f"{method}@{addr}: {reply.get('_msg')}")
            _obs(health.REJECTED)
            raise RpcError(f"{method}@{addr}: "
                           f"{reply.get('_err')}: {reply.get('_msg')}")
        _obs(health.OK)
        return reply
    _obs(health.UNREACHABLE)
    raise RpcUnavailable(f"{method}@{addr}: pooled connections exhausted")


def wait_rpc_ready(addr: str, method: str = "ping", timeout: float = 10.0):
    """Poll until a peer answers (process start-up races in harnesses).

    Jittered exponential backoff instead of a fixed 50 ms spin: N nodes
    waiting on the same meta service otherwise hammer it in lockstep.
    A caller-carried request deadline caps the whole poll budget — a
    short-deadline request must not wait out the full 10 s default."""
    timeout = deadline_mod.cap_current(timeout)
    start = time.monotonic()
    deadline = start + timeout
    bo = Backoff(initial=0.02, cap=0.5)
    while True:
        try:
            return rpc_call(addr, method, {}, timeout=2.0)
        except RpcError as e:
            if time.monotonic() > deadline or not bo.sleep(deadline):
                elapsed = time.monotonic() - start
                raise RpcUnavailable(
                    f"{method}@{addr} not ready after {elapsed:.1f}s "
                    f"(last error: {e})") from e
