"""Meta store: cluster catalog + placement.

Role-parity with the reference's meta crate (meta/src/model/meta_admin.rs
AdminMeta + meta_tenant.rs TenantMeta + store/storage.rs state machine):
tenants, databases, table schemas, buckets/replica-sets/vnode placement,
users/roles. The reference runs this as its own single-group raft cluster
over HTTP watch; here it is a process-local store with a durable JSON
snapshot (atomic rewrite per mutation — meta mutations are rare), designed
so the same API can later front a replicated backend without callers
changing.

Placement (reference meta_tenant.rs:562 create_bucket, :716
locate_replication_set_for_write): a write at ts t lands in the bucket
covering t (auto-created, duration = db.vnode_duration), within it in shard
`series_hash % shard_num`.
"""
from __future__ import annotations

import hashlib
import hmac
import json
import os
import secrets
import threading
import time

from ..utils import stages
from ..errors import (
    DatabaseAlreadyExists, DatabaseNotFound, MetaError, TableAlreadyExists,
    TableNotFound, TenantNotFound,
)
from ..models.meta_data import BucketInfo, NodeInfo, ReplicationSet, VnodeInfo
from ..models.schema import (
    DatabaseOptions, DatabaseSchema, TenantOptions, TskvTableSchema,
)
from ..utils import lockwatch

DEFAULT_TENANT = "cnosdb"

# limiter_config shape (reference limiter/limiter_kind.rs): fixed key
# order, every request slot present (null when unset)
_LIMITER_OBJECT_KEYS = ("max_users_number", "max_databases",
                        "max_shard_number", "max_replicate_number",
                        "max_retention_time")
_LIMITER_REQUEST_KEYS = ("coord_data_in", "coord_data_out",
                         "coord_queries", "coord_writes", "http_data_in",
                         "http_data_out", "http_queries", "http_writes")


def build_limiter_config(groups: dict) -> dict:
    """{group: {key: int}} from the SQL option list → the reference's
    limiter_config JSON structure."""
    obj = None
    if "object_config" in groups:
        src = groups["object_config"]
        obj = {k: src[k] for k in _LIMITER_OBJECT_KEYS if k in src}
    req = {}
    for g in _LIMITER_REQUEST_KEYS:
        src = groups.get(g)
        if src is None:
            req[g] = None
            continue
        missing = {"remote_max", "remote_initial", "remote_refill",
                   "remote_interval", "local_max",
                   "local_initial"} - set(src)
        if missing:
            # request buckets are all-or-nothing (dcl_tenant.slt pins a
            # 2-key coord_data_out as an error)
            raise MetaError(
                f"limiter group {g} missing {sorted(missing)}")
        req[g] = {
            "remote_bucket": {
                "max": src.get("remote_max", 0),
                "initial": src.get("remote_initial", 0),
                "refill": src.get("remote_refill", 0),
                "interval": src.get("remote_interval", 0)},
            "local_bucket": {
                "max": src.get("local_max", 0),
                "initial": src.get("local_initial", 0)}}
    return {"object_config": obj, "request_config": req}


DEFAULT_DATABASE = "public"
USAGE_SCHEMA = "usage_schema"


def hash_password(pw: str) -> str:
    """Salted PBKDF2 — passwords are never persisted in the clear
    (reference stores a hash too: common/models/src/auth/user.rs)."""
    salt = secrets.token_hex(8)
    h = hashlib.pbkdf2_hmac("sha256", pw.encode(), bytes.fromhex(salt), 50_000)
    return f"pbkdf2${salt}${h.hex()}"


def verify_password(stored: str, candidate: str) -> bool:
    """Constant-time verification against the stored hash (or a legacy
    plaintext value from a pre-hashing meta.json)."""
    parts = stored.split("$")
    if len(parts) == 3 and parts[0] == "pbkdf2":
        cand = hashlib.pbkdf2_hmac(
            "sha256", candidate.encode(), bytes.fromhex(parts[1]), 50_000).hex()
        return hmac.compare_digest(cand, parts[2])
    return hmac.compare_digest(stored, candidate)


_DUMMY_HASH = hash_password("!nonexistent!")


class MetaStore:
    def __init__(self, path: str | None = None, node_id: int = 1,
                 register_self: bool = True):
        """`register_self=False` for a standalone meta server: it is not a
        data node, so placement must not target its node_id."""
        self.path = path
        self.node_id = node_id
        self.lock = lockwatch.RLock("meta.store")
        self.tenants: dict[str, TenantOptions] = {}
        self.users: dict[str, dict] = {}
        self.databases: dict[str, DatabaseSchema] = {}          # owner → schema
        self.tables: dict[str, dict[str, TskvTableSchema]] = {}  # owner → {table}
        self.buckets: dict[str, list[BucketInfo]] = {}           # owner → buckets
        self.nodes: dict[int, NodeInfo] = \
            {node_id: NodeInfo(node_id)} if register_self else {}
        self.streams: dict[str, dict] = {}  # stream name → definition
        self.stream_tables: dict[str, dict] = {}  # stream table → binding
        self.matviews: dict[str, dict] = {}  # materialized view → definition
        self.members: dict[str, dict[str, str]] = {}  # tenant → {user → role}
        self.roles: dict[str, dict[str, dict]] = {}   # tenant → {role → spec}
        # external (file-backed) tables: owner → {name → {path, fmt, header}}
        self.externals: dict[str, dict[str, dict]] = {}
        # verified-credential cache; keys bind (user, stored-hash, password)
        # so password changes and drops invalidate naturally
        self._auth_cache: set = set()
        # monotone state version + bounded event log: the /watch long-poll
        # plane (reference meta/src/service/http.rs /watch + watch logs in
        # store/storage.rs) — every mutation bumps version and records its
        # event so remote caches can catch up incrementally
        self.version = 0
        self.events: list[tuple[int, str, dict]] = []
        self._version_cv = threading.Condition(self.lock)
        # raft apply watermark: persisted INSIDE meta.json (same atomic
        # write as the mutation itself) so a restarted replicated-meta
        # member never re-applies logged mutations its store already holds
        self.applied_index = 0
        # soft-deleted objects awaiting RECOVER or purge (reference DROP
        # moves to a recycle window; RECOVER TENANT/DATABASE/TABLE undoes
        # it, spi ast.rs:65-77). Payloads keep full schema state; data
        # files stay on disk until purge_trash.
        self.trash: dict[str, dict] = {"tenant": {}, "db": {}, "table": {}}
        # disaster-recovery backup catalog (storage/backup.py): owner →
        # ordered list of backup entries. Rides the same replicated
        # snapshot as the rest of the catalog, so RESTORE can find its
        # manifests after total node loss of any data node.
        self.backups: dict[str, list[dict]] = {}
        # recently-applied raft request ids, persisted in the SAME atomic
        # meta.json write as the mutations they guard: a restarted member
        # replaying a retried duplicate proposal (or a retry reaching a
        # restarted leader) must still dedup originals applied pre-crash
        self.recent_req_ids: list[str] = []
        self._next_bucket_id = 1
        self._next_replica_id = 1
        self._next_vnode_id = 1
        self._watchers: list = []
        if path and os.path.exists(path):
            self._load()
        else:
            self._bootstrap()
            self._persist()

    # ------------------------------------------------------------ durability
    def _bootstrap(self):
        self.tenants[DEFAULT_TENANT] = TenantOptions(comment="system tenant")
        self.users["root"] = {"password": hash_password(""), "admin": True,
                              "comment": "system admin",
                              "must_change_password": True}
        for db in (DEFAULT_DATABASE, USAGE_SCHEMA):
            opts = DatabaseOptions()
            if db == USAGE_SCHEMA:
                # the reference gives usage_schema a tiny memcache
                # (usage_schema.rs; DESCRIBE DATABASE pins '2 MiB')
                opts.config = dict(opts.config or {})
                opts.config["max_memcache_size"] = "2 MiB"
            schema = DatabaseSchema(DEFAULT_TENANT, db, opts)
            self.databases[schema.owner] = schema
            self.tables.setdefault(schema.owner, {})
            self.buckets.setdefault(schema.owner, [])
        self._bootstrap_usage_tables()

    def _bootstrap_usage_tables(self):
        """The reference's metrics reporter registers REAL tskv tables in
        usage_schema (usage_schema.rs): per-tenant coord/sql/http
        counters and per-vnode gauges, all `value BIGINT UNSIGNED` with
        STRING tags. Rows are written by the coordinator/HTTP hooks."""
        from ..models.schema import ColumnType

        owner = f"{DEFAULT_TENANT}.{USAGE_SCHEMA}"
        tbls = self.tables.setdefault(owner, {})

        from ..models.schema import TableColumn, ValueType

        def mk(name, tags):
            if name in tbls:
                return
            cols = [("time", ColumnType.time())]
            cols += [(t, ColumnType.tag()) for t in tags]
            cols.append(("value", ColumnType.field(ValueType.UNSIGNED)))
            tbls[name] = TskvTableSchema(
                DEFAULT_TENANT, USAGE_SCHEMA, name,
                [TableColumn(i, n, ct) for i, (n, ct) in enumerate(cols)])

        coord_tags = ("database", "node_id", "tenant")
        for n in ("coord_data_in", "coord_data_out", "coord_queries",
                  "coord_writes", "sql_data_in"):
            mk(n, coord_tags)
        http_tags = ("api", "database", "host", "node_id", "tenant",
                     "user")
        for n in ("http_data_in", "http_data_out", "http_queries",
                  "http_writes"):
            mk(n, http_tags)
        vnode_tags = ("database", "node_id", "tenant", "vnode_id")
        for n in ("vnode_disk_storage", "vnode_cache_size"):
            mk(n, vnode_tags)

    def _to_dict(self) -> dict:
        return {
            "tenants": {k: v.to_dict() for k, v in self.tenants.items()},
            "users": self.users,
            "databases": {k: v.to_dict() for k, v in self.databases.items()},
            "tables": {o: {t: s.to_dict() for t, s in ts.items()}
                       for o, ts in self.tables.items()},
            "buckets": {o: [b.to_dict() for b in bs] for o, bs in self.buckets.items()},
            "nodes": {str(k): v.to_dict() for k, v in self.nodes.items()},
            "streams": self.streams,
            "stream_tables": self.stream_tables,
            "matviews": self.matviews,
            "members": self.members,
            "roles": self.roles,
            "externals": self.externals,
            "applied_index": self.applied_index,
            "recent_req_ids": self.recent_req_ids,
            "trash": self.trash,
            "backups": self.backups,
            "next_ids": [self._next_bucket_id, self._next_replica_id, self._next_vnode_id],
        }

    def _persist(self):
        if not self.path:
            return
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(self._to_dict(), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def _load(self):
        with open(self.path) as f:
            d = json.load(f)
        self._from_dict(d)

    def _from_dict(self, d: dict):
        """Replace full state from a snapshot dict (used by durable load and
        by remote-cache hydration in MetaClient)."""
        self.tenants = {k: TenantOptions.from_dict(v) for k, v in d["tenants"].items()}
        self.users = d["users"]
        self.databases = {k: DatabaseSchema.from_dict(v) for k, v in d["databases"].items()}
        self.tables = {o: {t: TskvTableSchema.from_dict(s) for t, s in ts.items()}
                       for o, ts in d["tables"].items()}
        self.buckets = {o: [BucketInfo.from_dict(b) for b in bs]
                        for o, bs in d["buckets"].items()}
        self.nodes = {int(k): NodeInfo.from_dict(v) for k, v in d["nodes"].items()}
        self.streams = d.get("streams", {})
        self.stream_tables = d.get("stream_tables", {})
        self.matviews = d.get("matviews", {})
        self.members = d.get("members", {})
        self.roles = d.get("roles", {})
        self.externals = d.get("externals", {})
        self.applied_index = d.get("applied_index", 0)
        self.recent_req_ids = list(d.get("recent_req_ids", []))
        self.trash = d.get("trash", {"tenant": {}, "db": {}, "table": {}})
        self.backups = d.get("backups", {})
        self._next_bucket_id, self._next_replica_id, self._next_vnode_id = d["next_ids"]
        # snapshots written before the usage_schema metric tables existed
        # must still grow them on load (mk() is idempotent), along with
        # the 2 MiB memcache config the reference pins
        us = self.databases.get(f"{DEFAULT_TENANT}.{USAGE_SCHEMA}")
        if us is not None:
            us.options.config = dict(us.options.config or {})
            us.options.config.setdefault("max_memcache_size", "2 MiB")
            self._bootstrap_usage_tables()

    def _notify(self, event: str, **kw):
        with self.lock:
            self.version += 1
            self.events.append((self.version, event, kw))
            if len(self.events) > 4096:
                del self.events[:2048]
            self._version_cv.notify_all()
        for w in list(self._watchers):
            try:
                w(event, kw)
            except Exception:
                stages.count_error("swallow.meta.watcher_cb")

    def wait_version(self, after: int, timeout: float = 30.0) -> int:
        """Block until version > after (long-poll /watch); → current version."""
        deadline = time.monotonic() + timeout
        with self._version_cv:
            while self.version <= after:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._version_cv.wait(remaining)
            return self.version

    def events_since(self, after: int) -> list[tuple[int, str, dict]]:
        with self.lock:
            return [e for e in self.events if e[0] > after]

    def watch(self, callback):
        """callback(event:str, payload:dict) on every meta mutation
        (reference watch long-poll, meta/src/service/http.rs /watch)."""
        self._watchers.append(callback)

    # ------------------------------------------------------------ tenants/users
    def create_tenant(self, name: str, options: TenantOptions | None = None):
        with self.lock:
            if not name or not name.strip() or "/" in name:
                raise MetaError("invalid tenant name")
            if name in self.tenants:
                raise MetaError(f"tenant {name!r} exists")
            self.tenants[name] = options or TenantOptions()
            self._persist()
            self._notify("create_tenant", tenant=name)

    def alter_tenant_options(self, name: str, changes: dict):
        """SET/UNSET comment/drop_after/limiter groups (None value =
        unset) — reference ALTER TENANT (ast.rs AlterTenantOperation)."""
        from ..models.schema import Duration

        with self.lock:
            if name not in self.tenants:
                raise TenantNotFound(name)
            if name == DEFAULT_TENANT:
                # the system tenant's options are immutable
                # (dcl_tenant.slt / tenants.slt pin SET object_config
                # on cnosdb as an error)
                raise MetaError("cannot alter the system tenant")
            opts = self.tenants[name]
            # validate EVERYTHING before mutating: a failing option list
            # must leave the tenant untouched (dcl_tenant.slt: the
            # comment of an errored SET does not stick)
            staged = {}
            if "drop_after" in changes:
                v = changes["drop_after"]
                staged["drop_after"] = Duration.parse(v) if v else None
            if "_limiter_groups" in changes:
                groups = changes["_limiter_groups"]
                new = build_limiter_config(groups)
                cur = opts.limiter or {
                    "object_config": None,
                    "request_config": {k: None
                                       for k in _LIMITER_REQUEST_KEYS}}
                if "object_config" in groups:
                    # partial object_config MERGES over the existing
                    # values (dcl_tenant.slt: max_shard_number survives
                    # an alter that only sets users/databases/retention)
                    merged = dict(cur.get("object_config") or {})
                    merged.update(new["object_config"] or {})
                    cur["object_config"] = {
                        k: merged[k] for k in _LIMITER_OBJECT_KEYS
                        if k in merged}
                for g in groups:
                    if g != "object_config":
                        cur["request_config"][g] = new["request_config"][g]
                staged["limiter"] = cur
            if "comment" in changes:
                opts.comment = changes["comment"] or ""
            if "drop_after" in staged:
                opts.drop_after = staged["drop_after"]
            if "limiter" in staged:
                opts.limiter = staged["limiter"]
            if "_limiter" in changes:   # UNSET _LIMITER
                opts.limiter = None
            self._persist()
            self._notify("alter_tenant", tenant=name)

    def drop_tenant(self, name: str, at: float | None = None,
                    if_exists: bool = False, after: str | None = None):
        """Soft delete: the tenant and all its databases move to the
        recycle bin; RECOVER TENANT restores everything. DROP ... AFTER
        with a deadline SHORTER than the tenant's configured drop_after
        collapses to an immediate hard delete (dcl_tenant.slt: t5 is
        unrecoverable, t4 with a longer AFTER recovers)."""
        import time as _time

        with self.lock:
            if name == DEFAULT_TENANT:
                raise MetaError("cannot drop system tenant")
            if name not in self.tenants:
                if if_exists:
                    return
                raise TenantNotFound(name)
            hard = False
            if after is not None:
                from ..models.schema import Duration

                cfg = self.tenants[name].drop_after
                after_d = Duration.parse(after)
                # AFTER 'INF' (or a cfg of INF) never shrinks the window
                hard = cfg is not None and not after_d.is_inf \
                    and not cfg.is_inf and after_d.ns < cfg.ns
            dropped = [o for o in self.databases if o.startswith(name + ".")]
            fire = []
            old = self.trash["tenant"].pop(name, None)
            if old is not None:   # see drop_database: reclaim, don't leak
                for owner, p in old.get("dbs", {}).items():
                    fire += self._payload_vnode_events(owner, p)
            self.trash["tenant"][name] = {
                "options": self.tenants.pop(name).to_dict(),
                "members": self.members.pop(name, {}),
                "roles": self.roles.pop(name, {}),
                "dbs": {o: self._db_to_trash(o, at) for o in dropped},
                "at": _time.time() if at is None else at,
            }
            if hard:
                # immediate reclamation: no recycle-bin window
                p = self.trash["tenant"].pop(name)
                for owner, dbp in p["dbs"].items():
                    if owner in self.databases:
                        fire += self._payload_vnode_events(owner, dbp)
                    else:
                        fire.append(("drop_db", {"owner": owner}))
            self._persist()
            for event, kw in fire:
                self._notify(event, **kw)
            if not hard:
                for owner in dropped:
                    self._notify("trash_db", owner=owner)
            self._notify("drop_tenant", tenant=name)

    def recover_tenant(self, name: str):
        with self.lock:
            payload = self.trash["tenant"].get(name)
            if payload is None:
                raise MetaError(f"tenant {name!r} is not in the recycle bin")
            if name in self.tenants:
                raise MetaError(
                    f"cannot recover {name!r}: the name is in use again")
            del self.trash["tenant"][name]
            self.tenants[name] = TenantOptions.from_dict(payload["options"])
            self.members[name] = payload["members"]
            self.roles[name] = payload["roles"]
            for owner, db_payload in payload["dbs"].items():
                self._db_from_trash(owner, db_payload)
            self._persist()
            for owner in payload["dbs"]:
                self._notify("recover_db", owner=owner)
            self._notify("create_tenant", tenant=name)

    def purge_trash(self, older_than_s: float = 0.0,
                    now: float | None = None):
        """Permanently reclaim recycled objects (fires the hard-delete
        events so engines drop vnode data and disk). In replicated meta
        groups the PROPOSER pins `now` so every member purges the same
        set."""
        import time as _time

        cutoff = (_time.time() if now is None else now) - older_than_s  # lint: disable=wallclock-duration (proposer pins wall-clock now into the replicated purge command so members agree)
        with self.lock:
            fire = []

            def reclaim_db(owner, payload):
                # whole-dir removal only when no LIVE database reuses the
                # owner path; otherwise purge that incarnation's vnodes
                if owner in self.databases:
                    fire.extend(self._payload_vnode_events(owner, payload))
                else:
                    fire.append(("drop_db", {"owner": owner}))

            for owner in [o for o, p in self.trash["db"].items()
                          if p["at"] <= cutoff]:
                reclaim_db(owner, self.trash["db"].pop(owner))
            for key in [k for k, p in self.trash["table"].items()
                        if p["at"] <= cutoff]:
                self.trash["table"].pop(key)
                owner, _, table = key.rpartition(".")
                # table rows share the owner's vnode files; delete them
                # only when no live table re-took the name
                if table not in self.tables.get(owner, {}):
                    fire.append(("drop_table",
                                 {"owner": owner, "table": table}))
            for name in [n for n, p in self.trash["tenant"].items()
                         if p["at"] <= cutoff]:
                p = self.trash["tenant"].pop(name)
                for owner, dbp in p["dbs"].items():
                    reclaim_db(owner, dbp)
            self._persist()
            for event, kw in fire:
                self._notify(event, **kw)
            return len(fire)

    def create_user(self, name: str, password: str = "", admin: bool = False,
                    comment: str = "",
                    must_change_password: bool | None = None):
        with self.lock:
            if not name or not name.strip() or "/" in name:
                raise MetaError("invalid user name")
            if name in self.users:
                raise MetaError(f"user {name!r} exists")
            rec = {"password": hash_password(password),
                   "admin": admin, "comment": comment}
            if must_change_password is not None:
                # presence == explicitly set (user_options JSON surfaces
                # only set options — dcl/alter_user.slt)
                rec["must_change_password"] = must_change_password
            self.users[name] = rec
            self._persist()

    def drop_user(self, name: str, if_exists: bool = False):
        with self.lock:
            if name == "root":
                raise MetaError("cannot drop root")
            if name not in self.users:
                if if_exists:
                    return
                raise MetaError(f"user {name!r} not found")
            self.users.pop(name, None)
            for members in self.members.values():
                members.pop(name, None)
            self._auth_cache.clear()
            self._persist()

    def alter_user(self, name: str, password: str | None = None,
                   changes: dict | None = None):
        with self.lock:
            if name not in self.users:
                raise MetaError(f"user {name!r} missing")
            changes = dict(changes or {})
            if password is not None:
                changes.setdefault("password", password)
            if "granted_admin" in changes and name == "root":
                # the system admin's adminship is not grantable state
                # (dcl/alter_user.slt pins both true and false as errors)
                raise MetaError("cannot change root's granted_admin")
            if "password" in changes:
                self.users[name]["password"] = \
                    hash_password(changes.pop("password"))
                self._auth_cache.clear()
            if "granted_admin" in changes:
                ga = bool(changes.pop("granted_admin"))
                self.users[name]["admin"] = ga
                # surfaced as a SET option in user_options JSON
                # (dcl/alter_user.slt)
                self.users[name]["granted_admin"] = ga
            if "comment" in changes:
                self.users[name]["comment"] = changes.pop("comment")
            if "must_change_password" in changes:
                self.users[name]["must_change_password"] = bool(
                    changes.pop("must_change_password"))
            self._persist()

    def check_user(self, name: str, password: str) -> dict | None:
        """Authenticate; returns the user record or None. Unknown users pay
        exactly one PBKDF2 (precomputed dummy hash), like wrong passwords,
        so response timing does not enumerate usernames. Verified
        credentials are cached (invalidated on alter/drop) so steady-state
        auth costs one SHA-256 digest compare, not 50k PBKDF2 rounds."""
        with self.lock:
            u = self.users.get(name)
            stored = u["password"] if u else _DUMMY_HASH
        cache_key = (name, hashlib.sha256((stored + "\x00" + password).encode()).hexdigest())
        with self.lock:
            if cache_key in self._auth_cache:
                return u
        ok = verify_password(stored, password)
        if u is not None and ok:
            with self.lock:
                if len(self._auth_cache) > 1024:
                    self._auth_cache.clear()
                self._auth_cache.add(cache_key)
            return u
        return None

    # ------------------------------------------------------------ membership
    def add_member(self, tenant: str, user: str, role: str = "member"):
        with self.lock:
            if tenant not in self.tenants:
                raise TenantNotFound(tenant)
            if user not in self.users:
                raise MetaError(f"user {user!r} missing")
            if role not in ("member", "owner") \
                    and role not in self.roles.get(tenant, {}):
                raise MetaError(
                    f"unknown role {role!r} in tenant {tenant!r}")
            self.members.setdefault(tenant, {})[user] = role
            self._persist()

    def remove_member(self, tenant: str, user: str):
        with self.lock:
            self.members.get(tenant, {}).pop(user, None)
            self._persist()

    def member_role(self, tenant: str, user: str) -> str | None:
        with self.lock:
            return self.members.get(tenant, {}).get(user)

    def user_can_access(self, user: str, tenant: str) -> bool:
        """Tenant authorization: admins everywhere; everyone may use the
        system tenant; otherwise must be a member (reference
        meta_tenant member model, common/models/src/auth/role.rs)."""
        with self.lock:
            u = self.users.get(user)
            if u is None:
                return False
            if u.get("admin"):
                return True
            if tenant == DEFAULT_TENANT:
                return True
            return user in self.members.get(tenant, {})

    # ------------------------------------------------------------ roles/RBAC
    # role spec: {"inherit": "member"|"owner", "privileges": {db: level}}
    # levels order read < write < all (reference common/models/src/auth/
    # privilege.rs DatabasePrivilege)
    _PRIV_ORDER = {"read": 0, "write": 1, "all": 2}

    def create_role(self, tenant: str, name: str, inherit: str = "member"):
        with self.lock:
            if tenant not in self.tenants:
                raise TenantNotFound(tenant)
            if not name or not name.strip() or "/" in name:
                raise MetaError("invalid role name")
            roles = self.roles.setdefault(tenant, {})
            if name in roles or name in ("owner", "member"):
                raise MetaError(f"role {name!r} exists in tenant {tenant!r}")
            if inherit not in ("member", "owner"):
                raise MetaError(f"role can only inherit member|owner")
            roles[name] = {"inherit": inherit, "privileges": {}}
            self._persist()

    def drop_role(self, tenant: str, name: str):
        with self.lock:
            if name in ("owner", "member"):
                # system roles (drop_role.slt pins DROP ROLE owner as an
                # error)
                raise MetaError(f"cannot drop system role {name!r}")
            self.roles.get(tenant, {}).pop(name, None)
            # memberships through the dropped role die with it — the
            # user is OUT of the tenant, not demoted (dcl_role.slt:
            # SHOW DATABASES errors for them afterwards)
            members = self.members.get(tenant, {})
            for user, role in list(members.items()):
                if role == name:
                    del members[user]
            self._persist()

    def list_roles(self, tenant: str) -> dict:
        with self.lock:
            out = {"owner": {"inherit": "owner", "privileges": {}},
                   "member": {"inherit": "member", "privileges": {}}}
            out.update(self.roles.get(tenant, {}))
            return out

    def grant_db_privilege(self, tenant: str, role: str, db: str, level: str):
        if level not in self._PRIV_ORDER:
            raise MetaError(f"bad privilege level {level!r}")
        with self.lock:
            spec = self.roles.get(tenant, {}).get(role)
            if spec is None:
                raise MetaError(f"unknown role {role!r} (system roles "
                                "cannot be granted to)")
            if f"{tenant}.{db}" not in self.databases:
                # the grant target must exist (database_privileges.slt)
                raise DatabaseNotFound(db)
            spec["privileges"][db] = level
            self._persist()

    def revoke_db_privilege(self, tenant: str, role: str, db: str):
        with self.lock:
            spec = self.roles.get(tenant, {}).get(role)
            if spec is None:
                raise MetaError(f"unknown role {role!r}")
            if db not in spec["privileges"]:
                # revoking a grant that was never made is an error
                # (dcl_role.slt)
                raise MetaError(
                    f"role {role!r} holds no privilege on {db!r}")
            spec["privileges"].pop(db)
            self._persist()

    def check_db_privilege(self, user: str, tenant: str, db: str,
                           need: str) -> bool:
        """Does `user` hold `need` (read|write|all) on tenant.db?
        (reference auth/auth_control.rs AccessControlImpl)."""
        with self.lock:
            u = self.users.get(user)
            if u is None:
                return False
            if u.get("admin"):
                return True
            role = self.members.get(tenant, {}).get(user)
            if role is None:
                # membership is explicit even in the default tenant — a
                # user whose only role was dropped is OUT (dcl_role.slt
                # pins SHOW DATABASES as an error for them)
                return False
            need_rank = self._PRIV_ORDER[need]
            if role == "owner":
                return True
            if role == "member":
                return need_rank <= self._PRIV_ORDER["read"]
            spec = self.roles.get(tenant, {}).get(role)
            if spec is None:
                return False
            if spec.get("inherit") == "owner":
                return True
            granted = spec["privileges"].get(db)
            if granted is None:
                # a custom member-inherit role holds ONLY its explicit
                # grants (dcl_role.slt: read on db1 does not open db2)
                return False
            return need_rank <= self._PRIV_ORDER[granted]

    # ------------------------------------------------------------ databases
    # db names allow word chars and interior spaces ('dd c' is legal);
    # empty, whitespace-only, '/' or '.' are not (create_database.slt)
    _DB_NAME_RE = __import__("re").compile(r"^(?=.*\S)[^/.\x00-\x1f]+$")

    def create_database(self, schema: DatabaseSchema, if_not_exists: bool = False):
        with self.lock:
            if schema.tenant not in self.tenants:
                raise TenantNotFound(schema.tenant)
            if not self._DB_NAME_RE.match(schema.name or ""):
                # reference rejects names outside the identifier charset
                # (create_database.slt: "db/1", '', ' ')
                raise MetaError(f"invalid database name {schema.name!r}")
            reserved = ("information_schema", "usage_schema") \
                if schema.tenant != DEFAULT_TENANT else \
                ("cluster_schema", "information_schema", "usage_schema")
            if schema.name in reserved:
                # cluster_schema is reserved only in the system tenant —
                # others may own a real db of that name (dcl_tenant.slt)
                raise MetaError(
                    f"cannot create system schema {schema.name!r}")
            if schema.owner in self.databases:
                if if_not_exists:
                    return
                raise DatabaseAlreadyExists(schema.name)
            self.databases[schema.owner] = schema
            self.tables.setdefault(schema.owner, {})
            self.buckets.setdefault(schema.owner, [])
            self._persist()
            self._notify("create_db", owner=schema.owner)

    def alter_database(self, tenant: str, db: str, **opts):
        with self.lock:
            schema = self.database(tenant, db)
            for k, v in opts.items():
                if v is not None:
                    setattr(schema.options, k, v)
            self._persist()
            self._notify("alter_db", owner=schema.owner)

    def _db_to_trash(self, owner: str, at: float | None = None) -> dict:
        """Capture a database's full meta state for the recycle bin.
        `at` is pinned by the PROPOSER in replicated-meta groups so every
        member records the identical timestamp."""
        import time as _time

        return {
            "schema": self.databases.pop(owner).to_dict(),
            "tables": {t: s.to_dict()
                       for t, s in self.tables.pop(owner, {}).items()},
            "buckets": [b.to_dict() for b in self.buckets.pop(owner, [])],
            "at": _time.time() if at is None else at,
        }

    def _payload_vnode_events(self, owner: str, payload: dict) -> list:
        """Targeted reclamation for ONE trashed incarnation: per-vnode
        purge events. Never a whole-owner drop_db — a recreated live
        database shares the owner directory, and its files must survive
        the old incarnation's purge."""
        out = []
        for b in payload.get("buckets", []):
            bi = BucketInfo.from_dict(b)
            for rs in bi.shard_group:
                for v in rs.vnodes:
                    out.append(("purge_vnode",
                                {"owner": owner, "vnode_id": v.id}))
        return out

    def _db_from_trash(self, owner: str, payload: dict) -> None:
        self.databases[owner] = DatabaseSchema.from_dict(payload["schema"])
        self.tables[owner] = {t: TskvTableSchema.from_dict(s)
                              for t, s in payload["tables"].items()}
        self.buckets[owner] = [BucketInfo.from_dict(b)
                               for b in payload["buckets"]]

    def drop_database(self, tenant: str, db: str, if_exists: bool = True,
                      at: float | None = None):
        """Soft delete: the database moves to the recycle bin (data files
        untouched); RECOVER DATABASE restores it, purge_trash reclaims."""
        with self.lock:
            if tenant == DEFAULT_TENANT and db in (DEFAULT_DATABASE,
                                                   USAGE_SCHEMA):
                # system databases are not droppable (drop_database.slt
                # pins DROP DATABASE public as an error)
                raise MetaError(f"cannot drop system database {db!r}")
            owner = f"{tenant}.{db}"
            if owner not in self.databases:
                if if_exists:
                    return
                raise DatabaseNotFound(db)
            # a previous incarnation already in the bin can no longer be
            # recovered once this drop takes its slot: reclaim its vnode
            # files NOW instead of leaking them forever
            fire = []
            old = self.trash["db"].pop(owner, None)
            if old is not None:
                fire = self._payload_vnode_events(owner, old)
            self.trash["db"][owner] = self._db_to_trash(owner, at)
            self._persist()
            for event, kw in fire:
                self._notify(event, **kw)
            self._notify("trash_db", owner=owner)

    def recover_database(self, tenant: str, db: str):
        with self.lock:
            owner = f"{tenant}.{db}"
            payload = self.trash["db"].get(owner)
            if payload is None:
                raise MetaError(f"database {db!r} is not in the recycle bin")
            if owner in self.databases:
                raise MetaError(
                    f"cannot recover {db!r}: the name is in use again")
            if tenant not in self.tenants:
                raise MetaError(
                    f"cannot recover {db!r}: tenant {tenant!r} is gone "
                    f"(RECOVER TENANT first)")
            del self.trash["db"][owner]
            self._db_from_trash(owner, payload)
            self._persist()
            self._notify("recover_db", owner=owner)

    def database(self, tenant: str, db: str) -> DatabaseSchema:
        owner = f"{tenant}.{db}"
        schema = self.databases.get(owner)
        if schema is None:
            raise DatabaseNotFound(db)
        return schema

    def list_databases(self, tenant: str) -> list[str]:
        pre = tenant + "."
        return sorted(o[len(pre):] for o in self.databases if o.startswith(pre))

    # ------------------------------------------------------------ tables
    def create_table(self, schema: TskvTableSchema, if_not_exists: bool = False):
        with self.lock:
            owner = f"{schema.tenant}.{schema.db}"
            if owner not in self.databases:
                raise DatabaseNotFound(schema.db)
            tbls = self.tables.setdefault(owner, {})
            if schema.name in tbls \
                    or schema.name in self.externals.get(owner, {}):
                if if_not_exists:
                    return
                raise TableAlreadyExists(schema.name)
            # creating over a trashed same-name incarnation ends its
            # RECOVER window — the old incarnation's rows must never
            # resurface under the new table (reference: recreate after
            # DROP reads an empty table, create_table.slt)
            trashed = self.trash["table"].pop(f"{owner}.{schema.name}",
                                              None)
            tbls[schema.name] = schema
            self._persist()
            if trashed is not None:
                self._notify("purge_table", owner=owner, table=schema.name)
            self._notify("create_table", owner=owner, table=schema.name)

    def update_table(self, schema: TskvTableSchema):
        with self.lock:
            owner = f"{schema.tenant}.{schema.db}"
            self.tables.setdefault(owner, {})[schema.name] = schema
            self._persist()
            self._notify("update_table", owner=owner, table=schema.name)

    def drop_table(self, tenant: str, db: str, table: str,
                   if_exists: bool = True, at: float | None = None):
        """Soft delete (see drop_database): schema to the recycle bin,
        row data stays in the vnodes until purge."""
        import time as _time

        with self.lock:
            owner = f"{tenant}.{db}"
            tbls = self.tables.get(owner, {})
            if table not in tbls:
                if if_exists:
                    return
                raise TableNotFound(table)
            self.trash["table"][f"{owner}.{table}"] = {
                "schema": tbls.pop(table).to_dict(),
                "at": _time.time() if at is None else at}
            self._persist()
            self._notify("trash_table", owner=owner, table=table)

    def recover_table(self, tenant: str, db: str, table: str):
        with self.lock:
            owner = f"{tenant}.{db}"
            key = f"{owner}.{table}"
            payload = self.trash["table"].get(key)
            if payload is None:
                raise MetaError(f"table {table!r} is not in the recycle bin")
            if owner not in self.databases:
                raise MetaError(
                    f"cannot recover {table!r}: database {db!r} is gone")
            if table in self.tables.get(owner, {}):
                raise MetaError(
                    f"cannot recover {table!r}: the name is in use again")
            del self.trash["table"][key]
            self.tables.setdefault(owner, {})[table] = \
                TskvTableSchema.from_dict(payload["schema"])
            self._persist()
            self._notify("recover_table", owner=owner, table=table)

    def table(self, tenant: str, db: str, table: str) -> TskvTableSchema:
        owner = f"{tenant}.{db}"
        s = self.tables.get(owner, {}).get(table)
        if s is None:
            raise TableNotFound(table)
        return s

    def table_opt(self, tenant: str, db: str, table: str) -> TskvTableSchema | None:
        return self.tables.get(f"{tenant}.{db}", {}).get(table)

    def list_tables(self, tenant: str, db: str) -> list[str]:
        owner = f"{tenant}.{db}"
        return sorted(set(self.tables.get(owner, {}))
                      | set(self.externals.get(owner, {})))

    # ------------------------------------------------------------ nodes
    def register_node(self, node_id: int, grpc_addr: str = "",
                      http_addr: str = ""):
        """Data node joins the cluster (reference meta_admin.rs:479
        add_data_node); placement spreads over registered, alive nodes."""
        with self.lock:
            self.nodes[node_id] = NodeInfo(node_id, grpc_addr, http_addr,
                                           {"last_seen": time.time()})
            self._persist()
            self._notify("register_node", node_id=node_id)

    def report_heartbeat(self, node_id: int):
        """Liveness beat (reference regular_report_node_metrics
        server.rs:121-131); not persisted — liveness is runtime state."""
        with self.lock:
            n = self.nodes.get(node_id)
            if n is not None:
                n.attributes["last_seen"] = time.time()

    def node_addr(self, node_id: int) -> str | None:
        with self.lock:
            n = self.nodes.get(node_id)
            return n.grpc_addr if n else None

    def alive_nodes(self, max_age: float = 15.0) -> list[NodeInfo]:
        """Nodes seen within max_age seconds. Nodes that never heartbeat
        (single-process/test stores) count as alive."""
        now = time.time()
        with self.lock:
            out = []
            for n in self.nodes.values():
                seen = n.attributes.get("last_seen")
                if seen is None or now - seen <= max_age:  # lint: disable=wallclock-duration (last_seen rides meta snapshots cross-process; wall clock by design)
                    out.append(n)
            return out

    def placement_candidates(self) -> list[int]:
        """Node ids eligible for new vnode placement: alive ones, falling
        back to all REGISTERED nodes when heartbeats are transiently stale
        (a persisted bucket must never land on a phantom id). The single
        authority — both the in-process path and the replicated-meta
        leader's proposal pinning use it."""
        cand = sorted(n.id for n in self.alive_nodes())
        return cand or sorted(self.nodes)

    # ------------------------------------------------------------ vnode admin
    def find_vnode(self, vnode_id: int):
        """→ (owner, bucket, rs, vnode) or None."""
        with self.lock:
            for owner, buckets in self.buckets.items():
                for b in buckets:
                    for rs in b.shard_group:
                        v = rs.vnode(vnode_id)
                        if v is not None:
                            return owner, b, rs, v
            return None

    def update_vnode(self, vnode_id: int, node_id: int | None = None,
                     status: int | None = None):
        """Re-place or re-mark one vnode (reference MOVE VNODE admin +
        broken-marking, coordinator/src/reader/mod.rs:36)."""
        from ..models.meta_data import VnodeStatus

        with self.lock:
            hit = self.find_vnode(vnode_id)
            if hit is None:
                raise MetaError(f"unknown vnode {vnode_id}")
            owner, _b, rs, v = hit
            if node_id is not None:
                v.node_id = node_id
                if rs.leader_vnode_id == vnode_id:
                    rs.leader_node_id = node_id
            if status is not None:
                v.status = VnodeStatus(status)
            self._persist()
            self._notify("update_vnode", owner=owner, vnode_id=vnode_id,
                         rs_id=rs.id, node_id=v.node_id, status=int(v.status))

    def find_replica_set(self, rs_id: int):
        """→ (owner, rs) or None — the single authority for rs lookups."""
        with self.lock:
            for owner, buckets in self.buckets.items():
                for b in buckets:
                    for rs in b.shard_group:
                        if rs.id == rs_id:
                            return owner, rs
            return None

    def add_replica_vnode(self, rs_id: int, node_id: int,
                          status: int = 0) -> int:
        """COPY VNODE target: add a replica to a replica set (reference
        REPLICA ADD, raft/manager.rs add_follower). Callers seeding data
        pass status=COPYING and flip to RUNNING only after the snapshot
        installs, so readers never trust a data-less replica."""
        from ..models.meta_data import VnodeInfo, VnodeStatus

        with self.lock:
            hit = self.find_replica_set(rs_id)
            if hit is None:
                raise MetaError(f"unknown replica set {rs_id}")
            owner, rs = hit
            vid = self._next_vnode_id
            self._next_vnode_id += 1
            rs.vnodes.append(VnodeInfo(vid, node_id, VnodeStatus(status)))
            self._persist()
            self._notify("update_vnode", owner=owner, vnode_id=vid,
                         rs_id=rs.id, node_id=node_id, status=status)
            return vid

    def remove_replica_vnode(self, vnode_id: int):
        """REPLICA REMOVE: drop one replica entry from its set."""
        with self.lock:
            hit = self.find_vnode(vnode_id)
            if hit is None:
                raise MetaError(f"unknown vnode {vnode_id}")
            owner, _b, rs, v = hit
            if len(rs.vnodes) <= 1:
                raise MetaError("cannot remove the last replica")
            rs.vnodes = [x for x in rs.vnodes if x.id != vnode_id]
            if rs.leader_vnode_id == vnode_id:
                rs.leader_vnode_id = rs.vnodes[0].id
                rs.leader_node_id = rs.vnodes[0].node_id
            self._persist()
            self._notify("update_vnode", owner=owner, vnode_id=vnode_id,
                         rs_id=rs.id, node_id=-1, status=-1)

    def remove_replica_set(self, rs_id: int) -> list:
        """REPLICA DESTORY: remove a (damaged) replica set wholesale from
        its bucket (reference parser.rs:2046 / manager.rs destory) —
        callers drop the member data. → the removed VnodeInfo list."""
        with self.lock:
            hit = self.find_replica_set(rs_id)
            if hit is None:
                raise MetaError(f"unknown replica set {rs_id}")
            owner, rs = hit
            removed = list(rs.vnodes)
            for buckets in self.buckets.values():
                for b in buckets:
                    if rs in b.shard_group:
                        b.shard_group.remove(rs)
            # a bucket with no shards left can serve nothing: drop it
            self.buckets[owner] = [b for b in self.buckets[owner]
                                   if b.shard_group]
            self._persist()
            self._notify("update_vnode", owner=owner, vnode_id=-1,
                         rs_id=rs_id, node_id=-1, status=-1)
            return removed

    def promote_replica(self, vnode_id: int):
        """REPLICA PROMOTE: make this replica the placement leader."""
        with self.lock:
            hit = self.find_vnode(vnode_id)
            if hit is None:
                raise MetaError(f"unknown vnode {vnode_id}")
            owner, _b, rs, v = hit
            rs.leader_vnode_id = v.id
            rs.leader_node_id = v.node_id
            self._persist()
            self._notify("update_vnode", owner=owner, vnode_id=vnode_id,
                         rs_id=rs.id, node_id=v.node_id, status=int(v.status))

    # ------------------------------------------------------------ externals
    def create_external_table(self, tenant: str, db: str, name: str,
                              path: str, fmt: str = "csv",
                              header: bool = True,
                              if_not_exists: bool = False,
                              options: dict | None = None,
                              columns: list | None = None):
        """File- or object-store-backed table (reference
        create_external_table.rs:189; s3/gcs/azblob connection options per
        spi/src/query/datasource/)."""
        with self.lock:
            owner = f"{tenant}.{db}"
            if owner not in self.databases:
                raise DatabaseNotFound(db)
            tbls = self.externals.setdefault(owner, {})
            if name in tbls or name in self.tables.get(owner, {}):
                if if_not_exists:
                    return
                raise TableAlreadyExists(name)
            tbls[name] = {"path": path, "fmt": fmt, "header": header,
                          "options": dict(options or {}),
                          "columns": [list(c) for c in (columns or [])]}
            self._persist()
        self._notify("create_external", owner=owner, table=name)

    def drop_external_table(self, tenant: str, db: str, name: str) -> bool:
        with self.lock:
            owner = f"{tenant}.{db}"
            out = self.externals.get(owner, {}).pop(name, None)
            if out is not None:
                self._persist()
        if out is not None:
            self._notify("drop_external", owner=owner, table=name)
        return out is not None

    def external_opt(self, tenant: str, db: str, name: str) -> dict | None:
        with self.lock:
            return self.externals.get(f"{tenant}.{db}", {}).get(name)

    # ------------------------------------------------------------ streams
    def create_stream(self, name: str, definition: dict):
        with self.lock:
            if name in self.streams:
                raise MetaError(f"stream {name!r} exists")
            self.streams[name] = definition
            self._persist()

    def drop_stream(self, name: str):
        with self.lock:
            if self.streams.pop(name, None) is not None:
                self._persist()

    # ------------------------------------------------- materialized views
    def create_matview(self, name: str, definition: dict):
        with self.lock:
            if name in self.matviews:
                raise MetaError(f"materialized view {name!r} exists")
            self.matviews[name] = definition
            self._persist()

    def drop_matview(self, name: str):
        with self.lock:
            if self.matviews.pop(name, None) is not None:
                self._persist()

    # ------------------------------------------------- stream tables
    # keyed by tenant.db.name: stream tables are catalog objects scoped
    # like any table, not a global namespace
    def create_stream_table(self, tenant: str, db: str, name: str,
                            definition: dict,
                            if_not_exists: bool = False):
        key = f"{tenant}.{db}.{name}"
        with self.lock:
            if key in self.stream_tables:
                if if_not_exists:
                    return
                raise MetaError(f"stream table {name!r} exists")
            self.stream_tables[key] = definition
            self._persist()

    def drop_stream_table(self, tenant: str, db: str, name: str) -> bool:
        key = f"{tenant}.{db}.{name}"
        with self.lock:
            if self.stream_tables.pop(key, None) is not None:
                self._persist()
                return True
            return False

    def stream_table(self, tenant: str, db: str, name: str) -> dict | None:
        with self.lock:
            return self.stream_tables.get(f"{tenant}.{db}.{name}")

    # ------------------------------------------------------------ placement
    def locate_bucket_for_write(self, tenant: str, db: str, ts: int,
                                nodes: list[int] | None = None,
                                now_ns: int | None = None) -> BucketInfo:
        """Find-or-create the bucket covering ts (reference
        meta_tenant.rs:716). `nodes` pins the placement candidates and
        `now_ns` the TTL-expiry clock — the replicated meta leader
        computes both BEFORE proposing so apply is deterministic on every
        member and on log replay (liveness and wall time are runtime
        state)."""
        with self.lock:
            owner = f"{tenant}.{db}"
            schema = self.database(tenant, db)
            for b in self.buckets.get(owner, []):
                if b.contains(ts):
                    return b
            # bucket-creation guards (reference meta_tenant.rs:562 /
            # database_schema.rs:70-84): a write below now - ttl refuses
            # with "create expired bucket" — and the INF TTL sentinel
            # still subtracts i64::MAX, so timestamps hugging the i64-ns
            # floor reject even without a TTL (time_window.slt pins it)
            import time as _time

            i64max = 2**63 - 1
            # a TTL larger than the i64-ns domain saturates (upstream
            # CnosDuration::to_nanoseconds caps at i64::MAX, so even
            # '1000000d' leaves the extreme-past timestamps unwritable)
            ttl_ns = min(schema.options.ttl.ns or i64max, i64max)
            if now_ns is None:
                now_ns = _time.time_ns()
            if ts < now_ns - ttl_ns:
                raise MetaError(
                    f"create expired bucket db:{db} ts:{ts}")
            dur = schema.options.vnode_duration.ns or 365 * 86_400_000_000_000
            start = (ts // dur) * dur if ts >= 0 else -((-ts + dur - 1) // dur) * dur
            if start + dur > i64max:
                # bucket end would overflow the i64-ns domain (reference:
                # "create bucket unknown error" at the max timestamp)
                raise MetaError(
                    f"create bucket unknown error db:{db} ts:{ts}")
            bucket = BucketInfo(self._next_bucket_id, start, start + dur, [])
            self._next_bucket_id += 1
            # spread replicas round-robin over alive nodes (reference
            # meta_tenant.rs:562 create_bucket node selection); fall back to
            # all REGISTERED nodes rather than placing on a phantom id when
            # heartbeats are transiently stale — a bucket is persisted, so a
            # bad placement would poison its time range permanently
            cand = sorted(nodes) if nodes else self.placement_candidates()
            if not cand:
                raise MetaError("no data nodes registered; cannot place bucket")
            rr = bucket.id  # deterministic stagger across buckets
            for _ in range(max(1, schema.options.shard_num)):
                replica = max(1, schema.options.replica)
                vnodes = []
                for i in range(replica):
                    node = cand[(rr + i) % len(cand)]
                    vnodes.append(VnodeInfo(self._next_vnode_id + i, node))
                rr += replica
                self._next_vnode_id += len(vnodes)
                rs = ReplicationSet(self._next_replica_id, vnodes[0].node_id,
                                    vnodes[0].id, vnodes)
                self._next_replica_id += 1
                bucket.shard_group.append(rs)
            self.buckets.setdefault(owner, []).append(bucket)
            self.buckets[owner].sort(key=lambda b: b.start_time)
            self._persist()
            self._notify("create_bucket", owner=owner, bucket_id=bucket.id)
            return bucket

    # ------------------------------------------------------------ backups
    def record_backup(self, owner: str, entry: dict) -> None:
        """Append one backup-catalog entry (storage/backup.py manifest
        pointer). Meta-replicated: the catalog is part of the persisted
        snapshot, so it survives any data node."""
        with self.lock:
            self.backups.setdefault(owner, []).append(dict(entry))
            self._persist()
            self._notify("record_backup", owner=owner, backup_id=entry["id"])

    def list_backups(self, owner: str) -> list[dict]:
        with self.lock:
            return [dict(e) for e in self.backups.get(owner, [])]

    def prune_backups(self, owner: str, keep: int) -> int:
        """Drop catalog entries beyond the newest `keep` (manifest GC has
        already deleted their objects); keep=0 clears the owner's whole
        catalog. → entries removed."""
        with self.lock:
            entries = self.backups.get(owner, [])
            if keep < 0 or len(entries) <= keep:
                return 0
            dropped = len(entries) - keep
            self.backups[owner] = entries[-keep:] if keep else []
            self._persist()
            self._notify("prune_backups", owner=owner)
            return dropped

    def buckets_for(self, tenant: str, db: str,
                    min_ts: int | None = None, max_ts: int | None = None) -> list[BucketInfo]:
        owner = f"{tenant}.{db}"
        out = []
        for b in self.buckets.get(owner, []):
            if min_ts is not None and b.end_time <= min_ts:
                continue
            if max_ts is not None and b.start_time > max_ts:
                continue
            out.append(b)
        return out

    def expire_buckets(self, tenant: str, db: str, now_ns: int) -> list[BucketInfo]:
        """TTL expiry (reference meta_admin.rs:848 expired_bucket)."""
        with self.lock:
            schema = self.database(tenant, db)
            if schema.options.ttl.is_inf:
                return []
            cutoff = now_ns - schema.options.ttl.ns
            owner = f"{tenant}.{db}"
            expired = [b for b in self.buckets.get(owner, []) if b.end_time <= cutoff]
            if expired:
                self.buckets[owner] = [b for b in self.buckets[owner]
                                       if b.end_time > cutoff]
                self._persist()
                self._notify("expire_buckets", owner=owner,
                             bucket_ids=[b.id for b in expired])
            return expired
