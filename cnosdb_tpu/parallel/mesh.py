"""Device mesh management.

The rebuild's answer to the reference's scan fan-out + NCCL-style backend
(SURVEY §2.4): rows shard across a 1-D `jax.sharding.Mesh` axis ("shard"),
partial aggregates combine over ICI collectives. Multi-host extends the
same mesh across processes (jax distributed init), with DCN handled by XLA.
"""
from __future__ import annotations

import numpy as np

from .. import ops as _ops  # noqa: F401 - x64 config side effect
import jax
from jax.sharding import Mesh

SHARD_AXIS = "shard"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (SHARD_AXIS,))


def mesh_size(mesh: Mesh) -> int:
    return mesh.shape[SHARD_AXIS]
