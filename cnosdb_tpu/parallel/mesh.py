"""Device mesh management + the mesh-lane accounting plane.

The rebuild's answer to the reference's scan fan-out + NCCL-style backend
(SURVEY §2.4): rows shard across a 1-D `jax.sharding.Mesh` axis ("shard"),
partial aggregates combine over ICI collectives. Multi-host extends the
same mesh across processes (jax distributed init), with DCN handled by
XLA. A second ("replica") axis name is reserved for replicated operand
placement — P() over it pins small tables to every device.

The process-wide mesh is built once (`get_mesh`) from the placement
plane's device pool (ops/placement.py `mesh_platform`), so vnode→device
placement and the NamedSharding specs the exec lane emits agree by
construction. `CNOSDB_MESH=0` disables the lane entirely — every query
takes the byte-identical legacy merge path.

Accounting: every mesh-lane engage/decline books here via
`count_outcome(lane, reason)` (the mesh-accounting lint rule holds the
exec lane to it) and is exported as `cnosdb_mesh_total{lane,reason}`
by the HTTP /metrics scrape.
"""
from __future__ import annotations

import os
import threading

import numpy as np

from .. import ops as _ops  # noqa: F401 - x64 config side effect
import jax
from jax.sharding import Mesh

SHARD_AXIS = "shard"
# reserved second axis name: replicated operands (label LUTs, bucket
# tables) are placed with P() which spans every named axis, so a 1-D
# mesh today grows to ("shard", "replica") without spec rewrites
REPLICA_AXIS = "replica"

_lock = threading.Lock()
_counters: dict[tuple[str, str], int] = {}
_cached_mesh: Mesh | None = None
_cached_key: tuple | None = None


def enabled() -> bool:
    """Master switch: CNOSDB_MESH=0 keeps every query on the legacy
    (byte-identical) host merge path."""
    return os.environ.get("CNOSDB_MESH", "1") != "0"


def count_outcome(lane: str, reason: str, n: int = 1) -> None:
    """Book one mesh-lane outcome (engage or decline) — the counter
    behind `cnosdb_mesh_total{lane,reason}`."""
    with _lock:
        _counters[(lane, reason)] = _counters.get((lane, reason), 0) + n


def outcomes_snapshot() -> dict[tuple[str, str], int]:
    with _lock:
        return dict(sorted(_counters.items()))


def reset_counters() -> None:
    """Test isolation only."""
    with _lock:
        _counters.clear()


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None and n_devices > len(devs):
        # default backend short on devices (e.g. one real TPU): fall back to
        # the host platform, which xla_force_host_platform_device_count can
        # expand into a virtual mesh
        try:
            cpu = jax.devices("cpu")
        except Exception:
            cpu = []
        if len(cpu) >= n_devices:
            devs = cpu
        else:
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)} "
                f"(+{len(cpu)} cpu); set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={n_devices}")
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (SHARD_AXIS,))


def get_mesh() -> Mesh | None:
    """The process-wide execution mesh, built once from the placement
    plane's device pool. CNOSDB_MESH_DEVICES caps the width (the bench
    sweep uses it to scale 1→2→4→8 on a fixed virtual-device pool);
    None when the pool is empty."""
    global _cached_mesh, _cached_key
    want = os.environ.get("CNOSDB_MESH_DEVICES")
    with _lock:
        if _cached_mesh is not None and _cached_key == want:
            return _cached_mesh
    from ..ops.placement import mesh_devices

    devs = mesh_devices()
    if not devs:
        return None
    if want:
        devs = devs[:max(1, int(want))]
    mesh = Mesh(np.array(devs), (SHARD_AXIS,))
    with _lock:
        _cached_mesh = mesh
        _cached_key = want
    return mesh


def mesh_size(mesh: Mesh) -> int:
    return mesh.shape[SHARD_AXIS]
