"""Device mesh management.

The rebuild's answer to the reference's scan fan-out + NCCL-style backend
(SURVEY §2.4): rows shard across a 1-D `jax.sharding.Mesh` axis ("shard"),
partial aggregates combine over ICI collectives. Multi-host extends the
same mesh across processes (jax distributed init), with DCN handled by XLA.
"""
from __future__ import annotations

import numpy as np

from .. import ops as _ops  # noqa: F401 - x64 config side effect
import jax
from jax.sharding import Mesh

SHARD_AXIS = "shard"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None and n_devices > len(devs):
        # default backend short on devices (e.g. one real TPU): fall back to
        # the host platform, which xla_force_host_platform_device_count can
        # expand into a virtual mesh
        try:
            cpu = jax.devices("cpu")
        except Exception:
            cpu = []
        if len(cpu) >= n_devices:
            devs = cpu
        else:
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)} "
                f"(+{len(cpu)} cpu); set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={n_devices}")
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (SHARD_AXIS,))


def mesh_size(mesh: Mesh) -> int:
    return mesh.shape[SHARD_AXIS]
