"""Arrow IPC encoding of scan results for the cross-process data plane.

Counterpart of the reference's scan-stream wire format (tskv/src/reader/
serialize.rs:30 TonicRecordBatchEncoder → Arrow IPC bytes inside
kv_service.proto BatchBytesResponse, decoded in coordinator/src/reader/
deserialize.rs): a ScanBatch crosses processes as one Arrow IPC stream
whose schema metadata carries the non-columnar sidecar (table name, series
ids, encoded series keys, field value-types).

Columns: ts i64 | sid_ordinal i32 | one column per field with Arrow-native
nulls for the validity mask. The receiving coordinator rebuilds the exact
ScanBatch layout the device staging path (ops/tpu_exec) expects.
"""
from __future__ import annotations

import json

import numpy as np
import pyarrow as pa

from ..models.schema import ValueType
from ..models.series import SeriesKey
from ..models.strcol import DictArray
from ..storage.scan import ScanBatch

_ARROW_TYPES = {
    ValueType.FLOAT: pa.float64(),
    ValueType.INTEGER: pa.int64(),
    ValueType.UNSIGNED: pa.uint64(),
    ValueType.BOOLEAN: pa.bool_(),
    ValueType.STRING: pa.large_utf8(),
    ValueType.GEOMETRY: pa.large_utf8(),
}


def encode_scan_batch(b: ScanBatch) -> bytes:
    arrays = [pa.array(b.ts, type=pa.int64()),
              pa.array(b.sid_ordinal, type=pa.int32())]
    fields = [pa.field("time", pa.int64()), pa.field("__sid_ord", pa.int32())]
    vts = {}
    for name, (vt, vals, valid) in b.fields.items():
        vt = ValueType(vt)
        vts[name] = int(vt)
        mask = ~np.asarray(valid, dtype=bool)
        if vt in (ValueType.STRING, ValueType.GEOMETRY):
            # dictionary columns ride as Arrow DictionaryArray: codes move
            # as int32 buffers, the dictionary once — no per-row Python
            da = vals if isinstance(vals, DictArray) \
                else DictArray.from_objects(vals)
            idx = pa.array(da.codes, type=pa.int32(), mask=mask)
            arr = pa.DictionaryArray.from_arrays(
                idx, pa.array([str(v) for v in da.values],
                              type=pa.large_utf8()))
        else:
            arr = pa.array(np.asarray(vals), type=_ARROW_TYPES[vt], mask=mask)
        arrays.append(arr)
        fields.append(pa.field(name, arr.type))
    meta = {
        "table": b.table,
        "series_ids": [int(s) for s in b.series_ids],
        "series_keys": [k.encode().hex() if k is not None else ""
                        for k in b.series_keys],
        "value_types": vts,
    }
    schema = pa.schema(fields, metadata={b"cnos": json.dumps(meta).encode()})
    batch = pa.record_batch(arrays, schema=schema)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, schema) as w:
        w.write_batch(batch)
    return sink.getvalue().to_pybytes()


def decode_scan_batch(raw: bytes) -> ScanBatch:
    with pa.ipc.open_stream(pa.BufferReader(raw)) as r:
        table = r.read_all()
    meta = json.loads(table.schema.metadata[b"cnos"].decode())
    ts = table.column("time").to_numpy(zero_copy_only=False).astype(np.int64)
    sid_ord = (table.column("__sid_ord").to_numpy(zero_copy_only=False)
               .astype(np.int32))
    fields = {}
    for name, vt_i in meta["value_types"].items():
        vt = ValueType(vt_i)
        col = table.column(name)
        valid = ~np.asarray(col.is_null().to_numpy(zero_copy_only=False),
                            dtype=bool)
        if vt in (ValueType.STRING, ValueType.GEOMETRY):
            chunk = (col.combine_chunks() if isinstance(col, pa.ChunkedArray)
                     else col)
            if pa.types.is_dictionary(chunk.type):
                idx = chunk.indices
                if idx.null_count:
                    idx = idx.fill_null(0)
                codes = np.asarray(idx.to_numpy(zero_copy_only=False),
                                   dtype=np.int64)
                values = np.array(chunk.dictionary.to_pylist(), dtype=object)
                vals = DictArray._normalize(codes, values)
            else:  # older peers ship plain utf8
                vals = DictArray.from_objects(
                    np.array([v if v is not None else ""
                              for v in chunk.to_pylist()], dtype=object))
        else:
            np_dtype = {ValueType.FLOAT: np.float64,
                        ValueType.INTEGER: np.int64,
                        ValueType.UNSIGNED: np.uint64,
                        ValueType.BOOLEAN: np.bool_}[vt]
            filled = pa.compute.fill_null(col, pa.scalar(0, type=col.type)
                                          if vt != ValueType.BOOLEAN
                                          else pa.scalar(False))
            vals = (filled.to_numpy(zero_copy_only=False).astype(np_dtype))
        fields[name] = (vt, vals, valid)
    keys = [SeriesKey.decode(bytes.fromhex(h)) if h else None
            for h in meta["series_keys"]]
    return ScanBatch(
        table=meta["table"],
        series_ids=np.asarray(meta["series_ids"], dtype=np.uint64),
        series_keys=keys,
        ts=ts,
        sid_ordinal=sid_ord,
        fields=fields,
    )
