"""Replica groups: raft-replicated vnodes.

Role-parity with the reference's RaftNodesManager + TskvRaftWriter
(coordinator/src/raft/manager.rs:33-754, raft/writer.rs:19): every
ReplicationSet with more than one vnode runs a raft group whose state
machine is the VnodeStorage apply path and whose log store is that vnode's
WAL (one durable log per vnode, reference wal_store.rs). Writes go to the
group leader (retry-on-leader-change like tskv_executor.rs
TskvLeaderExecutor); single-vnode sets bypass consensus entirely.

Two deployments share this code:
- single-process (tests, singleton mode): every replica is local, messages
  ride the InProcessTransport;
- multi-node: each node builds ONLY the raft members whose vnodes are
  placed on it; peer messages ride HttpTransport to the owning node's RPC
  service (reference network_grpc.rs), resolved through meta placement.
"""
from __future__ import annotations

import os
import threading

import msgpack

from ..errors import ReplicationError
from ..models.meta_data import ReplicationSet
from ..storage.engine import TsKv
from ..storage.vnode import VnodeStorage
from .raft import (
    HttpTransport, InProcessTransport, LogEntry, MultiRaft, NotLeader,
    RaftNode, StateMachine, WalLogStore,
)
from ..utils import lockwatch


class VnodeStateMachine(StateMachine):
    """ApplyStorage over VnodeStorage (reference tskv TskvEngineStorage)."""

    def __init__(self, vnode: VnodeStorage):
        self.vnode = vnode

    def apply(self, entry: LogEntry):
        self.vnode.apply_entry(entry.entry_type, entry.data, entry.index)

    def snapshot(self) -> bytes:
        """FILE-level snapshot (reference vnode_store.rs VnodeSnapshot +
        DownloadFile shipping): flush, then capture the vnode's physical
        files — no per-row re-encoding, and install is byte-identical."""
        return msgpack.packb(self.vnode.file_snapshot(), use_bin_type=True)

    def install_snapshot(self, data: bytes, last_index: int, last_term: int):
        snap = msgpack.unpackb(data, raw=False, strict_map_key=False)
        self.vnode.install_file_snapshot(snap)


class ReplicaGroupManager:
    """Builds/holds the raft groups for this node's replica-set members.

    With `meta=None` (single-process), all members of every set are built
    locally over InProcessTransport — the round-1 behavior. With a meta
    view, only vnodes placed on `node_id` are built and remote peers are
    resolved to their owning node's RPC address."""

    def __init__(self, engine: TsKv, node_id: int | None = None,
                 meta=None,
                 election_timeout=(0.15, 0.3), heartbeat_interval=0.05):
        self.engine = engine
        self.node_id = node_id
        self.meta = meta
        if meta is None:
            self.transport = InProcessTransport()
        else:
            self.transport = HttpTransport(self._resolve_peer)
        self.multi = MultiRaft()
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        self.lock = lockwatch.Lock("replica.manager")
        # group_id → ReplicationSet placement (for peer resolution)
        self._placements: dict[str, ReplicationSet] = {}
        # leadership transitions wake blocked writers (event-driven, not
        # sleep-polling: pollers starve under load and hit deadlines)
        self._state_cv = threading.Condition(lockwatch.RLock("replica.state_cv"))

    def _on_member_state(self, _node) -> None:
        with self._state_cv:
            self._state_cv.notify_all()

    def group_id(self, owner: str, rs: ReplicationSet) -> str:
        return f"{owner}/{rs.id}"

    # ------------------------------------------------------------ placement
    def _resolve_peer(self, group_id: str, peer_vnode: int) -> str | None:
        rs = self._placements.get(group_id)
        if rs is None:
            rs = self._find_placement(group_id)
        if rs is None:
            return None
        v = rs.vnode(peer_vnode)
        if v is None or v.node_id == self.node_id:
            return None
        return self.meta.node_addr(v.node_id)

    def _find_placement(self, group_id: str) -> ReplicationSet | None:
        """owner/rs_id → ReplicationSet via the meta bucket map."""
        owner, _, rs_id_s = group_id.rpartition("/")
        tenant, _, db = owner.partition(".")
        try:
            rs_id = int(rs_id_s)
        except ValueError:
            return None
        for bucket in self.meta.buckets_for(tenant, db):
            for rs in bucket.shard_group:
                if rs.id == rs_id:
                    self._placements[group_id] = rs
                    return rs
        return None

    def _is_local(self, v) -> bool:
        return self.meta is None or v.node_id == self.node_id

    # ------------------------------------------------------------ groups
    def get_or_build(self, owner: str, rs: ReplicationSet) -> dict[int, RaftNode]:
        """→ vnode_id → RaftNode for this node's members of the set."""
        gid = self.group_id(owner, rs)
        with self.lock:
            self._placements[gid] = rs
            nodes = {}
            peers = [v.id for v in rs.vnodes]
            for v in rs.vnodes:
                if not self._is_local(v):
                    continue
                key = (gid, v.id)
                existing = self.transport.nodes.get(key)
                if existing is not None:
                    nodes[v.id] = existing
                    continue
                vnode = self.engine.open_vnode(owner, v.id)
                log = WalLogStore(vnode.wal,
                                  os.path.join(vnode.dir, "hardstate"))
                node = RaftNode(gid, v.id, peers, log,
                                VnodeStateMachine(vnode), self.transport,
                                election_timeout=self.election_timeout,
                                heartbeat_interval=self.heartbeat_interval,
                                on_state=self._on_member_state)
                self.multi.add(node)
                nodes[v.id] = node
            return nodes

    def ensure_group(self, group_id: str) -> bool:
        """Build this node's members for a group named by id (first contact
        from a remote raft peer, reference manager.rs open-on-demand)."""
        rs = self._placements.get(group_id) or self._find_placement(group_id)
        if rs is None:
            return False
        owner = group_id.rpartition("/")[0]
        self.get_or_build(owner, rs)
        return True

    def handle_raft_msg(self, group_id: str, to: int, msg: dict) -> dict | None:
        node = self.transport.nodes.get((group_id, to))
        if node is None:
            if not self.ensure_group(group_id):
                return None
            node = self.transport.nodes.get((group_id, to))
            if node is None:
                return None
        return node.handle_message(msg)

    def invalidate(self, owner: str, rs_id: int):
        """Placement changed: drop the cached ReplicationSet for peer
        resolution (single authority for the cache-key format)."""
        self._placements.pop(f"{owner}/{rs_id}", None)

    def stop_member(self, owner: str, rs_id: int, vnode_id: int):
        """Tear down this node's raft member for a removed replica — its
        WAL/dir is about to be dropped and a live ticker would recreate
        them (REPLICA REMOVE)."""
        gid = f"{owner}/{rs_id}"
        node = self.transport.nodes.pop((gid, vnode_id), None)
        if node is not None:
            node.stop()
            self.multi.remove(node)

    def current_leader_vnode(self, owner: str, rs: ReplicationSet) -> int | None:
        """The raft leader's vnode id (may differ from meta's static
        leader_vnode_id after elections) — readers follow it for
        read-your-writes."""
        gid = self.group_id(owner, rs)
        for v in rs.vnodes:
            node = self.transport.nodes.get((gid, v.id))
            if node is not None and node.is_leader():
                return v.id
        return None

    def leader_hint(self, owner: str, rs: ReplicationSet) -> int | None:
        """A local member's view of the current leader vnode id."""
        gid = self.group_id(owner, rs)
        for v in rs.vnodes:
            node = self.transport.nodes.get((gid, v.id))
            if node is not None and node.leader_id is not None:
                return node.leader_id
        return None

    # ------------------------------------------------------------ writes
    def write(self, owner: str, rs: ReplicationSet, entry_type: int,
              data: bytes, timeout: float = 10.0, sync: bool = False) -> int:
        """Propose on the current leader, retrying across leader changes
        (reference TskvLeaderExecutor). Deadline-based: a cold-start
        election on a loaded host can take seconds; giving up early turns
        a transient into a write failure."""
        import time

        nodes = self.get_or_build(owner, rs)
        last_err: Exception | None = None
        deadline = time.monotonic() + timeout

        def wait_state(span: float):
            # woken early by any leadership transition; the timeout is a
            # fallback for remote-leader groups whose local members see
            # no transition
            with self._state_cv:
                self._state_cv.wait(min(span, max(
                    0.0, deadline - time.monotonic())))

        while time.monotonic() < deadline:
            leader = next((n for n in nodes.values() if n.is_leader()), None)
            if leader is None:
                wait_state(0.25)
                continue
            try:
                idx = leader.propose(entry_type, data)
                if sync:
                    self.engine.open_vnode(owner, leader.node_id).wal.sync()
                return idx
            except NotLeader as e:
                last_err = e
                wait_state(0.1)
            except ReplicationError as e:
                last_err = e
                wait_state(0.1)
        raise ReplicationError(
            f"no leader for {self.group_id(owner, rs)}") from last_err

    def propose_local(self, owner: str, rs: ReplicationSet, entry_type: int,
                      data: bytes, sync: bool = False) -> int:
        """Propose iff a member on THIS node is the raft leader; raises
        NotLeader(hint) otherwise so the coordinator can forward."""
        nodes = self.get_or_build(owner, rs)
        leader = next((n for n in nodes.values() if n.is_leader()), None)
        if leader is None:
            raise NotLeader(self.leader_hint(owner, rs))
        idx = leader.propose(entry_type, data)
        if sync:
            self.engine.open_vnode(owner, leader.node_id).wal.sync()
        return idx

    # ------------------------------------------------------------ membership
    def change_membership_local(self, owner: str, rs: ReplicationSet,
                                member_ids: list[int],
                                timeout: float = 10.0) -> int:
        """Single-step config change via a LOCAL leader member; raises
        NotLeader(hint) when no member on this node leads the group (the
        coordinator then forwards to the leader's node). `rs` is the
        CURRENT placement (pre- or post-change both work: peer resolution
        uses meta placement, the raft config rides the log entry)."""
        nodes = self.get_or_build(owner, rs)
        leader = next((n for n in nodes.values() if n.is_leader()), None)
        if leader is None:
            raise NotLeader(self.leader_hint(owner, rs))
        return leader.change_membership(member_ids, timeout=timeout)

    def stepdown_local(self, owner: str, rs: ReplicationSet,
                       vnode_id: int) -> bool:
        """Ask a local member to yield leadership (pre-removal of the
        leader member). → True if it was leader and stepped down."""
        gid = self.group_id(owner, rs)
        node = self.transport.nodes.get((gid, vnode_id))
        if node is None or not node.is_leader():
            return False
        node.stepdown()
        return True

    def member_progress(self, owner: str, rs: ReplicationSet,
                        vnode_id: int) -> tuple[int, int] | None:
        """(match_index, commit_index) of `vnode_id` as seen by a LOCAL
        leader — the catch-up gauge for REPLICA ADD. None when this node
        does not lead the group."""
        nodes = self.get_or_build(owner, rs)
        leader = next((n for n in nodes.values() if n.is_leader()), None)
        if leader is None:
            return None
        if vnode_id == leader.node_id:
            return leader.log.last_index(), leader.commit_index
        return leader.match_index.get(vnode_id, 0), leader.commit_index

    def stop(self):
        self.multi.stop_all()
