"""Replica groups: raft-replicated vnodes.

Role-parity with the reference's RaftNodesManager + TskvRaftWriter
(coordinator/src/raft/manager.rs:33-754, raft/writer.rs:19): every
ReplicationSet with more than one vnode runs a raft group whose state
machine is the VnodeStorage apply path and whose log store is that vnode's
WAL (one durable log per vnode, reference wal_store.rs). Writes go to the
group leader (retry-on-leader-change like tskv_executor.rs
TskvLeaderExecutor); single-vnode sets bypass consensus entirely.
"""
from __future__ import annotations

import threading

import msgpack

from ..errors import ReplicationError
from ..models.meta_data import ReplicationSet
from ..storage.engine import TsKv
from ..storage.vnode import VnodeStorage
from .raft import (
    InProcessTransport, LogEntry, MultiRaft, NotLeader, RaftNode,
    StateMachine, WalLogStore,
)


class VnodeStateMachine(StateMachine):
    """ApplyStorage over VnodeStorage (reference tskv TskvEngineStorage)."""

    def __init__(self, vnode: VnodeStorage):
        self.vnode = vnode

    def apply(self, entry: LogEntry):
        self.vnode.apply_entry(entry.entry_type, entry.data, entry.index)

    def snapshot(self) -> bytes:
        """Ship the memcache + flushed state as a write-batch replay bundle
        (round-1 scope: logical snapshot; file-level snapshots later)."""
        from ..storage.scan import scan_vnode

        tables = {}
        for (table, _sid) in list(self.vnode.active.series.keys()) + \
                [(t, s) for c in self.vnode.immutables for (t, s) in c.series]:
            tables[table] = True
        for fm in self.vnode.summary.version.all_files():
            r = self.vnode.summary.version.reader(fm)
            for t in r.tables():
                tables[t] = True
        out = {}
        for table in tables:
            b = scan_vnode(self.vnode, table)
            rows = []
            for i in range(b.n_rows):
                sid = int(b.series_ids[b.sid_ordinal[i]])
                key = self.vnode.index.get_series_key(sid)
                fields = {}
                for name, (vt, vals, valid) in b.fields.items():
                    if valid[i]:
                        v = vals[i]
                        fields[name] = [int(vt), v.item() if hasattr(v, "item") else v]
                rows.append([key.encode() if key else b"", int(b.ts[i]), fields])
            out[table] = rows
        return msgpack.packb(out, use_bin_type=True)

    def install_snapshot(self, data: bytes, last_index: int, last_term: int):
        from ..models.points import SeriesRows, WriteBatch
        from ..models.series import SeriesKey

        obj = msgpack.unpackb(data, raw=False, strict_map_key=False)
        # replace local state: drop all tables, then re-apply rows
        wb = WriteBatch()
        for table, rows in obj.items():
            self.vnode._apply_drop_table(table)
            per_key: dict[bytes, list] = {}
            for key_b, ts, fields in rows:
                per_key.setdefault(key_b, []).append((ts, fields))
            for key_b, items in per_key.items():
                key = SeriesKey.decode(key_b)
                ts_list = [t for t, _ in items]
                fnames = {n for _, f in items for n in f}
                fs = {}
                for n in fnames:
                    vt = next(f[n][0] for _, f in items if n in f)
                    fs[n] = (vt, [f.get(n, [None, None])[1] if n in f else None
                                  for _, f in items])
                wb.add_series(table, SeriesRows(key, ts_list, fs))
        if wb.tables:
            self.vnode._apply_write(wb, last_index)


class ReplicaGroupManager:
    """Builds/holds raft groups for replica sets (all local this round)."""

    def __init__(self, engine: TsKv,
                 election_timeout=(0.15, 0.3), heartbeat_interval=0.05):
        self.engine = engine
        self.transport = InProcessTransport()
        self.multi = MultiRaft()
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        self.lock = threading.Lock()

    def group_id(self, owner: str, rs: ReplicationSet) -> str:
        return f"{owner}/{rs.id}"

    def get_or_build(self, owner: str, rs: ReplicationSet) -> dict[int, RaftNode]:
        """→ vnode_id → RaftNode for the set (builds all local members)."""
        gid = self.group_id(owner, rs)
        with self.lock:
            nodes = {}
            peers = [v.id for v in rs.vnodes]
            for v in rs.vnodes:
                key = (gid, v.id)
                existing = self.transport.nodes.get(key)
                if existing is not None:
                    nodes[v.id] = existing
                    continue
                vnode = self.engine.open_vnode(owner, v.id)
                import os

                log = WalLogStore(vnode.wal,
                                  os.path.join(vnode.dir, "hardstate"))
                node = RaftNode(gid, v.id, peers, log,
                                VnodeStateMachine(vnode), self.transport,
                                election_timeout=self.election_timeout,
                                heartbeat_interval=self.heartbeat_interval)
                self.multi.add(node)
                nodes[v.id] = node
            return nodes

    def current_leader_vnode(self, owner: str, rs: ReplicationSet) -> int | None:
        """The raft leader's vnode id (may differ from meta's static
        leader_vnode_id after elections) — readers follow it for
        read-your-writes."""
        gid = self.group_id(owner, rs)
        for v in rs.vnodes:
            node = self.transport.nodes.get((gid, v.id))
            if node is not None and node.is_leader():
                return v.id
        return None

    def write(self, owner: str, rs: ReplicationSet, entry_type: int,
              data: bytes, retries: int = 20, sync: bool = False) -> int:
        """Propose on the current leader, retrying across leader changes
        (reference TskvLeaderExecutor)."""
        import time

        nodes = self.get_or_build(owner, rs)
        last_err: Exception | None = None
        for _ in range(retries):
            leader = next((n for n in nodes.values() if n.is_leader()), None)
            if leader is None:
                time.sleep(0.05)
                continue
            try:
                idx = leader.propose(entry_type, data)
                if sync:
                    self.engine.open_vnode(owner, leader.node_id).wal.sync()
                return idx
            except NotLeader as e:
                last_err = e
                time.sleep(0.05)
            except ReplicationError as e:
                last_err = e
                time.sleep(0.05)
        raise ReplicationError(
            f"no leader for {self.group_id(owner, rs)}") from last_err

    def stop(self):
        self.multi.stop_all()
