"""Distributed scan aggregation: shard_map partials + ICI collectives.

The multi-chip form of ops.kernels.segment_aggregate (SURVEY §2.4
"Partial-agg distribution"): rows are sharded over the mesh axis, every
device reduces its shard into [num_segments] partials in one fused
program, then count/sum combine with `psum`, min/max with `pmin`/`pmax`,
and first/last resolve by all-gathering the per-device (rank, value)
candidates and selecting the global arg-min/max — all inside the same jit,
so XLA schedules compute and ICI traffic together. Output is replicated
(P()) on every device.
"""
from __future__ import annotations

import functools

import numpy as np

from .. import ops as _ops  # noqa: F401 - x64 config side effect
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..ops.kernels import local_segment_partials, pad_rows, pad_segments, _pad
from .mesh import SHARD_AXIS, mesh_size


@functools.partial(
    jax.jit, static_argnames=("mesh", "num_segments", "want_first", "want_last"))
def _dist_kernel(values, valid, seg_ids, rank, *, mesh: Mesh,
                 num_segments: int, want_first: bool, want_last: bool):
    def body(v, m, s, r):
        local = local_segment_partials(
            v, m, s, r, num_segments=num_segments,
            want_first=want_first, want_last=want_last)
        out = {
            "count": jax.lax.psum(local["count"], SHARD_AXIS),
            "sum": jax.lax.psum(local["sum"], SHARD_AXIS),
            "min": jax.lax.pmin(local["min"], SHARD_AXIS),
            "max": jax.lax.pmax(local["max"], SHARD_AXIS),
        }
        if want_first:
            ranks = jax.lax.all_gather(local["first_rank"], SHARD_AXIS)  # [D,S]
            vals = jax.lax.all_gather(local["first"], SHARD_AXIS)
            dev = jnp.argmin(ranks, axis=0)
            out["first"] = jnp.take_along_axis(vals, dev[None, :], axis=0)[0]
            out["first_rank"] = jnp.min(ranks, axis=0)
        if want_last:
            ranks = jax.lax.all_gather(local["last_rank"], SHARD_AXIS)
            vals = jax.lax.all_gather(local["last"], SHARD_AXIS)
            dev = jnp.argmax(ranks, axis=0)
            out["last"] = jnp.take_along_axis(vals, dev[None, :], axis=0)[0]
            out["last_rank"] = jnp.max(ranks, axis=0)
        return out

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(), check_vma=False)
    return fn(values, valid, seg_ids, rank)


def merge_distinct_pairs(chunks: list[np.ndarray], n_values: int,
                         num_segments: int) -> np.ndarray:
    """Combine per-chunk/per-shard DISTINCT partials (sorted (group·nv +
    value) pair-code arrays from ops.kernels.sorted_pair_codes) into
    per-group distinct counts. The wire format is the plain sorted i64
    pair array — the same shape single-chip partials use, so multi-chip
    merging needs no new collective."""
    if not chunks:
        return np.zeros(num_segments, dtype=np.int64)
    pairs = np.unique(np.concatenate(chunks))
    nv = max(int(n_values), 1)
    return np.bincount((pairs // nv).astype(np.int64),
                       minlength=num_segments).astype(np.int64)[:num_segments]


def distributed_aggregate_host(values: np.ndarray, valid: np.ndarray,
                               seg_ids: np.ndarray, rank: np.ndarray,
                               num_segments: int, mesh: Mesh,
                               want_first: bool = False,
                               want_last: bool = False) -> dict:
    """Host wrapper: pad rows to devices × size class, shard, run, fetch."""
    n = len(values)
    d = mesh_size(mesh)
    np_pad = pad_rows(max(n, 1))
    if np_pad % d:
        np_pad = ((np_pad + d - 1) // d) * d
    ns_pad = pad_segments(max(num_segments, 1))
    values = _pad(values, np_pad)
    valid = _pad(valid, np_pad, fill=False)
    seg_ids = _pad(seg_ids, np_pad, fill=0)
    rank = _pad(rank, np_pad, fill=0)
    sharding = NamedSharding(mesh, P(SHARD_AXIS))
    dv = jax.device_put(values, sharding)
    dm = jax.device_put(valid, sharding)
    ds = jax.device_put(seg_ids, sharding)
    dr = jax.device_put(rank, sharding)
    out = _dist_kernel(dv, dm, ds, dr, mesh=mesh, num_segments=ns_pad,
                       want_first=want_first, want_last=want_last)
    host = {k: np.asarray(v)[:num_segments] for k, v in out.items()}
    if "count" in host:
        host["count"] = host["count"].astype(np.int64)
    return host
