"""Distributed scan aggregation: shard_map partials + ICI collectives.

The multi-chip form of ops.kernels.segment_aggregate (SURVEY §2.4
"Partial-agg distribution"): rows are sharded over the mesh axis, every
device reduces its shard into [num_segments] partials in one fused
program, then count/sum combine with `psum`, min/max with `pmin`/`pmax`,
and first/last resolve by all-gathering the per-device (rank, value)
candidates and selecting the global arg-min/max — all inside the same jit,
so XLA schedules compute and ICI traffic together. Output is replicated
(P()) on every device.
"""
from __future__ import annotations

import functools

import numpy as np

from .. import ops as _ops  # noqa: F401 - x64 config side effect
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:              # jax >= 0.6 exports shard_map at top level (check_vma)
    from jax import shard_map
except ImportError:   # jax 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)

from ..ops.kernels import local_segment_partials, pad_rows, pad_segments, _pad
from .mesh import SHARD_AXIS, mesh_size


@functools.partial(
    jax.jit, static_argnames=("mesh", "num_segments", "want_first", "want_last"))
def _dist_kernel(values, valid, seg_ids, rank, *, mesh: Mesh,
                 num_segments: int, want_first: bool, want_last: bool):
    def body(v, m, s, r):
        local = local_segment_partials(
            v, m, s, r, num_segments=num_segments,
            want_first=want_first, want_last=want_last)
        out = {
            "count": jax.lax.psum(local["count"], SHARD_AXIS),
            "sum": jax.lax.psum(local["sum"], SHARD_AXIS),
            "min": jax.lax.pmin(local["min"], SHARD_AXIS),
            "max": jax.lax.pmax(local["max"], SHARD_AXIS),
        }
        if want_first:
            ranks = jax.lax.all_gather(local["first_rank"], SHARD_AXIS)  # [D,S]
            vals = jax.lax.all_gather(local["first"], SHARD_AXIS)
            dev = jnp.argmin(ranks, axis=0)
            out["first"] = jnp.take_along_axis(vals, dev[None, :], axis=0)[0]
            out["first_rank"] = jnp.min(ranks, axis=0)
        if want_last:
            ranks = jax.lax.all_gather(local["last_rank"], SHARD_AXIS)
            vals = jax.lax.all_gather(local["last"], SHARD_AXIS)
            dev = jnp.argmax(ranks, axis=0)
            out["last"] = jnp.take_along_axis(vals, dev[None, :], axis=0)[0]
            out["last_rank"] = jnp.max(ranks, axis=0)
        return out

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(), check_vma=False)
    return fn(values, valid, seg_ids, rank)


@functools.partial(
    jax.jit, static_argnames=("mesh", "slots", "num_segments", "wants",
                              "run_pad"))
def mesh_merge_kernel(values, valid, seg_ids, rank, run_sums, run_segs, *,
                      mesh: Mesh, slots: int, num_segments: int,
                      wants: tuple[str, ...], run_pad: int = 0):
    """Deterministic-order collective merge for the mesh exec lane
    (ops/mesh_exec.py): each shard holds up to `slots` whole scan
    batches, rows carry slot-local segment ids (slot · num_segments +
    seg), and per-(slot, segment) partials fold in GLOBAL BATCH ORDER —
    shard-major, slot-minor — after an `all_gather` over the shard axis.

    That fold order is the whole point: `sql/executor._merge_results_vec`
    adds per-batch partials in batch order with np.add.at, so a psum
    (whose reduction order XLA owns) could drift f64 sums by an ulp. The
    unrolled fold reproduces the legacy addition order bit-for-bit;
    min/max/first/last are order-insensitive and ride the same gather.
    Output is replicated (P()) — one host fetch serves the coordinator.

    Float sums carry one more ordering constraint: the legacy CPU host
    kernels are run-aware (ufunc.reduceat per contiguous equal-segment
    run, then run partials folded per segment in run order —
    ops.kernels.run_segment_partials), and reduceat's within-run f64
    association is numpy's PAIRWISE reduce — unreproducible by any
    row-order device scatter. So when `run_pad` > 0 the host has staged
    the per-run reduceat partials themselves (`run_sums`, computed with
    the same numpy call the legacy kernel makes) and `run_segs` maps
    runs to slot-local segments (unused run slots → the dead segment
    slots·num_segments, sliced off). The device then folds run partials
    per segment in run order — bincount-over-runs association,
    bit-for-bit — and the cross-shard merge below stays collective.
    run_pad == 0 keeps the flat row-order sum (the legacy flat-scatter
    branches and integer columns).
    """
    want_first = "first" in wants
    want_last = "last" in wants
    two_level = run_pad > 0 and "sum" in wants

    def body(v, m, s, r, rsum, rseg):
        local = local_segment_partials(
            v, m, s, r, num_segments=slots * num_segments,
            want_count=True, want_sum="sum" in wants and not two_level,
            want_min="min" in wants, want_max="max" in wants,
            want_first=want_first, want_last=want_last)
        if two_level:
            # run partials → per-(slot, segment) sums in run order (the
            # bincount-over-runs association); the dead segment absorbs
            # unused run slots and is sliced off
            local["sum"] = jax.ops.segment_sum(
                rsum, rseg,
                num_segments=slots * num_segments + 1)[:-1]
        d = mesh_size(mesh)

        def folded(name, op, cast=None):
            a = jax.lax.all_gather(local[name], SHARD_AXIS)   # [D, slots·S]
            a = a.reshape(d * slots, num_segments)            # batch order
            if cast is not None:
                a = a.astype(cast)
            acc = a[0]
            for k in range(1, d * slots):
                acc = op(acc, a[k])
            return acc

        out = {"count": folded("count", jnp.add, cast=jnp.int64)}
        if "sum" in wants:
            out["sum"] = folded("sum", jnp.add)
        if "min" in wants:
            out["min"] = folded("min", jnp.minimum)
        if "max" in wants:
            out["max"] = folded("max", jnp.maximum)
        for nm, pick in (("first", jnp.argmin), ("last", jnp.argmax)):
            if nm not in wants:
                continue
            ranks = jax.lax.all_gather(local[f"{nm}_rank"], SHARD_AXIS) \
                .reshape(d * slots, num_segments)
            vals = jax.lax.all_gather(local[nm], SHARD_AXIS) \
                .reshape(d * slots, num_segments)
            # ranks are globally unique per valid row (stable argsort of
            # the concatenated timestamps), so the arg pick is exact —
            # ties exist only between empty slots' fill keys
            win = pick(ranks, axis=0)
            out[nm] = jnp.take_along_axis(vals, win[None, :], axis=0)[0]
            out[f"{nm}_rank"] = jnp.take_along_axis(
                ranks, win[None, :], axis=0)[0]
        return out

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(SHARD_AXIS),) * 6,
        out_specs=P(), check_vma=False)
    return fn(values, valid, seg_ids, rank, run_sums, run_segs)


def merge_distinct_pairs(chunks: list[np.ndarray], n_values: int,
                         num_segments: int) -> np.ndarray:
    """Combine per-chunk/per-shard DISTINCT partials (sorted (group·nv +
    value) pair-code arrays from ops.kernels.sorted_pair_codes) into
    per-group distinct counts. The wire format is the plain sorted i64
    pair array — the same shape single-chip partials use, so multi-chip
    merging needs no new collective."""
    if not chunks:
        return np.zeros(num_segments, dtype=np.int64)
    pairs = np.unique(np.concatenate(chunks))
    nv = max(int(n_values), 1)
    return np.bincount((pairs // nv).astype(np.int64),
                       minlength=num_segments).astype(np.int64)[:num_segments]


def distributed_aggregate_host(values: np.ndarray, valid: np.ndarray,
                               seg_ids: np.ndarray, rank: np.ndarray,
                               num_segments: int, mesh: Mesh,
                               want_first: bool = False,
                               want_last: bool = False) -> dict:
    """Host wrapper: pad rows to devices × size class, shard, run, fetch."""
    n = len(values)
    d = mesh_size(mesh)
    np_pad = pad_rows(max(n, 1))
    if np_pad % d:
        np_pad = ((np_pad + d - 1) // d) * d
    ns_pad = pad_segments(max(num_segments, 1))
    values = _pad(values, np_pad)
    valid = _pad(valid, np_pad, fill=False)
    seg_ids = _pad(seg_ids, np_pad, fill=0)
    rank = _pad(rank, np_pad, fill=0)
    sharding = NamedSharding(mesh, P(SHARD_AXIS))
    dv = jax.device_put(values, sharding)
    dm = jax.device_put(valid, sharding)
    ds = jax.device_put(seg_ids, sharding)
    dr = jax.device_put(rank, sharding)
    out = _dist_kernel(dv, dm, ds, dr, mesh=mesh, num_segments=ns_pad,
                       want_first=want_first, want_last=want_last)
    host = {k: np.asarray(v)[:num_segments] for k, v in out.items()}
    if "count" in host:
        host["count"] = host["count"].astype(np.int64)
    return host
